"""SLO burn-rate feedback control: the budget controller that closes
the observability loop (docs/observability.md "Budget feedback
control").

The :class:`~platform_aware_scheduling_tpu.utils.slo.SLOEngine` judges
— burn rates, error budgets, pages — and until this module nothing
*acted* on the judgment.  :class:`BudgetController` subscribes to the
engine's post-tick hook (same injectable clock, one evaluation per
engine tick) and drives four feedback paths through explicit bounded
actuators:

  * **admission shedding** (``verb_availability``): the serving layer's
    admission queue depth steps down a declared ladder as the
    availability budget burns — cheap early 503s before expensive queue
    collapse — and steps back up hysteretically on recovery.
  * **rebalancer aggressiveness** (``eviction_safety``): ``max_moves``
    steps down and the drift hysteresis ``K`` steps up while eviction
    attempts are failing (PDB denials, flaky eviction API), so the
    actuator backs off a misbehaving dependency instead of burning the
    safety budget slamming into it.
  * **degraded extrapolation bounds** (``telemetry_freshness``): the
    forecaster's uncertainty-band bound, its extrapolation-horizon cap,
    and the degraded controller's last-known-good age multiple all
    tighten once the freshness budget is gone — stale data gets trusted
    *less*, not longer, when staleness is already over budget.
  * **trend pre-arming**: a predicted storm (the forecaster's trend
    signal) tightens the shed knob ONE step before any budget burns,
    so the first surge tick meets a queue that is already defensive.

Every actuation is itself observed: a ``pas_control_*`` gauge per knob,
an actuation counter labeled ``knob``/``direction``/``slo``, a
decision-provenance record, and a bounded recent-actuation ring served
by ``GET /debug/control`` on both front-ends.  The controller is
strictly one-step-per-knob-per-engine-tick (rate limit), every knob
clamps to its declared ladder ends, and with ``--sloControl=off``
nothing is constructed — the request path never sees the controller
either way (it only ever mutates knobs other components already read
live).
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from platform_aware_scheduling_tpu.utils import decisions, events, klog
from platform_aware_scheduling_tpu.utils.tracing import CounterSet

#: tighten while the trigger SLO's remaining error budget sits below
#: this fraction (or while it pages) …
DEFAULT_TIGHTEN_BUDGET = 0.25
#: … loosen one step only after LOOSEN_HOLD_TICKS consecutive ticks
#: with the budget back above this fraction and no alert — the
#: hysteresis gap (loosen > tighten) is what prevents flapping at the
#: threshold
DEFAULT_LOOSEN_BUDGET = 0.50
DEFAULT_LOOSEN_HOLD_TICKS = 3

#: recent-actuation ring served by /debug/control
_RECENT = 64

DIRECTION_TIGHTEN = "tighten"
DIRECTION_LOOSEN = "loosen"

#: trigger label for trend pre-arming (not an SLO name: the whole point
#: is that it fires BEFORE any SLO burns)
TRIGGER_TREND = "trend"


def _ladder(values: Sequence) -> Tuple:
    """Validate a knob ladder: at least two distinct settings, loosest
    (baseline) first, strictly monotonic toward the tight end."""
    vals = tuple(values)
    if len(vals) < 2:
        raise ValueError("a knob ladder needs >= 2 settings")
    deltas = [b - a for a, b in zip(vals, vals[1:])]
    if not (all(d > 0 for d in deltas) or all(d < 0 for d in deltas)):
        raise ValueError(f"knob ladder must be strictly monotonic: {vals}")
    return vals


class Knob:
    """One bounded actuation point: a ladder of allowed settings from
    the baseline (index 0, the operator-configured value) to the
    tightest defensive posture (the last index).  ``write`` applies a
    setting to the live component; ``read`` is only used for the
    snapshot.  The ladder IS the clamp: the controller can only ever
    select an index in ``[0, len(ladder) - 1]``."""

    def __init__(
        self,
        name: str,
        slo: str,
        ladder: Sequence,
        write: Callable[[object], None],
        read: Optional[Callable[[], object]] = None,
        labels: Optional[Dict[str, str]] = None,
    ):
        self.name = name
        self.slo = slo
        self.ladder = _ladder(ladder)
        self.write = write
        self.read = read
        # extra gauge labels beyond {"knob": name} — the per-partition
        # shed knobs surface as pas_control_knob_setting{knob=...,
        # partition=...} (docs/sharding.md)
        self.labels = dict(labels) if labels else {}
        self.level = 0  # index into the ladder; 0 == baseline
        self.last_step_tick = -1  # rate limit: one step per engine tick
        self.steps = 0  # lifetime actuation count

    @property
    def setting(self):
        return self.ladder[self.level]

    @property
    def baseline(self):
        return self.ladder[0]

    @property
    def bounds(self) -> Tuple:
        lo, hi = self.ladder[0], self.ladder[-1]
        return (lo, hi) if lo <= hi else (hi, lo)

    def step(self, direction: str, tick: int) -> bool:
        """Move one ladder index (tighten -> higher index); clamps at
        the ends and refuses a second step within the same engine tick.
        Returns whether the setting actually moved."""
        if self.last_step_tick == tick:
            return False
        delta = 1 if direction == DIRECTION_TIGHTEN else -1
        level = min(len(self.ladder) - 1, max(0, self.level + delta))
        if level == self.level:
            return False
        self.level = level
        self.last_step_tick = tick
        self.steps += 1
        self.write(self.ladder[level])
        return True


class BudgetController:
    """Reads the SLO engine's per-tick evaluations and steps the
    attached knobs.  Construct with the engine, attach actuators, and
    either let the engine drive it (``engine.subscribe`` happens here)
    or call :meth:`on_tick` directly with an evaluation dict."""

    def __init__(
        self,
        engine,
        *,
        trend_source: Optional[Callable[[], Tuple[bool, str]]] = None,
        tighten_budget: float = DEFAULT_TIGHTEN_BUDGET,
        loosen_budget: float = DEFAULT_LOOSEN_BUDGET,
        loosen_hold_ticks: int = DEFAULT_LOOSEN_HOLD_TICKS,
        decision_log=None,
    ):
        if loosen_budget < tighten_budget:
            raise ValueError(
                "loosen_budget must sit at or above tighten_budget "
                "(the hysteresis gap prevents flapping)"
            )
        self.engine = engine
        self.trend_source = trend_source
        self.tighten_budget = float(tighten_budget)
        self.loosen_budget = float(loosen_budget)
        self.loosen_hold_ticks = max(1, int(loosen_hold_ticks))
        self.decision_log = (
            decision_log if decision_log is not None else decisions.DECISIONS
        )
        self.enabled = True
        # controller-local metrics, merged into /metrics only while the
        # controller is wired — the same off-path convention the SLO
        # engine set (utils/slo.py): --sloControl=off emits nothing
        self.counters = CounterSet()
        self.knobs: Dict[str, Knob] = {}
        self._hold: Dict[str, int] = {}  # slo -> consecutive healthy ticks
        self._recent: deque = deque(maxlen=_RECENT)
        self._ticks = 0
        self._prearmed = False
        self._lock = threading.Lock()
        if engine is not None:
            engine.subscribe(self.on_tick)

    # -- actuator attachment ---------------------------------------------------

    def add_knob(self, knob: Knob) -> Knob:
        with self._lock:
            if knob.name in self.knobs:
                raise ValueError(f"duplicate knob {knob.name!r}")
            self.knobs[knob.name] = knob
        self.counters.set_gauge(
            "pas_control_knob_setting",
            float(knob.setting),
            labels={"knob": knob.name, **knob.labels},
        )
        return knob

    def attach_admission(self, target, floor: int = 4) -> Knob:
        """The shed knob: any object exposing a live-read
        ``max_queue_depth`` (serving.MicroBatchDispatcher, the twin's
        admission model).  Tighten halves the depth toward ``floor``."""
        baseline = int(target.max_queue_depth)
        ladder: List[int] = [baseline]
        while ladder[-1] // 2 >= max(1, int(floor)):
            ladder.append(ladder[-1] // 2)
        if len(ladder) < 2:
            ladder = [baseline, max(1, int(floor))]

        def write(value, target=target):
            target.max_queue_depth = int(value)

        return self.add_knob(
            Knob(
                "admission_queue_depth",
                "verb_availability",
                ladder,
                write,
                read=lambda: target.max_queue_depth,
            )
        )

    def attach_rebalancer(self, rebalancer) -> List[Knob]:
        """The aggressiveness knobs: churn budget down, drift
        hysteresis up, through Rebalancer.set_aggressiveness (which
        validates and clamps on its side too)."""
        moves = int(rebalancer.replanner.max_moves)
        k = int(rebalancer.drift.k)
        move_ladder = sorted(
            {max(1, moves), max(1, moves // 2), max(1, moves // 4), 1},
            reverse=True,
        )
        k_ladder = sorted({k, k + 1, k + 2, k * 2 + 2})
        knobs = [
            Knob(
                "rebalance_max_moves",
                "eviction_safety",
                move_ladder,
                lambda v: rebalancer.set_aggressiveness(max_moves=int(v)),
                read=lambda: rebalancer.replanner.max_moves,
            ),
            Knob(
                "drift_hysteresis_k",
                "eviction_safety",
                k_ladder,
                lambda v: rebalancer.set_aggressiveness(hysteresis_k=int(v)),
                read=lambda: rebalancer.drift.k,
            ),
        ]
        return [self.add_knob(knob) for knob in knobs]

    def attach_forecaster(self, forecaster) -> List[Knob]:
        """The extrapolation-bound knobs: band bound and horizon cap
        tighten through Forecaster.set_extrapolation_bounds, which
        clears the per-fit memoized verdict so the new bound applies to
        the CURRENT fit."""
        band = float(forecaster.band_bound)
        band_ladder = [band, band * 0.5, band * 0.25]
        window = max(2, int(forecaster.window))
        horizon_ladder = sorted(
            {window, max(1, window // 2), max(1, window // 4)},
            reverse=True,
        )
        knobs = [
            Knob(
                "forecast_band_bound",
                "telemetry_freshness",
                band_ladder,
                lambda v: forecaster.set_extrapolation_bounds(
                    band_bound=float(v)
                ),
                read=lambda: forecaster.band_bound,
            ),
            Knob(
                "forecast_horizon_cap",
                "telemetry_freshness",
                horizon_ladder,
                lambda v: forecaster.set_extrapolation_bounds(
                    horizon_cap=int(v)
                ),
                read=lambda: forecaster.horizon_cap or forecaster.window,
            ),
        ]
        return [self.add_knob(knob) for knob in knobs]

    def attach_shard(self, plane, floor: int = 2) -> List[Knob]:
        """The per-partition shed knobs: each partition's digest top-k
        width halves toward ``floor`` under telemetry-freshness pressure
        — a smaller summary is cheaper to build and gossip, at the cost
        of remote ranking resolution (the classic shed: degrade answer
        quality before availability).  One knob per partition, surfaced
        as ``pas_control_knob_setting{knob=shard_topk_p<N>,
        partition=<N>}`` so operators see which partitions are running
        thin (docs/sharding.md)."""
        baseline = int(plane.default_topk())
        ladder: List[int] = [baseline]
        while ladder[-1] // 2 >= max(1, int(floor)):
            ladder.append(ladder[-1] // 2)
        if len(ladder) < 2:
            ladder = [baseline, max(1, int(floor))]
        knobs = []
        for partition in range(plane.pmap.partitions):
            knobs.append(
                Knob(
                    f"shard_topk_p{partition}",
                    "telemetry_freshness",
                    ladder,
                    lambda v, p=partition: plane.set_topk(p, int(v)),
                    read=lambda p=partition: plane.topk_for(p),
                    labels={"partition": str(partition)},
                )
            )
        return [self.add_knob(knob) for knob in knobs]

    def attach_degraded(self, degraded) -> Knob:
        """The last-known-good trust knob: how many freshness bounds of
        staleness degraded mode keeps serving from — tightens toward
        1.0 once staleness is already over budget."""
        multiple = float(degraded.lkg_bound_multiple)
        ladder = [multiple]
        for candidate in (multiple * 2 / 3, multiple / 2, 1.0):
            if candidate < ladder[-1] - 1e-9 and candidate >= 1.0:
                ladder.append(round(candidate, 3))
        if len(ladder) < 2:
            ladder = [multiple, max(1.0, multiple / 2)]

        def write(value, degraded=degraded):
            degraded.lkg_bound_multiple = float(value)

        return self.add_knob(
            Knob(
                "lkg_bound_multiple",
                "telemetry_freshness",
                ladder,
                write,
                read=lambda: degraded.lkg_bound_multiple,
            )
        )

    def attach_preemption(
        self, planner, slo: str = "verb_availability"
    ) -> Optional[Knob]:
        """The preemption-aggressiveness knob: sustained availability
        burn steps the per-plan victim budget (admission/preempt.py
        reads ``max_victims`` live each plan) down by halving toward 1
        — a cluster already burning availability budget must not ALSO
        amplify churn with bigger victim sets.  ``slo`` defaults to the
        shared verb-availability objective; the twin attaches it to the
        per-class availability SLOs instead.  None when the configured
        budget is already 1 (nothing to tighten)."""
        baseline = max(1, int(planner.max_victims))
        ladder: List[int] = [baseline]
        while ladder[-1] > 1:
            ladder.append(ladder[-1] // 2)
        if len(ladder) < 2:
            return None

        def write(value, planner=planner):
            planner.max_victims = max(1, int(value))

        return self.add_knob(
            Knob(
                "preemption_max_victims",
                slo,
                ladder,
                write,
                read=lambda: planner.max_victims,
            )
        )

    # -- the control loop ------------------------------------------------------

    def on_tick(self, evaluations: Dict[str, Dict]) -> None:
        """One control pass per engine tick (the engine invokes this
        OUTSIDE its lock).  Never raises: a controller crash must not
        take the judge down with it."""
        try:
            self._control_pass(evaluations)
        except Exception as exc:
            klog.error("budget controller pass failed: %r", exc)

    def _control_pass(self, evaluations: Dict[str, Dict]) -> None:
        with self._lock:
            self._ticks += 1
            tick = self._ticks
            self.counters.inc("pas_control_ticks_total")
            by_slo: Dict[str, List[Knob]] = {}
            for knob in self.knobs.values():
                by_slo.setdefault(knob.slo, []).append(knob)
            for slo_name, knobs in by_slo.items():
                evaluation = evaluations.get(slo_name)
                if evaluation is None:
                    continue
                budget = float(
                    evaluation.get("error_budget_remaining", 1.0)
                )
                alert = evaluation.get("alert", "ok")
                if alert == "page" or budget < self.tighten_budget:
                    self._hold[slo_name] = 0
                    for knob in knobs:
                        self._actuate(
                            knob,
                            DIRECTION_TIGHTEN,
                            slo_name,
                            tick,
                            f"budget {budget:.3f} below "
                            f"{self.tighten_budget} (alert {alert})",
                        )
                elif alert == "ok" and budget >= self.loosen_budget:
                    held = self._hold.get(slo_name, 0) + 1
                    if held >= self.loosen_hold_ticks and any(
                        knob.level > 0 for knob in knobs
                    ):
                        self._hold[slo_name] = 0
                        for knob in knobs:
                            self._actuate(
                                knob,
                                DIRECTION_LOOSEN,
                                slo_name,
                                tick,
                                f"budget {budget:.3f} healthy for "
                                f"{held} ticks",
                            )
                    else:
                        self._hold[slo_name] = held
                else:
                    # the hysteresis band between the thresholds: hold
                    # position, reset the recovery streak
                    self._hold[slo_name] = 0
            self._prearm_pass(evaluations, tick)

    def _prearm_pass(self, evaluations: Dict[str, Dict], tick: int) -> None:
        """Trend pre-arming: a predicted storm tightens the shed knob
        one step BEFORE the availability budget burns (PR 8 meets
        PR 10).  Only from baseline — once armed (or once real burn has
        taken over), the ordinary hysteresis owns the knob."""
        knob = self.knobs.get("admission_queue_depth")
        if knob is None or self.trend_source is None:
            self.counters.set_gauge("pas_control_prearmed", 0.0)
            return
        try:
            storm, why = self.trend_source()
        except Exception:
            storm, why = False, "trend source failed"
        if storm and knob.level == 0:
            if self._actuate(
                knob, DIRECTION_TIGHTEN, TRIGGER_TREND, tick,
                f"predicted storm: {why}",
            ):
                self._prearmed = True
        elif not storm and knob.level == 0:
            self._prearmed = False
        self.counters.set_gauge(
            "pas_control_prearmed", 1.0 if self._prearmed else 0.0
        )

    def _actuate(
        self, knob: Knob, direction: str, trigger: str, tick: int,
        reason: str,
    ) -> bool:
        before = knob.setting
        if not knob.step(direction, tick):
            return False
        after = knob.setting
        self.counters.inc(
            "pas_control_actuations_total",
            labels={"knob": knob.name, "direction": direction,
                    "slo": trigger},
        )
        self.counters.set_gauge(
            "pas_control_knob_setting",
            float(after),
            labels={"knob": knob.name, **knob.labels},
        )
        record = {
            "tick": tick,
            "knob": knob.name,
            "direction": direction,
            "trigger": trigger,
            "from": before,
            "to": after,
            "level": knob.level,
            "reason": reason,
        }
        self._recent.append(record)
        try:
            self.decision_log.record_control(dict(record))
        except Exception as exc:
            klog.error("control decision record failed: %r", exc)
        events.JOURNAL.publish(
            "control",
            f"knob {direction}",
            data={
                "knob": knob.name,
                "trigger": trigger,
                "from": before,
                "to": after,
            },
        )
        return True

    # -- introspection ---------------------------------------------------------

    def actuation_count(self) -> int:
        with self._lock:
            return sum(knob.steps for knob in self.knobs.values())

    def snapshot(self) -> Dict:
        """The GET /debug/control payload: every knob's live setting,
        baseline, ladder bounds and level, plus the recent-actuation
        provenance ring."""
        with self._lock:
            knobs = []
            for knob in self.knobs.values():
                lo, hi = knob.bounds
                live = knob.setting
                if knob.read is not None:
                    try:
                        live = knob.read()
                    except Exception:
                        pass
                knobs.append({
                    "name": knob.name,
                    "slo": knob.slo,
                    "setting": live,
                    "baseline": knob.baseline,
                    "min": lo,
                    "max": hi,
                    "level": knob.level,
                    "levels": len(knob.ladder),
                    "steps": knob.steps,
                })
            return {
                "enabled": True,
                "ticks": self._ticks,
                "prearmed": self._prearmed,
                "thresholds": {
                    "tighten_budget": self.tighten_budget,
                    "loosen_budget": self.loosen_budget,
                    "loosen_hold_ticks": self.loosen_hold_ticks,
                },
                "knobs": knobs,
                "recent": list(self._recent),
            }

    def to_json(self) -> bytes:
        return (json.dumps(self.snapshot(), indent=1) + "\n").encode()

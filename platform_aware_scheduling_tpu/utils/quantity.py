"""Exact Kubernetes ``resource.Quantity`` arithmetic.

The reference's entire rule engine compares telemetry values as k8s
quantities: ``EvaluateRule`` dispatches on ``Quantity.CmpInt64`` and
``OrderedList`` sorts by ``Quantity.Cmp`` (reference
telemetry-aware-scheduling/pkg/strategies/core/operator.go:13-42), and GAS
reads capacities with ``Quantity.AsInt64`` (reference
gpu-aware-scheduling/pkg/gpuscheduler/scheduler.go:150-162).  This module
implements the same semantics exactly, backed by ``fractions.Fraction`` so
that comparisons are arbitrary precision, plus the scaled-integer accessors
the tensorized device path needs (``milli_value_exact``).

Grammar (k8s apimachinery/pkg/api/resource):
    <quantity>  ::= <signedNumber><suffix>
    <suffix>    ::= <binarySI> | <decimalExponent> | <decimalSI>
    <binarySI>  ::= Ki | Mi | Gi | Ti | Pi | Ei
    <decimalSI> ::= n | u | m | "" | k | M | G | T | P | E
    <decimalExponent> ::= "e"<signedNumber> | "E"<signedNumber>
"""

from __future__ import annotations

import re
from fractions import Fraction
from typing import Tuple, Union

_INT64_MAX = (1 << 63) - 1
_INT64_MIN = -(1 << 63)

_BINARY_SUFFIXES = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}

_DECIMAL_SUFFIXES = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<int>[0-9]*)(?:\.(?P<frac>[0-9]*))?"
    r"(?P<suffix>Ki|Mi|Gi|Ti|Pi|Ei|[numkMGTPE]|[eE][+-]?[0-9]+)?$"
)


class QuantityParseError(ValueError):
    """Raised when a string is not a valid k8s quantity."""


class Quantity:
    """An exact, immutable k8s resource quantity."""

    __slots__ = ("_value", "_text", "_milli")

    def __init__(self, value: Union[str, int, float, Fraction, "Quantity"]):
        # lazily-computed milli_value_exact cache: Quantity is immutable,
        # so the Fraction scaling can run once per object instead of once
        # per telemetry pass per node (the mirror reads every value in
        # fixed-point form each refresh)
        self._milli: Union[Tuple[int, bool], None] = None
        if isinstance(value, Quantity):
            self._value = value._value
            self._text = value._text
            self._milli = value._milli
            return
        if isinstance(value, str):
            self._value = _parse(value)
            self._text = value
            return
        if isinstance(value, bool):
            raise QuantityParseError(f"not a quantity: {value!r}")
        if isinstance(value, int):
            self._value = Fraction(value)
            self._text = None
            return
        if isinstance(value, float):
            self._value = Fraction(value).limit_denominator(10**9)
            self._text = None
            return
        if isinstance(value, Fraction):
            self._value = value
            self._text = None
            return
        raise QuantityParseError(f"not a quantity: {value!r}")

    # -- comparisons (reference semantics: Cmp / CmpInt64) -------------------

    def cmp(self, other: Union["Quantity", int, Fraction]) -> int:
        """Three-way compare, matching Go ``Quantity.Cmp``: -1, 0, or 1."""
        ov = other._value if isinstance(other, Quantity) else Fraction(other)
        if self._value < ov:
            return -1
        if self._value > ov:
            return 1
        return 0

    def cmp_int64(self, target: int) -> int:
        """Three-way compare against an int64, matching ``Quantity.CmpInt64``."""
        return self.cmp(Fraction(target))

    # -- accessors -----------------------------------------------------------

    @property
    def value(self) -> Fraction:
        return self._value

    def as_int64(self) -> Tuple[int, bool]:
        """(value, ok) like Go ``Quantity.AsInt64``: ok only when the value is
        an integer representable in int64; otherwise ``(0, False)``.  GAS uses
        the value and ignores ok (reference gpuscheduler/utils.go:25), so a
        fractional capacity reads as 0 there, exactly as in the reference."""
        if self._value.denominator != 1:
            return 0, False
        v = self._value.numerator
        if v < _INT64_MIN or v > _INT64_MAX:
            return 0, False
        return v, True

    def as_approximate_float(self) -> float:
        return float(self._value)

    def milli_value_exact(self) -> Tuple[int, bool]:
        """(milli_value, exact): the value scaled by 1000 as an int64 plus a
        flag saying whether the scaling was lossless AND in int64 range.  The
        device-tensor mirror stores metric values in this fixed-point form;
        when ``exact`` is false for any node the host fallback path is used so
        rule evaluation stays bit-identical to the reference."""
        cached = self._milli
        if cached is not None:
            return cached
        scaled = self._value * 1000
        exact = scaled.denominator == 1
        if exact:
            v = scaled.numerator
        else:
            # round toward zero for the approximate device value
            v = int(scaled)
        if v > _INT64_MAX:
            result = (_INT64_MAX, False)
        elif v < _INT64_MIN:
            result = (_INT64_MIN, False)
        else:
            result = (v, exact)
        self._milli = result
        return result

    def as_dec(self) -> str:
        """Decimal string (used in log lines, like Go ``AsDec``)."""
        v = self._value
        if v.denominator == 1:
            return str(v.numerator)
        f = float(v)
        return repr(f)

    # -- dunder plumbing -----------------------------------------------------

    def __eq__(self, other) -> bool:
        if isinstance(other, Quantity):
            return self._value == other._value
        if isinstance(other, (int, Fraction)):
            return self._value == other
        return NotImplemented

    def __lt__(self, other) -> bool:
        ov = other._value if isinstance(other, Quantity) else Fraction(other)
        return self._value < ov

    def __hash__(self) -> int:
        return hash(self._value)

    def __repr__(self) -> str:
        return f"Quantity({str(self)!r})"

    def __str__(self) -> str:
        if self._text is not None:
            return self._text
        return self.as_dec()


def _parse(text: str) -> Fraction:
    s = text.strip()
    if not s:
        raise QuantityParseError("empty quantity")
    m = _QUANTITY_RE.match(s)
    if m is None:
        raise QuantityParseError(f"invalid quantity: {text!r}")
    int_part = m.group("int") or ""
    frac_part = m.group("frac")
    if not int_part and not frac_part:
        raise QuantityParseError(f"invalid quantity: {text!r}")
    digits = int_part or "0"
    number = Fraction(int(digits))
    if frac_part:
        number += Fraction(int(frac_part or "0"), 10 ** len(frac_part))
    if m.group("sign") == "-":
        number = -number
    suffix = m.group("suffix") or ""
    if suffix in _BINARY_SUFFIXES:
        number *= _BINARY_SUFFIXES[suffix]
    elif suffix in _DECIMAL_SUFFIXES:
        number *= _DECIMAL_SUFFIXES[suffix]
    elif suffix and suffix[0] in "eE":
        exp = int(suffix[1:])
        number *= Fraction(10) ** exp
    elif suffix:
        raise QuantityParseError(f"invalid suffix in quantity: {text!r}")
    return number


def parse_quantity(text: Union[str, int, float]) -> Quantity:
    return Quantity(text)

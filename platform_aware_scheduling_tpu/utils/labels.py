"""Shared cluster-object label vocabulary.

One home for every ``pas-*`` label the subsystems read off pods and
nodes, so ``gang/``, ``rebalance/``, and the decision records all import
one definition (hoisted out of ``rebalance/actuator.py``, which keeps a
back-compat alias).  This module must stay importable without jax.

  * ``GROUP_LABEL`` — the workload-group key: the rebalance actuator's
    min-available accounting unit AND (together with ``GANG_SIZE_LABEL``)
    the gang identity for all-or-nothing co-scheduling (docs/gang.md);
  * ``GANG_SIZE_LABEL`` — the gang's total member count ``k``; a pod
    carrying both group and size labels is a gang member;
  * ``GANG_TOPOLOGY_LABEL`` — the required ICI sub-mesh shape, e.g.
    ``4x4`` (a contiguous 4-row by 4-column slice); absent means any
    ``k`` mesh nodes (no adjacency constraint);
  * ``TPU_COORD_LABEL`` — a node's mesh coordinate ``"row,col"``
    (synthesized by testing/fake_kube for hermetic meshes);
  * ``PRIORITY_LABEL`` — the pod's admission priority class name
    (admission/plane.py; unlabeled or unknown-class pods take the
    plane's default class).
"""

from __future__ import annotations

from typing import Dict, Optional

GROUP_LABEL = "pas-workload-group"
GANG_SIZE_LABEL = "pas-gang-size"
GANG_TOPOLOGY_LABEL = "pas-gang-topology"
TPU_COORD_LABEL = "pas-tpu-coord"
PRIORITY_LABEL = "pas-priority"


def gang_reserved_reason(gang_id: str) -> str:
    """The Filter FailedNodes reason for a node held by another gang's
    reservation.  ONE format shared by the tracker's overlay
    (gang/group.py) and the Filter response cache's merged verdict
    (tas/fastpath.gang_merged) — the cached and exact paths must stay
    byte-identical, so the string may only ever change here."""
    return f"gang: node reserved by gang {gang_id}"


def gang_id_for(namespace: str, pod_labels: Dict[str, str]) -> Optional[str]:
    """The gang identity of a pod, or None when the pod is not a gang
    member.  A gang needs BOTH the group label (identity) and a
    WELL-FORMED size label (+ consistent topology when given) — a bare
    ``pas-workload-group`` stays what it always was: the rebalance
    min-available unit.  The validation here is the single classifier
    (GangSpec.from_pod gates on it), so a pod with a malformed gang
    label is non-gang EVERYWHERE — scheduler and rebalance actuator can
    never disagree about membership."""
    group = pod_labels.get(GROUP_LABEL)
    if not group:
        return None
    raw_size = pod_labels.get(GANG_SIZE_LABEL)
    if raw_size is None:
        return None
    try:
        size = int(raw_size)
    except ValueError:
        return None
    if size < 1:
        return None
    raw_topo = pod_labels.get(GANG_TOPOLOGY_LABEL)
    if raw_topo:
        topo = parse_topology(raw_topo)
        if topo is None or topo[0] * topo[1] != size:
            return None
    return f"{namespace}/{group}"


def priority_class_for(pod_labels: Dict[str, str], classes) -> Optional[str]:
    """The pod's declared admission priority class, or None when the pod
    is unlabeled or names a class outside ``classes`` (the configured
    ladder).  This is the single classifier — the admission plane, the
    preemption planner's victim census, and the decision records all go
    through it, so a mislabeled pod degrades to the default class
    EVERYWHERE instead of crashing Filter or forking semantics."""
    raw = pod_labels.get(PRIORITY_LABEL)
    if not raw:
        return None
    if raw not in classes:
        return None
    return raw


#: sanity ceiling per mesh dimension: the dense [rows, cols] grids the
#: topology kernel allocates are sized by the LARGEST labeled
#: coordinate, so one mislabeled node (``"1000000,1000000"``) must not
#: turn every gang Filter into a terabyte allocation.  1024x1024 = 1M
#: cells comfortably covers real TPU pod meshes.
MAX_MESH_DIM = 1024


def format_coord(row: int, col: int) -> str:
    """The ``pas-tpu-coord`` label value for one mesh cell — the single
    writer-side formatter (parse_coord is the reader); every mesh
    synthesizer goes through it so the wire format cannot fork."""
    return f"{row},{col}"


def parse_coord(node_labels: Dict[str, str]) -> Optional[tuple]:
    """``pas-tpu-coord: "2,3"`` -> (2, 3); None when absent/malformed or
    outside the ``MAX_MESH_DIM`` sanity bound (a coordinate-less node
    simply sits outside the mesh)."""
    raw = node_labels.get(TPU_COORD_LABEL)
    if not raw:
        return None
    row, sep, col = raw.partition(",")
    if not sep:
        return None
    try:
        i, j = int(row), int(col)
    except ValueError:
        return None
    if i < 0 or j < 0 or i >= MAX_MESH_DIM or j >= MAX_MESH_DIM:
        return None
    return i, j


def parse_topology(raw: str) -> Optional[tuple]:
    """``"4x4"`` -> (4, 4); None when malformed."""
    a, sep, b = raw.partition("x")
    if not sep:
        return None
    try:
        rows, cols = int(a), int(b)
    except ValueError:
        return None
    if rows <= 0 or cols <= 0:
        return None
    return rows, cols

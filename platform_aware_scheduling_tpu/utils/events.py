"""Causal event spine: one bounded journal every subsystem publishes into.

The observability stack grew piecewise — spans (``utils/trace.py``),
decision provenance (``utils/decisions.py``), admission/preemption
records (``admission/``), controller actuations (``utils/control.py``),
SLO verdicts (``utils/slo.py``) — each in its own bounded ring with its
own keys.  Answering "why did pod X wait 40 s and land on node Y?"
meant joining five ``/debug/*`` endpoints by eyeball.

This module is the join.  ``JOURNAL`` is a process-wide, bounded,
lock-light ring of typed events, each carrying the correlation keys
(``request_id``, ``pod``, ``gang``, ``node``, ``tick``) that let
``explain()`` walk from a wire response back through admission,
preemption, rebalancing, control, and SLO state without any subsystem
knowing about any other.  ``GET /debug/explain`` (both front-ends)
serves ``explain()`` over HTTP.

Publication is off-path cheap: one short lock, one deque append, one
counter bump — the budget is <=5 us added per warm verb, measured the
same way as the flight recorder's +4.0/+7.8 us (benchmarks/obs_smoke).
Overflow drops oldest and counts ``pas_events_dropped_total``; a
publish NEVER raises into, or blocks, a verb.

Wire events need no per-handler calls: a ``trace.SPAN_OBSERVERS`` hook
registered at import turns every completed span that carries a ``verb``
attribute into a ``kind="wire"`` event, on both front-ends, for free.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from . import trace

#: event kinds the spine understands (the ``kind`` label on
#: ``pas_events_published_total``); publishers outside this list still
#: work — the list documents the contract, it does not gate.
KINDS = (
    "wire",        # span completion: verb handled on the wire
    "verdict",     # Filter/Prioritize/bind verdicts (tas/, gas/)
    "admission",   # enqueue/hold/backfill/shed/starve/admit (admission/plane.py)
    "preemption",  # plan/victim/reservation (admission/preempt.py)
    "rebalance",   # executed rebalancer moves (rebalance/loop.py)
    "control",     # budget-controller actuations (utils/control.py)
    "slo",         # SLO state flips (utils/slo.py)
    "serving",     # dispatcher-level sheds (serving/dispatcher.py)
    "churn",       # refresh-pass churn: rows changed / world (ops/solveobs.py)
    "solve",       # fastpath warm passes: the solve cadence (tas/)
    "shard",       # partition ownership + digest lifecycle (shard/)
)

#: kinds that describe the WORLD rather than any one entity: explain()
#: joins them into a chain by tick, not by correlation key, so a pod's
#: narrative can say "the state changed under you between these events"
#: — partition assignment/handoff is world state too: "who owned this
#: node when the verdict fired" reads off the shard events whose ticks
#: bracket the verdict
CONTEXT_KINDS = ("churn", "solve", "shard")


def _anon_corr(request_id: str, pod: str, gang: str, node: str) -> str:
    """A process-local correlation hash for flight-recorder export.

    Captures must NEVER contain node/pod/namespace names (the
    anonymization sweep in tests/test_record.py); the spine exports
    only this hash, stable within a process so chains stay joinable
    inside one capture but meaningless outside it."""
    h = hash((request_id, pod, gang, node))
    return format(h & 0xFFFFFFFFFFFFFFFF, "016x")


class EventJournal:
    """Bounded, lock-light, process-wide causal event ring.

    One short lock per publish (deque append + overflow check); the
    ring is hard-bounded so ``/debug/explain`` can never grow without
    limit.  ``tick_source`` is an optional zero-arg callable (the twin
    wires its engine tick) so events carry scheduler time, not just
    wall time; ``flight`` is an optional FlightRecorder the journal
    forwards anonymized spine events into."""

    def __init__(
        self,
        capacity: int = 4096,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.capacity = max(1, capacity)
        self.clock = clock
        self.enabled = True
        #: zero-arg callable returning the current scheduler tick, or None
        self.tick_source: Optional[Callable[[], int]] = None
        #: FlightRecorder to forward anonymized spine events into, or None
        self.flight = None
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self._dropped = 0

    # -- write path ---------------------------------------------------

    def publish(
        self,
        kind: str,
        event: str,
        request_id: str = "",
        pod: str = "",
        gang: str = "",
        node: str = "",
        data: Optional[Dict] = None,
    ) -> None:
        """Append one typed event; never raises, never blocks a verb."""
        if not self.enabled:
            return
        tick = -1
        source = self.tick_source
        if source is not None:
            try:
                tick = int(source())
            except Exception:
                tick = -1
        record = {
            "seq": 0,  # assigned under the lock
            "t": self.clock(),
            "tick": tick,
            "kind": kind,
            "event": event,
            "request_id": request_id,
            "pod": pod,
            "gang": gang,
            "node": node,
            "data": data if data is not None else {},
        }
        with self._lock:
            self._seq += 1
            record["seq"] = self._seq
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
                trace.COUNTERS.inc("pas_events_dropped_total")
            self._ring.append(record)
        trace.COUNTERS.inc(
            "pas_events_published_total", labels={"kind": kind}
        )
        flight = self.flight
        if flight is not None:
            try:
                flight.record_spine(
                    kind, event, tick, _anon_corr(request_id, pod, gang, node)
                )
            except Exception:
                pass

    # -- lifecycle ----------------------------------------------------

    def configure(
        self,
        enabled: Optional[bool] = None,
        capacity: Optional[int] = None,
    ) -> None:
        with self._lock:
            if capacity is not None and capacity != self.capacity:
                self.capacity = max(1, capacity)
                self._ring = deque(self._ring, maxlen=self.capacity)
        if enabled is not None:
            self.enabled = enabled

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._dropped = 0

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- read path ----------------------------------------------------

    def snapshot(self) -> List[Dict]:
        with self._lock:
            return [dict(r) for r in self._ring]

    def explain(
        self,
        request_id: str = "",
        pod: str = "",
        gang: str = "",
        node: str = "",
    ) -> Dict:
        """Walk the correlation graph from any one key.

        Pass 1 gathers events directly matching the query key(s); the
        correlation keys found on those events (request_ids, pods,
        gangs) seed pass 2, which gathers everything sharing them —
        one-hop expansion is enough to join a pod's wire span to the
        preemption that seated it, because every event carries the keys
        of the entities it acted on.  The chain comes back seq-ordered
        with a per-event human narrative."""
        events = self.snapshot()

        def direct(r: Dict) -> bool:
            if request_id and r["request_id"] == request_id:
                return True
            if pod and r["pod"] == pod:
                return True
            if gang and r["gang"] == gang:
                return True
            if node and r["node"] == node:
                return True
            return False

        seeds = [r for r in events if direct(r)]
        request_ids = {r["request_id"] for r in seeds if r["request_id"]}
        pods = {r["pod"] for r in seeds if r["pod"]}
        gangs = {r["gang"] for r in seeds if r["gang"]}

        def correlated(r: Dict) -> bool:
            return (
                (r["request_id"] and r["request_id"] in request_ids)
                or (r["pod"] and r["pod"] in pods)
                or (r["gang"] and r["gang"] in gangs)
                or direct(r)
            )

        chain = [r for r in events if correlated(r)]
        chain.sort(key=lambda r: r["seq"])
        # "the world changed under you": churn/solve events carry no
        # entity keys, so they join by TICK — any context event sharing
        # a tick with the chain rides along (the refresh that moved the
        # state between a pod's enqueue and its verdict is causal
        # context even though it names no pod)
        ticks = {r["tick"] for r in chain if r["tick"] >= 0}
        in_chain = {r["seq"] for r in chain}
        context = [
            r
            for r in events
            if r["kind"] in CONTEXT_KINDS
            and r["tick"] >= 0
            and r["tick"] in ticks
            and r["seq"] not in in_chain
        ]
        context.sort(key=lambda r: r["seq"])
        trace.COUNTERS.inc("pas_explain_requests_total")
        trace.COUNTERS.set_gauge("pas_explain_chain_events", len(chain))
        return {
            "query": {
                "request_id": request_id,
                "pod": pod,
                "gang": gang,
                "node": node,
            },
            "correlated": {
                "request_ids": sorted(request_ids),
                "pods": sorted(pods),
                "gangs": sorted(gangs),
            },
            "events": chain,
            "narrative": [_narrate(r) for r in chain],
            "context": context,
            "context_narrative": [_narrate(r) for r in context],
            "dropped": self.dropped,
        }

    def to_json(self, **query) -> bytes:
        return json.dumps(self.explain(**query)).encode() + b"\n"


def _narrate(r: Dict) -> str:
    """One human sentence per event — the causal-narrative renderer."""
    head = f"[{r['kind']}] {r['event']}"
    subject = r["pod"] or r["gang"] or r["node"] or r["request_id"]
    if subject:
        head += f" {subject}"
    data = r.get("data") or {}
    detail = ", ".join(
        f"{k}={v}" for k, v in sorted(data.items()) if v not in ("", None)
    )
    if detail:
        head += f" ({detail})"
    if r["tick"] >= 0:
        return f"tick {r['tick']}: {head}"
    return head


#: the process-wide journal every subsystem publishes into
JOURNAL = EventJournal()


def _on_span(span) -> None:
    """trace.SPAN_OBSERVERS hook: completed verb spans become wire events.

    Only spans carrying a ``verb`` attribute publish (health checks and
    debug endpoints stay out of the spine); runs on the request thread,
    so it must stay as cheap as publish() itself."""
    verb = span.attrs.get("verb")
    if not verb:
        return
    duration_us = round((span.duration_s or 0.0) * 1e6, 1)
    JOURNAL.publish(
        "wire",
        f"{verb} responded",
        request_id=span.trace_id,
        pod=str(span.attrs.get("pod", "")),
        gang=str(span.attrs.get("gang", "")),
        node=str(span.attrs.get("node", "")),
        data={"status": span.status, "duration_us": duration_us},
    )


trace.SPAN_OBSERVERS.append(_on_span)

"""Leveled, structured logging in the style of k8s klog.

The reference logs through klog with verbosity levels 1-5 and a
``"component"`` key on most lines (e.g. reference
telemetry-aware-scheduling/pkg/telemetryscheduler/telemetryscheduler.go:40).
This module provides the same surface — ``v(level).info_s(msg, component=..)``
— on top of the stdlib ``logging`` module, with the verbosity controlled by
``set_verbosity`` (the ``--v`` flag) or the ``PAS_TPU_LOG_LEVEL`` env var.
"""

from __future__ import annotations

import contextvars
import logging
import os
import sys
import threading
from contextlib import contextmanager

_logger = logging.getLogger("pas_tpu")
_lock = threading.Lock()
_verbosity = int(os.environ.get("PAS_TPU_LOG_LEVEL", "0") or 0)
_configured = False

# the active request's X-Request-ID (utils/trace.py span id), stamped
# onto every structured line emitted while serving that request so a
# trace in /debug/traces can be joined against the logs.  A ContextVar
# follows both the threaded handler (one thread per request) and the
# async dispatcher's worker (route runs synchronously per request).
_request_id: contextvars.ContextVar = contextvars.ContextVar(
    "pas_request_id", default=""
)


@contextmanager
def request_context(request_id: str):
    """Scope the current request id: structured lines (``info_s``) inside
    the scope carry ``request_id="..."`` automatically."""
    token = _request_id.set(request_id or "")
    try:
        yield
    finally:
        _request_id.reset(token)


def current_request_id() -> str:
    return _request_id.get()


def _ensure_configured() -> None:
    global _configured
    with _lock:
        if _configured:
            return
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname).1s %(message)s")
        )
        _logger.addHandler(handler)
        _logger.setLevel(logging.INFO)
        _logger.propagate = False
        _configured = True


def set_verbosity(level: int) -> None:
    """Set the global verbosity (the ``--v`` flag of the reference binaries)."""
    global _verbosity
    _verbosity = int(level)


def verbosity() -> int:
    return _verbosity


def _escape_value(value) -> str:
    # structured values render inside double quotes on one line; a
    # client-controlled value (X-Request-ID rides in here) must not be
    # able to forge fields or break the line
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
        .replace("\r", "\\r")
    )


def _fmt(msg: str, kv: dict) -> str:
    rid = _request_id.get()
    if rid and "request_id" not in kv:
        kv = {**kv, "request_id": rid}
    if not kv:
        return msg
    pairs = " ".join(f'{k}="{_escape_value(v)}"' for k, v in kv.items())
    return f"{msg} {pairs}"


class _Verbose:
    __slots__ = ("_enabled",)

    def __init__(self, enabled: bool):
        self._enabled = enabled

    def enabled(self) -> bool:
        return self._enabled

    def info_s(self, msg: str, **kv) -> None:
        if self._enabled:
            _ensure_configured()
            _logger.info(_fmt(msg, kv))

    # klog.V(n).Infof-style formatting
    def infof(self, fmt: str, *args) -> None:
        if self._enabled:
            _ensure_configured()
            _logger.info(fmt % args if args else fmt)

    info = infof


def v(level: int) -> _Verbose:
    return _Verbose(level <= _verbosity)


def info_s(msg: str, **kv) -> None:
    _ensure_configured()
    _logger.info(_fmt(msg, kv))


def warning(msg: str, *args) -> None:
    _ensure_configured()
    _logger.warning(msg % args if args else msg)


warningf = warning


def error(msg: str, *args) -> None:
    _ensure_configured()
    _logger.error(msg % args if args else msg)


errorf = error


def fatal(msg: str, *args) -> None:
    _ensure_configured()
    _logger.critical(msg % args if args else msg)
    raise SystemExit(255)

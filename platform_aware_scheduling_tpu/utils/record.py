"""Flight recorder: a bounded ring of anonymized control-plane events
(docs/observability.md "Flight recorder & what-if").

Production traffic becomes twin scenarios: both front-ends can record
what they actually see — verb arrivals, telemetry movement, eviction and
leadership flips — into a fixed-size in-memory ring, exportable as
versioned JSONL via ``GET /debug/record`` and replayable through the
digital twin (testing/replay.py) to answer "what if yesterday's traffic
arrived at 2x load?" with projected SLO verdicts.

The anonymization contract (gated by tests/test_record.py, not merely
promised here): a capture NEVER contains node, pod, or namespace names.

  * verb events carry the PR-11 interned-universe digest (a 64-bit span
    hash over the candidate-name bytes — irreversible) plus the
    candidate COUNT and the pod's gang size label, nothing more; when no
    universe is interned (cold span, host path) the key is simply null —
    the recorder must stay O(1) on the hot path, so it never hashes a
    10k-name list itself;
  * telemetry events summarize each refresh pass as a per-metric DECILE
    curve (11 quantiles + node count) — the load SHAPE replays, the
    node->value map never leaves the process;
  * eviction and leadership events are bare counts/flips.

Off by default (``--flightRecorder=off``): while no recorder is wired
the verbs skip a single attribute check and the wire stays
byte-identical (pinned by tests/test_record.py).  The ring is bounded
(``--recordSize``); overflow drops the OLDEST event and counts it in
``pas_record_dropped_total`` — a flight recorder keeps the latest
window, like its aviation namesake.

All stamps come from the injectable clock, so a twin-hosted recorder
produces replayable fake-clock timelines and a production recorder
produces wall-clock ones, through the same code.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional

from platform_aware_scheduling_tpu.utils import klog, trace
from platform_aware_scheduling_tpu.utils.tracing import CounterSet

#: capture format version: bumped on any event-schema change so a
#: replay loader can refuse captures it would misread.  /2 added the
#: causal-spine passthrough events (kind "spine": utils/events.py
#: forwards journal events with an irreversible process-local
#: correlation hash); /3 added refresh-churn summaries (kind "churn":
#: counts + fraction-of-world per pass, ops/solveobs.py — replayed
#: captures carry production churn shape for ROADMAP item 4's
#: delta-aware staging).  /4 added partition-plane events (kind
#: "shard": ownership assigns/handoffs as partition id + fencing epoch,
#: utils/record.record_shard — ids and epochs only, no member names).
#: Loaders that fold a capture into a twin scenario ignore kinds they
#: don't infer from, so all stay replayable.
FORMAT = "pas-flight-record/4"

DEFAULT_CAPACITY = 4096

#: decile grid for telemetry summaries (0%, 10%, ..., 100%)
QUANTILES = tuple(i / 10.0 for i in range(11))


def decile_summary(values: Iterable[float]) -> Optional[List[float]]:
    """The 11-point decile curve of ``values`` (linear interpolation
    between order statistics), or None for an empty pass.  This is the
    WHOLE anonymized representation of a telemetry refresh: enough to
    replay the load distribution at recorded scale, nothing to join back
    to a node name."""
    data = sorted(float(v) for v in values)
    if not data:
        return None
    last = len(data) - 1
    curve: List[float] = []
    for q in QUANTILES:
        pos = q * last
        lo = int(pos)
        hi = min(lo + 1, last)
        frac = pos - lo
        curve.append(round(data[lo] * (1.0 - frac) + data[hi] * frac, 3))
    return curve


class FlightRecorder:
    """Bounded, clock-injectable ring of anonymized control-plane events.

    Hot-path cost budget: :meth:`record_verb` is one lock, one deque
    append, one counter increment — measured <=5% p99 against the
    recorder-off path by benchmarks/http_load.record_overhead.  The
    heavier summarizers (:meth:`record_telemetry`, :meth:`poll_control`)
    run on the telemetry refresh thread, never on a request."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        clock=time.monotonic,
    ):
        self.capacity = max(1, int(capacity))
        self.clock = clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._dropped = 0
        # recorder-local CounterSet, merged into /metrics only while a
        # recorder is wired — the SLO engine's off-path convention:
        # --flightRecorder=off emits no pas_record_* families at all
        self.counters = CounterSet()
        # control-event baselines for poll_control(): the recorder
        # watches fleet counters it does not own and emits events on
        # movement (one subscription point instead of N call sites)
        self._seen_evictions: Optional[float] = None
        self._seen_leader: Optional[bool] = None

    # -- event intake ----------------------------------------------------------

    def _append(self, event: Dict) -> None:
        with self._lock:
            if len(self._ring) == self.capacity:
                self._dropped += 1
                self.counters.inc("pas_record_dropped_total")
            self._ring.append(event)
            self.counters.inc("pas_record_events_total")

    def record_verb(
        self,
        verb: str,
        universe_uid: Optional[int] = None,
        candidates: int = 0,
        gang_size: int = 0,
    ) -> None:
        """One verb arrival.  ``universe_uid`` is the interned-universe
        digest when the wire path interned this candidate span, else
        None — the recorder never derives a key itself (O(1) rule)."""
        event = {
            "t": round(self.clock(), 6),
            "kind": "verb",
            "verb": verb,
            "universe": (
                format(universe_uid & 0xFFFFFFFFFFFFFFFF, "016x")
                if universe_uid is not None
                else None
            ),
            "candidates": int(candidates),
        }
        if gang_size:
            event["gang_size"] = int(gang_size)
        self._append(event)

    def record_telemetry(
        self, metric: str, values: Iterable[float]
    ) -> None:
        """One refresh pass's movement for ``metric``, anonymized to a
        decile curve + node count.  Metric NAMES are operator-chosen
        policy vocabulary (``node_load``), not cluster topology, so they
        stay."""
        data = list(values)
        curve = decile_summary(data)
        if curve is None:
            return
        self._append(
            {
                "t": round(self.clock(), 6),
                "kind": "telemetry",
                "metric": str(metric),
                "nodes": len(data),
                "deciles": curve,
            }
        )

    def record_eviction(self, count: int = 1) -> None:
        if count <= 0:
            return
        self._append(
            {
                "t": round(self.clock(), 6),
                "kind": "eviction",
                "count": int(count),
            }
        )

    def record_leader(self, is_leader: bool) -> None:
        self._append(
            {
                "t": round(self.clock(), 6),
                "kind": "leader",
                "leader": bool(is_leader),
            }
        )

    def record_spine(
        self, kind: str, event: str, tick: int, corr: str
    ) -> None:
        """One causal-spine event (utils/events.py forwards every
        journal publish here while wired).  Anonymization holds: the
        correlation keys (pod/gang/node/request id) are collapsed into
        ``corr``, an irreversible process-local hash — chains stay
        joinable within one capture, nothing joins back to a name."""
        self._append(
            {
                "t": round(self.clock(), 6),
                "kind": "spine",
                "spine_kind": str(kind),
                "event": str(event),
                "tick": int(tick),
                "corr": str(corr),
            }
        )

    def record_churn(
        self, metrics: int, rows: int, world: int, fraction: float
    ) -> None:
        """One refresh pass's churn shape (ops/solveobs.py flushes this
        while an observatory is wired next to the recorder).
        Anonymization holds by construction: counts and a fraction, no
        metric or node names — the pass SHAPE replays, nothing joins
        back to a cluster."""
        self._append(
            {
                "t": round(self.clock(), 6),
                "kind": "churn",
                "metrics": int(metrics),
                "rows": int(rows),
                "world": int(world),
                "fraction": round(float(fraction), 4),
            }
        )

    def record_shard(self, event: str, partition: int, epoch: int) -> None:
        """One partition-ownership event (shard/partition.py publishes
        assigns/handoffs here while wired).  Anonymization holds by
        construction: a partition id and a fencing epoch — replica
        identities and node names never enter the capture."""
        self._append(
            {
                "t": round(self.clock(), 6),
                "kind": "shard",
                "event": str(event),
                "partition": int(partition),
                "epoch": int(epoch),
            }
        )

    # -- control-event polling -------------------------------------------------

    def poll_control(self) -> None:
        """Diff the fleet's eviction/leadership families since the last
        pass and emit events on movement.  Runs on the telemetry refresh
        thread (subscribed via ``cache.on_refresh_pass``), so one
        subscription covers every actuator instead of hooking each."""
        try:
            executed = trace.COUNTERS.get(
                "pas_rebalance_moves_executed_total", kind="counter"
            )
            if self._seen_evictions is None:
                self._seen_evictions = executed
            elif executed > self._seen_evictions:
                self.record_eviction(int(executed - self._seen_evictions))
                self._seen_evictions = executed
            leader_val = trace.COUNTERS.get("pas_leader", kind="gauge")
            is_leader = bool(leader_val and leader_val > 0)
            if self._seen_leader is None or is_leader != self._seen_leader:
                # the FIRST observation is itself an event: a capture
                # should say which role the window started in
                self.record_leader(is_leader)
                self._seen_leader = is_leader
        except Exception as exc:  # never break the refresh thread
            klog.error("flight recorder control poll failed: %r", exc)

    def observe_cache(self, cache) -> None:
        """One telemetry refresh pass: summarize every registered
        metric's current values (milli-exact, scaled back to metric
        units) and poll the control families.  This is the single
        ``cache.on_refresh_pass`` subscription assembly wires."""
        try:
            for name in cache.registered_metric_names():
                try:
                    info = cache.read_metric(name)
                except Exception:
                    continue
                if not isinstance(info, dict) or not info:
                    continue
                values = []
                for metric in info.values():
                    try:
                        milli, _exact = metric.value.milli_value_exact()
                        values.append(milli / 1000.0)
                    except Exception:
                        continue
                self.record_telemetry(name, values)
        except Exception as exc:  # never break the refresh thread
            klog.error("flight recorder telemetry pass failed: %r", exc)
        self.poll_control()

    # -- export ----------------------------------------------------------------

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "format": FORMAT,
                "capacity": self.capacity,
                "events": len(self._ring),
                "dropped": self._dropped,
            }

    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def to_jsonl(self) -> bytes:
        """Versioned JSONL: a header object line, then one event per
        line — streamable, greppable, and the exact payload
        testing/replay.parse_capture consumes."""
        # snapshot under the lock, serialize after release: dumping the
        # whole ring is O(capacity) and this lock sits on the record path
        with self._lock:
            header = {
                "format": FORMAT,
                "capacity": self.capacity,
                "events": len(self._ring),
                "dropped": self._dropped,
            }
            events = list(self._ring)
        lines = [json.dumps(header, separators=(",", ":"))]
        lines.extend(
            json.dumps(event, separators=(",", ":")) for event in events
        )
        return ("\n".join(lines) + "\n").encode()

"""Scheduling decision provenance: per-decision explain records, the
bounded DecisionLog ring behind ``GET /debug/decisions``, and the
integer reason-code taxonomy shared by the host and device paths.

The reference PAS answers Filter/Prioritize with opaque verdicts — the
wire's per-node ``FailedNodes`` map carries the literal "Node violates"
(telemetryscheduler.go:206) — so an operator can never answer "why
didn't pod X land on node Y?" or "are our placements actually good?".
This module closes that gap without touching the hot path's cost
profile:

  * **Reason codes are small integers.**  The device kernels return a
    per-node *first-matching-rule index* vector alongside the violation
    verdict (ops/scoring.filter_explain_kernel); the host strategies
    produce the identical indexes (tas/strategies/dontschedule.py
    ``violated_details``), so native↔host provenance is byte-comparable.
    Rule indexes decode host-side — once per state change, never per
    request — into reason strings via :func:`rule_reason`.

  * **A DecisionRecord is O(1) to create.**  Per-node detail is held by
    REFERENCE to structures shared across requests (the per-state
    violation-reason map, the per-ranking score head), so recording a
    decision on the native fastpath costs an object allocation, a deque
    append, and a few counter bumps — the ≤5 % serving-p99 budget the
    http_load decision A/B pins.

  * **Outcome feedback closes the loop.**  Pod-bind observations (TAS
    Bind parses the body before its reference-parity 404; GAS Bind on
    success) flow back into the pod's open records: the chosen node's
    score rank and whether it was violating at decision time become the
    ``pas_decision_*`` placement-quality metric families.  The
    rebalancer's evict/skip causes land as events on the evicted pod's
    open records.

Everything is served on ``GET /debug/decisions`` (both front-ends,
admission-queue bypass like /debug/traces) with ``?pod=``, ``?verb=``
and ``?limit=`` filters; 404 while the log is disabled
(``--decisionLog=off``).  See docs/observability.md "Decision
provenance".
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from platform_aware_scheduling_tpu.utils import trace

# ---------------------------------------------------------------------------
# reason-code taxonomy
# ---------------------------------------------------------------------------

#: integer decision codes — the compact form the device fastpaths carry.
#: TAS rule violations additionally carry the violated RULE INDEX (the
#: first matching rule, by policy rule-list position) as their detail.
CODE_ELIGIBLE = 0
CODE_RULE_VIOLATION = 1
CODE_FAIL_CLOSED = 2
CODE_GAS_UNKNOWN_NODE = 3
CODE_GAS_NO_GPUS = 4
CODE_GAS_CAPACITY = 5
CODE_GAS_ERROR = 6  # host-loop unexpected failure; no device analog
CODE_GANG_RESERVED = 7  # node held by another gang's reservation
CODE_GANG_INFEASIBLE = 8  # no feasible slice / node outside the gang's slice
CODE_ADMISSION_BLOCKED = 9  # admission queue holding the pod back

#: code -> bounded Prometheus ``reason`` label (never per-rule/per-node:
#: label cardinality stays fixed; per-rule detail lives in the records
#: and the wire reason strings)
CODE_LABELS: Dict[int, str] = {
    CODE_RULE_VIOLATION: "rule_violation",
    CODE_FAIL_CLOSED: "fail_closed",
    CODE_GAS_UNKNOWN_NODE: "gas_unknown_node",
    CODE_GAS_NO_GPUS: "gas_no_gpus",
    CODE_GAS_CAPACITY: "gas_capacity",
    CODE_GAS_ERROR: "gas_error",
    CODE_GANG_RESERVED: "gang_reserved",
    CODE_GANG_INFEASIBLE: "gang_infeasible",
    CODE_ADMISSION_BLOCKED: "admission_blocked",
}

#: the capacity-vs-policy split the admission queue keys on.  A
#: QUEUEABLE failure is transient cluster state — someone else holds the
#: capacity right now (gang reservations, GAS card occupancy, no feasible
#: slice THIS tick) — so retrying from the queue can succeed without any
#: policy change.  Everything else is TERMINAL for the queue: a
#: ``dontschedule`` policy rejection, fail-closed degradation, or a node
#: that structurally cannot host the pod will fail identically on every
#: retry, so enqueueing it would only burn fairness budget (the
#: never-retry-a-policy-rejection pin in tests/test_admission.py).
QUEUEABLE_CODES = frozenset(
    {CODE_GAS_CAPACITY, CODE_GANG_RESERVED, CODE_GANG_INFEASIBLE}
)


def queueable(code: int) -> bool:
    """Whether one Filter failure code is capacity-class (retryable from
    the admission queue) rather than policy/error-class (terminal)."""
    return code in QUEUEABLE_CODES


def queueable_counts(reason_counts: Mapping[int, int]) -> bool:
    """Whether a whole Filter failure is queueable: every failed node's
    reason must be capacity-class.  One terminal reason anywhere makes
    the decision terminal — a pod rejected by policy on half the mesh
    and capacity on the other half would never bind even if the capacity
    half freed up, unless the policy verdict changes (which re-enters
    Filter on its own)."""
    counted = False
    for code, count in reason_counts.items():
        if not count:
            continue
        counted = True
        if code not in QUEUEABLE_CODES:
            return False
    return counted

REASON_FAIL_CLOSED = "degraded fail-closed"
REASON_GAS_UNKNOWN = "gas: node unknown to cache"
REASON_GAS_NO_GPUS = "gas: node has no GPUs"
REASON_GAS_ERROR = "gas: node could not be evaluated"

_OP_SYMBOLS = {"LessThan": "<", "GreaterThan": ">", "Equals": "=="}


def fmt_milli(milli: int) -> str:
    """Decimal string of a milli-unit int64 ("93000" -> "93", "500" ->
    "0.5").  Both provenance paths format observed values and thresholds
    through this one function from the SAME milli integers the device
    mirror stores, so native and host reason strings are byte-identical
    wherever the device path is eligible at all."""
    sign = "-" if milli < 0 else ""
    whole, frac = divmod(abs(int(milli)), 1000)
    if frac == 0:
        return f"{sign}{whole}"
    return f"{sign}{whole}.{str(frac).zfill(3).rstrip('0')}"


def rule_reason(
    policy: str, metric: str, operator: str, value_str: str, target_str: str
) -> str:
    """The concrete Filter ``FailedNodes`` reason for one violated rule:
    which policy, which metric, observed value vs threshold — e.g.
    ``policy cpu-pol: metric cpu=93 > threshold 80``."""
    sym = _OP_SYMBOLS.get(operator, operator)
    return f"policy {policy}: metric {metric}={value_str} {sym} threshold {target_str}"


def gas_reason(code: int, request_summary: str = "") -> str:
    """The concrete GAS Filter reason for one failed node; identical on
    the device (vmapped binpack) and host (per-node loop) paths because
    both derive it from the same code + the pod's own request."""
    if code == CODE_GAS_UNKNOWN_NODE:
        return REASON_GAS_UNKNOWN
    if code == CODE_GAS_NO_GPUS:
        return REASON_GAS_NO_GPUS
    if code == CODE_GAS_ERROR:
        return REASON_GAS_ERROR
    if request_summary:
        return f"gas: no card fits request ({request_summary})"
    return "gas: no card fits request"


def _rank_bucket(rank: Optional[int]) -> str:
    if rank is None:
        return "unknown"
    if rank <= 3:
        return str(rank)
    if rank <= 8:
        return "4_8"
    if rank <= 16:
        return "9_16"
    return "17_plus"


# ---------------------------------------------------------------------------
# records
# ---------------------------------------------------------------------------

#: per-record bound on materialized per-node detail in to_dict(); the
#: underlying shared reason maps are complete — only the JSON rendering
#: truncates (the endpoint must stay bounded at 10k-node scale)
DETAIL_NODE_CAP = 32

#: retention bound for a record's OWN per-request violating map
#: (violating_scope="request"): a fail-closed Filter at 10k nodes must
#: not pin a fresh 10k-entry dict per ring slot.  Shared per-state maps
#: (scope "policy_state") stay full by reference — they are one object
#: per state, not per record.
RETAIN_NODE_CAP = 128


class DecisionRecord:
    """One Filter/Prioritize/rebalance decision, keyed by request-id +
    pod.  Open until an outcome observation (pod bind, rebalance
    eviction) closes it or the ring overwrites it."""

    __slots__ = (
        "seq",
        "request_id",
        "verb",
        "pod_namespace",
        "pod_name",
        "policy",
        "path",
        "ts",
        "candidates",
        "eligible",
        "filtered",
        "violating",
        "violating_scope",
        "violating_total",
        "metric",
        "operator",
        "score_head",
        "planned",
        "detail",
        "outcome",
        "events",
        "_ranked",
        "_node_index",
    )

    def __init__(
        self,
        verb: str,
        request_id: str = "",
        pod_namespace: str = "",
        pod_name: str = "",
        policy: str = "",
        path: str = "",
        candidates: int = 0,
        filtered: int = 0,
        violating: Optional[Mapping[str, str]] = None,
        violating_scope: str = "request",
        metric: str = "",
        operator: str = "",
        score_head: Optional[List[Tuple[str, int]]] = None,
        planned: Optional[str] = None,
        detail: Optional[Dict] = None,
        ranked=None,
        node_index: Optional[Mapping[str, int]] = None,
    ):
        self.seq = 0  # assigned by the log
        self.request_id = request_id
        self.verb = verb
        self.pod_namespace = pod_namespace
        self.pod_name = pod_name
        self.policy = policy
        self.path = path
        self.ts = 0.0  # stamped by the log's clock in add(), like seq
        self.candidates = candidates
        self.filtered = filtered
        self.eligible = max(0, candidates - filtered)
        # shared, state-level reason map (device paths) or the request's
        # own failed map (exact path) — ``violating_scope`` says which
        violating = violating if violating is not None else {}
        self.violating_total = len(violating)
        if (
            violating_scope == "request"
            and len(violating) > RETAIN_NODE_CAP
        ):
            violating = dict(
                pair
                for pair, _ in zip(violating.items(), range(RETAIN_NODE_CAP))
            )
        self.violating = violating
        self.violating_scope = violating_scope
        self.metric = metric
        self.operator = operator
        self.score_head = score_head if score_head is not None else []
        self.planned = planned
        self.detail = detail
        self.outcome: Optional[Dict] = None
        self.events: List[Dict] = []
        # device-path rank lookup at bind time: the shared global
        # ranking + interning table (references, not copies)
        self._ranked = ranked
        self._node_index = node_index

    @property
    def pod_key(self) -> str:
        return f"{self.pod_namespace}/{self.pod_name}"

    def chosen_rank(self, node: str) -> Optional[int]:
        """1-based score rank of ``node`` in this decision's ordering, or
        None when unknown (host-path records keep only the score head)."""
        if self._ranked is not None and self._node_index is not None:
            row = self._node_index.get(node)
            if row is None:
                return None
            import numpy as np

            at = np.nonzero(self._ranked == row)[0]
            return int(at[0]) + 1 if at.size else None
        for i, (name, _score) in enumerate(self.score_head):
            if name == node:
                return i + 1
        return None

    def to_dict(self) -> Dict:
        violating = {}
        truncated = self.violating_total > len(self.violating)
        for i, (name, reason) in enumerate(self.violating.items()):
            if i >= DETAIL_NODE_CAP:
                truncated = True
                break
            violating[name] = reason
        out = {
            "seq": self.seq,
            "request_id": self.request_id,
            "verb": self.verb,
            "pod": self.pod_key,
            "policy": self.policy,
            "path": self.path,
            "ts": round(self.ts, 6),
            "candidates": self.candidates,
            "eligible": self.eligible,
            "filtered": self.filtered,
            "violating": violating,
            "violating_scope": self.violating_scope,
            "open": self.outcome is None,
        }
        if truncated:
            out["violating_truncated"] = True
            out["violating_total"] = self.violating_total
        if self.metric:
            out["metric"] = self.metric
            out["operator"] = self.operator
        if self.score_head:
            out["score_head"] = [
                {"node": n, "score": s} for n, s in self.score_head
            ]
        if self.planned is not None:
            out["planned"] = self.planned
        if self.detail is not None:
            out["detail"] = self.detail
        if self.outcome is not None:
            out["outcome"] = self.outcome
        if self.events:
            out["events"] = list(self.events)
        return out


# ---------------------------------------------------------------------------
# the log
# ---------------------------------------------------------------------------


class DecisionLog:
    """Bounded ring of DecisionRecords + a pod-keyed index of the OPEN
    ones (awaiting bind/rebalance feedback).  Lock-light: one short lock
    per record/feedback event; /debug/decisions serves a snapshot."""

    def __init__(
        self,
        capacity: int = 512,
        enabled: bool = True,
        clock: Callable[[], float] = time.time,
    ):
        self.capacity = max(1, capacity)
        self.enabled = enabled
        self._clock = clock
        self._lock = threading.Lock()
        self._records: deque = deque()
        self._open_by_pod: Dict[str, List[DecisionRecord]] = {}
        self._seq = 0
        self._recorded_total = 0
        self._open = 0

    # -- configuration ---------------------------------------------------------

    def configure(
        self, enabled: Optional[bool] = None, capacity: Optional[int] = None
    ) -> None:
        """Apply --decisionLog / --decisionLogSize; resets the ring (the
        records recorded under the old configuration keyed a different
        retention contract)."""
        with self._lock:
            if capacity is not None:
                self.capacity = max(1, int(capacity))
            if enabled is not None:
                self.enabled = bool(enabled)
            self._records.clear()
            self._open_by_pod.clear()
            self._open = 0
            self._recorded_total = 0
        trace.COUNTERS.set_gauge("pas_decision_open", 0.0)

    def clear(self) -> None:
        self.configure()

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    # -- recording -------------------------------------------------------------

    def add(self, record: DecisionRecord) -> None:
        if not self.enabled:
            return
        evicted_open = 0
        with self._lock:
            self._seq += 1
            record.seq = self._seq
            record.ts = self._clock()
            self._recorded_total += 1
            self._records.append(record)
            # records born closed (rebalance cycle summaries) never count
            # open: nothing can ever feed them back, and counting them
            # would fire the ring-too-small counter on every eviction
            if record.outcome is None:
                self._open += 1
                self._open_by_pod.setdefault(record.pod_key, []).append(
                    record
                )
            while len(self._records) > self.capacity:
                old = self._records.popleft()
                if old.outcome is None:
                    self._open -= 1
                    evicted_open += 1
                bucket = self._open_by_pod.get(old.pod_key)
                if bucket is not None:
                    try:
                        bucket.remove(old)
                    except ValueError:
                        pass
                    if not bucket:
                        del self._open_by_pod[old.pod_key]
            open_now = self._open
        trace.COUNTERS.inc(
            "pas_decision_records_total", labels={"verb": record.verb}
        )
        if evicted_open:
            trace.COUNTERS.inc(
                "pas_decision_evicted_open_total", evicted_open
            )
        trace.COUNTERS.set_gauge("pas_decision_open", float(open_now))

    def record_filter(
        self,
        verb: str = "filter",
        reason_code: int = CODE_RULE_VIOLATION,
        reason_counts: Optional[Dict[int, int]] = None,
        **kwargs,
    ) -> None:
        """One Filter decision.  ``filtered`` is the request's exact
        failed-node count (the per-reason counters must be exact even
        when the per-node map is a shared state-level reference); pass
        ``reason_counts`` ({code: node count}) when one request mixes
        reason classes (GAS: no-GPUs nodes next to capacity misses)."""
        if not self.enabled:
            return
        record = DecisionRecord(verb=verb, **kwargs)
        self.add(record)
        if reason_counts:
            for code, count in reason_counts.items():
                if count:
                    trace.COUNTERS.inc(
                        "pas_decision_filtered_nodes_total",
                        count,
                        labels={"reason": CODE_LABELS.get(code, "other")},
                    )
        elif record.filtered:
            trace.COUNTERS.inc(
                "pas_decision_filtered_nodes_total",
                record.filtered,
                labels={"reason": CODE_LABELS.get(reason_code, "other")},
            )

    def record_prioritize(self, verb: str = "prioritize", **kwargs) -> None:
        if not self.enabled:
            return
        self.add(DecisionRecord(verb=verb, **kwargs))

    def record_rebalance(self, detail: Dict) -> None:
        """One rebalance cycle's plan/actuation summary as a record
        (pod-less: the per-pod linkage happens via observe_rebalance)."""
        if not self.enabled:
            return
        record = DecisionRecord(
            verb="rebalance",
            pod_namespace="-",
            pod_name="rebalance",
            path=detail.get("mode", ""),
            detail=detail,
        )
        # a cycle summary IS its own outcome — born closed, so it never
        # inflates pas_decision_open or the evicted-open counter
        record.outcome = {"completed": True}
        self.add(record)

    def record_control(self, detail: Dict) -> None:
        """One budget-controller actuation (utils/control.py): the knob,
        direction, trigger SLO and before/after settings, pod-less and
        born closed like a rebalance cycle summary — an actuation is its
        own outcome."""
        if not self.enabled:
            return
        record = DecisionRecord(
            verb="control",
            pod_namespace="-",
            pod_name=str(detail.get("knob", "control")),
            path=str(detail.get("direction", "")),
            detail=detail,
        )
        record.outcome = {"completed": True}
        self.add(record)

    def record_admission(self, detail: Dict) -> None:
        """One admission-plane event (admission/plane.py): enqueue,
        backfill, overflow shed, or starvation promotion — keyed by the
        subject pod but born closed (the pod's own Filter records carry
        the open/bind lifecycle; the admission event is its own
        outcome)."""
        if not self.enabled:
            return
        pod = str(detail.get("pod", "-/admission"))
        namespace, _, name = pod.partition("/")
        record = DecisionRecord(
            verb="admission",
            request_id=str(detail.get("request_id", "")),
            pod_namespace=namespace or "-",
            pod_name=name or "admission",
            path=str(detail.get("event", "")),
            detail=detail,
        )
        record.outcome = {"completed": True}
        self.add(record)

    def record_preemption(self, detail: Dict) -> None:
        """One gang preemption (admission/preempt.py): which gang was
        admitted over which victims, the per-victim eviction counts, and
        the slice reserved for the preemptor — the provenance record the
        acceptance gate requires for EVERY preemption.  Born closed like
        a rebalance cycle summary."""
        if not self.enabled:
            return
        pod = str(detail.get("target", "-/preemption"))
        namespace, _, name = pod.partition("/")
        record = DecisionRecord(
            verb="preemption",
            request_id=str(detail.get("request_id", "")),
            pod_namespace=namespace or "-",
            pod_name=name or "preemption",
            path=str(detail.get("outcome", "")),
            detail=detail,
        )
        record.outcome = {"completed": True}
        self.add(record)

    # -- outcome feedback ------------------------------------------------------

    def observe_bind(self, namespace: str, name: str, node: str) -> None:
        """A pod-bind observation: close the pod's open records, scoring
        placement quality against what was decided — the chosen node's
        rank in the Prioritize ordering, and whether Filter had marked it
        violating at decision time."""
        if not self.enabled:
            return
        key = f"{namespace}/{name}"
        bound_at = self._clock()
        violated = False
        rank: Optional[int] = None
        # outcomes are assigned UNDER the lock: a record must never sit
        # decremented-from-_open but still outcome-None, or a concurrent
        # add()'s ring eviction would double-decrement it (binds are
        # rare, so the rank lookup's numpy scan is fine to hold here)
        with self._lock:
            open_records = self._open_by_pod.pop(key, [])
            closed = [r for r in open_records if r.outcome is None]
            for record in closed:
                outcome: Dict = {
                    "bound_node": node,
                    "bound_at": round(bound_at, 6),
                }
                if record.verb.endswith("prioritize"):
                    r = record.chosen_rank(node)
                    outcome["rank"] = r
                    if rank is None:
                        rank = r
                if record.violating and node in record.violating:
                    outcome["violated_at_bind"] = True
                    outcome["violation_reason"] = record.violating[node]
                    violated = True
                record.outcome = outcome
            self._open -= len(closed)
            open_now = self._open
        if not closed:
            return
        trace.COUNTERS.inc("pas_decision_closed_total", len(closed))
        if any(r.verb.endswith("prioritize") for r in closed):
            trace.COUNTERS.inc(
                "pas_decision_chosen_rank_total",
                labels={"rank": _rank_bucket(rank)},
            )
        if violated:
            trace.COUNTERS.inc("pas_decision_violated_at_bind_total")
        trace.COUNTERS.set_gauge("pas_decision_open", float(open_now))

    def observe_rebalance(
        self, namespace: str, name: str, action: str, detail: str = ""
    ) -> None:
        """Rebalancer evict/skip feedback: appended as an event to the
        pod's open records (an evicted pod's decision is superseded — the
        pod will be rescheduled — but the record stays open so the NEXT
        bind closes it with the post-eviction placement)."""
        if not self.enabled:
            return
        key = f"{namespace}/{name}"
        event = {
            "ts": round(self._clock(), 6),
            "action": action,
        }
        if detail:
            event["detail"] = detail
        with self._lock:
            for record in self._open_by_pod.get(key, []):
                record.events.append(event)

    # -- the debug surface -----------------------------------------------------

    def snapshot(
        self,
        pod: Optional[str] = None,
        verb: Optional[str] = None,
        limit: int = 64,
    ) -> Dict:
        with self._lock:
            records = list(self._records)
            recorded_total = self._recorded_total
            open_count = self._open
        selected = []
        for record in reversed(records):  # newest first
            if pod is not None and pod not in (record.pod_name, record.pod_key):
                continue
            if verb is not None and record.verb != verb:
                continue
            selected.append(record.to_dict())
            if len(selected) >= max(1, limit):
                break
        return {
            "enabled": self.enabled,
            "capacity": self.capacity,
            "recorded_total": recorded_total,
            "open": open_count,
            "returned": len(selected),
            "records": selected,
        }

    def to_json(
        self,
        pod: Optional[str] = None,
        verb: Optional[str] = None,
        limit: int = 64,
    ) -> bytes:
        return (
            json.dumps(self.snapshot(pod=pod, verb=verb, limit=limit)).encode()
            + b"\n"
        )


#: the process-wide log every layer records into (like trace.TRACES);
#: --decisionLog=off flips ``enabled`` via configure()
DECISIONS = DecisionLog()

"""Per-request latency tracing.

The reference has no tracing/profiling at all (SURVEY §5.1: no pprof, no
OpenTelemetry — only klog verbosity).  Since this framework's north-star
metric is p99 Prioritize latency, latency histograms are built in: every
extender verb records into a :class:`LatencyRecorder`, exposed as a
Prometheus-style text dump (and consumed by bench.py).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, Iterable, List, Tuple

# exponential bucket bounds in seconds: 100us .. ~105s
_BUCKETS: List[float] = [0.0001 * (2**i) for i in range(21)]


def quantile(sorted_values: List[float], q: float) -> float:
    if not sorted_values:
        return 0.0
    idx = min(len(sorted_values) - 1, max(0, int(q * len(sorted_values))))
    return sorted_values[idx]


class CounterSet:
    """Thread-safe named counters and gauges with Prometheus text
    exposition — the non-latency half of the serving subsystem's metrics
    (queue depth, admission rejections, batch sizes; docs/serving.md).
    Names are emitted verbatim, so callers pass fully-qualified metric
    names (``pas_serving_queue_depth`` etc.)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}

    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + by

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def get(self, name: str) -> float:
        with self._lock:
            if name in self._counters:
                return self._counters[name]
            return self._gauges.get(name, 0)

    def prometheus_text(self) -> str:
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
        lines = [f"{name} {value}" for name, value in counters]
        lines += [f"{name} {value:g}" for name, value in gauges]
        return "\n".join(lines) + ("\n" if lines else "")


class LatencyRecorder:
    """Thread-safe per-label latency stats: histogram buckets plus a bounded
    window of raw samples for exact quantiles."""

    def __init__(self, window: int = 4096):
        self._lock = threading.Lock()
        self._window = window
        self._samples: Dict[str, Deque[float]] = {}
        self._counts: Dict[str, int] = {}
        self._sums: Dict[str, float] = {}
        self._buckets: Dict[str, List[int]] = {}

    def observe(self, label: str, seconds: float) -> None:
        with self._lock:
            if label not in self._samples:
                self._samples[label] = deque(maxlen=self._window)
                self._counts[label] = 0
                self._sums[label] = 0.0
                self._buckets[label] = [0] * (len(_BUCKETS) + 1)
            self._samples[label].append(seconds)
            self._counts[label] += 1
            self._sums[label] += seconds
            for i, bound in enumerate(_BUCKETS):
                if seconds <= bound:
                    self._buckets[label][i] += 1
                    break
            else:
                self._buckets[label][-1] += 1

    def labels(self) -> List[str]:
        with self._lock:
            return list(self._counts)

    def summary(self, label: str) -> Dict[str, float]:
        with self._lock:
            samples = sorted(self._samples.get(label, ()))
            count = self._counts.get(label, 0)
            total = self._sums.get(label, 0.0)
        return {
            "count": count,
            "mean": (total / count) if count else 0.0,
            "p50": quantile(samples, 0.50),
            "p90": quantile(samples, 0.90),
            "p99": quantile(samples, 0.99),
            "max": samples[-1] if samples else 0.0,
        }

    def prometheus_text(self) -> str:
        """Cumulative-histogram text exposition (the format the reference's
        own metrics pipeline scrapes, docs/custom-metrics.md)."""
        lines: List[str] = []
        with self._lock:
            items: Iterable[Tuple[str, List[int]]] = list(self._buckets.items())
            counts = dict(self._counts)
            sums = dict(self._sums)
        for label, buckets in items:
            cumulative = 0
            for bound, n in zip(_BUCKETS, buckets):
                cumulative += n
                lines.append(
                    f'pas_request_duration_seconds_bucket{{verb="{label}",le="{bound:g}"}} {cumulative}'
                )
            cumulative += buckets[-1]
            lines.append(
                f'pas_request_duration_seconds_bucket{{verb="{label}",le="+Inf"}} {cumulative}'
            )
            lines.append(
                f'pas_request_duration_seconds_sum{{verb="{label}"}} {sums[label]:.9f}'
            )
            lines.append(
                f'pas_request_duration_seconds_count{{verb="{label}"}} {counts[label]}'
            )
        return "\n".join(lines) + ("\n" if lines else "")

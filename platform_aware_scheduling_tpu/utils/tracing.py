"""Per-request latency tracing primitives.

The reference has no tracing/profiling at all (SURVEY §5.1: no pprof, no
OpenTelemetry — only klog verbosity).  Since this framework's north-star
metric is p99 Prioritize latency, latency histograms are built in: every
extender verb records into a :class:`LatencyRecorder`, and serving-layer
counters live in :class:`CounterSet`, both exposed as real Prometheus
text exposition (``# HELP``/``# TYPE``, ``_bucket``/``_sum``/``_count``
histogram series) on ``/metrics`` and consumed by bench.py.

The request-level span model, the trace ring buffer, and the metric-name
inventory build on these in utils/trace.py (docs/observability.md).
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Deque, Dict, Iterable, List, Optional, Tuple

# bucket bounds in seconds: a doubling ladder from 100 µs to ~105 s,
# densified below 1 ms (250/500/750 µs).  The serving floor at 10k nodes
# is ~755 µs host-side (ROADMAP item 1), and with the bare 2x ladder the
# whole sub-millisecond story — and every latency SLO computed from these
# buckets (utils/slo.py) — collapsed into the 400 µs -> 800 µs step; the
# extra bounds resolve it.  Sorted and deduplicated by construction so
# the exposition's cumulative-bucket invariant cannot be violated by a
# misordered literal.  PUBLIC: this ladder is the one definition shared
# by the histogram exposition, the SLO quantile math (utils/slo.py), and
# the exemplar store below — consumers import ``BUCKETS``, never a copy.
BUCKETS: List[float] = sorted(
    {0.00025, 0.0005, 0.00075} | {0.0001 * (2**i) for i in range(21)}
)
#: backward-compatible alias (pre-explain-plane importers)
_BUCKETS = BUCKETS


def quantile_from_buckets(
    buckets: List[int], q: float, bounds: Optional[List[float]] = None
) -> float:
    """Estimate the q-quantile in seconds from per-bucket counts.

    ``buckets`` holds one count per bound in ``bounds`` (default: the
    shared ``BUCKETS`` ladder) plus a trailing +Inf overflow count —
    exactly the shape :meth:`LatencyRecorder.snapshot` returns, and the
    shape the SLO engine's windowed bucket deltas take (utils/slo.py).

    The estimate interpolates LINEARLY WITHIN the bucket containing the
    target rank (between the previous bound — 0 for the first bucket —
    and the bucket's own bound), at the continuous rank ``q * total``
    inside the bucket's samples — the Prometheus ``histogram_quantile``
    convention, which assumes samples spread uniformly across the
    bucket.  Returning the bucket's upper bound outright would overstate
    sparse distributions by up to a whole bucket width, and an EMPTY
    family would "estimate" the top bound of the ladder.  Edge cases,
    each pinned in tests/test_slo.py:

      * zero observations -> 0.0 (no data is not "as slow as possible");
      * all samples in one bucket -> a value inside that bucket;
      * samples in the +Inf overflow bucket -> the last finite bound
        (there is no upper edge to interpolate toward — the estimate is
        a floor, as for any +Inf-bucket quantile)."""
    if bounds is None:
        bounds = BUCKETS
    total = sum(buckets)
    if total <= 0:
        return 0.0
    # continuous rank (histogram_quantile convention), clamped into
    # (0, total] so q=0 and q=1 stay inside the observed range
    rank = min(float(total), max(1e-9, q * total))
    cumulative = 0.0
    for i, count in enumerate(buckets):
        if count <= 0:
            continue
        if cumulative + count >= rank:
            if i >= len(bounds):
                # +Inf bucket: no finite upper edge — floor estimate
                return bounds[-1]
            lower = bounds[i - 1] if i > 0 else 0.0
            upper = bounds[i]
            fraction = (rank - cumulative) / count
            return lower + (upper - lower) * fraction
        cumulative += count
    return bounds[-1]  # unreachable when counts sum to total


def bucket_count_below(
    buckets: List[int],
    threshold_s: float,
    bounds: Optional[List[float]] = None,
) -> float:
    """How many of the bucketed samples fall at or under ``threshold_s``
    — the latency-SLI "good event" count (utils/slo.py).  Whole buckets
    whose bound is <= threshold count fully; the bucket straddling the
    threshold contributes the linearly interpolated fraction of its
    width below it (the same within-bucket model as
    :func:`quantile_from_buckets`); +Inf samples never count."""
    if bounds is None:
        bounds = BUCKETS
    good = 0.0
    for i, count in enumerate(buckets):
        if count <= 0:
            continue
        if i >= len(bounds):
            break  # +Inf bucket: all above any finite threshold
        lower = bounds[i - 1] if i > 0 else 0.0
        upper = bounds[i]
        if upper <= threshold_s:
            good += count
        elif lower < threshold_s:
            good += count * (threshold_s - lower) / (upper - lower)
    return good


def quantile(sorted_values: List[float], q: float) -> float:
    """Nearest-rank quantile over an ascending-sorted sample.

    ``ceil(q * n)`` is the classic nearest-rank definition: p99 of 100
    samples is the 99th value (index 98), p50 of 4 samples is the 2nd.
    The previous ``int(q * n)`` overshot by one rank — for small windows
    p99 collapsed to the out-of-range-clamped max every time."""
    if not sorted_values:
        return 0.0
    rank = math.ceil(q * len(sorted_values))
    idx = min(len(sorted_values) - 1, max(0, rank - 1))
    return sorted_values[idx]


def _fmt_value(value) -> str:
    """Prometheus sample value: ints stay exact, floats go %g."""
    if isinstance(value, int):
        return str(value)
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return f"{value:g}"


#: a family's series map: label tuple (sorted (k, v) pairs) -> value.
#: The unlabeled series uses the empty tuple.
_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[Dict[str, str]]) -> _LabelKey:
    return tuple(sorted(labels.items())) if labels else ()


def _escape_label_value(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _render_series(name: str, key: _LabelKey) -> str:
    if not key:
        return name
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in key
    )
    return f"{name}{{{inner}}}"


class CounterSet:
    """Thread-safe named counters and gauges with Prometheus text
    exposition — the non-latency half of the serving metrics (queue
    depth, admission rejections, batch sizes; docs/serving.md), the
    path-attribution / JAX-compile counters (utils/trace.py), and the
    control-plane/device families (telemetry ages, workqueue depth,
    device watermarks).  Names are emitted verbatim, so callers pass
    fully-qualified metric names (``pas_serving_queue_depth`` etc.; the
    inventory lives in trace.METRICS and ``make trace-lint`` enforces
    it).  A family may carry labeled series (``labels={"metric": ...}``)
    — one ``# TYPE`` line per family, one sample line per label set."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Dict[_LabelKey, float]] = {}
        self._gauges: Dict[str, Dict[_LabelKey, float]] = {}

    def inc(
        self,
        name: str,
        by: float = 1,
        labels: Optional[Dict[str, str]] = None,
    ) -> None:
        key = _label_key(labels)
        with self._lock:
            series = self._counters.setdefault(name, {})
            series[key] = series.get(key, 0) + by

    def set_gauge(
        self,
        name: str,
        value: float,
        labels: Optional[Dict[str, str]] = None,
    ) -> None:
        with self._lock:
            self._gauges.setdefault(name, {})[_label_key(labels)] = value

    def get(
        self,
        name: str,
        kind: Optional[str] = None,
        labels: Optional[Dict[str, str]] = None,
    ) -> float:
        """The value under ``name``: the exact series when ``labels`` is
        given, the sum over every series otherwise (for an unlabeled
        family that is just its single value).  When a counter and a
        gauge collide on one name, ``kind`` ("counter" or "gauge")
        disambiguates; without it the counter wins (the historical
        precedence)."""
        key = None if labels is None else _label_key(labels)

        def read(table: Dict[str, Dict[_LabelKey, float]]) -> float:
            series = table.get(name, {})
            if key is not None:
                return series.get(key, 0)
            return sum(series.values()) if series else 0

        with self._lock:
            if kind == "counter":
                return read(self._counters)
            if kind == "gauge":
                return read(self._gauges)
            if kind is not None:
                raise ValueError(f"unknown kind {kind!r}")
            if name in self._counters:
                return read(self._counters)
            return read(self._gauges)

    def remove(
        self,
        name: str,
        labels: Optional[Dict[str, str]] = None,
        kind: Optional[str] = None,
    ) -> None:
        """Drop a series (or, with ``labels=None``, the whole family)
        from future exposition — for label sets whose subject no longer
        exists (an evicted telemetry metric's age gauge must not stay
        frozen in /metrics forever)."""
        key = None if labels is None else _label_key(labels)
        tables = (
            [self._counters] if kind == "counter"
            else [self._gauges] if kind == "gauge"
            else [self._counters, self._gauges]
        )
        with self._lock:
            for table in tables:
                if key is None:
                    table.pop(name, None)
                    continue
                series = table.get(name)
                if series is not None:
                    series.pop(key, None)
                    if not series:
                        del table[name]

    def prometheus_text(
        self, help_texts: Optional[Dict[str, str]] = None
    ) -> str:
        """Valid exposition: ``# HELP`` (when the name is in the declared
        inventory) + ``# TYPE`` per family, then one sample per series.
        A name colliding across counter and gauge emits the counter only
        — two TYPE lines for one name would be invalid exposition
        (get(kind=) still reads both)."""
        with self._lock:
            counters = sorted(
                (name, sorted(series.items()))
                for name, series in self._counters.items()
            )
            gauges = sorted(
                (name, sorted(series.items()))
                for name, series in self._gauges.items()
                if name not in self._counters
            )
        lines: List[str] = []
        for kind, families in (("counter", counters), ("gauge", gauges)):
            for name, series in families:
                if help_texts and name in help_texts:
                    lines.append(f"# HELP {name} {help_texts[name]}")
                lines.append(f"# TYPE {name} {kind}")
                for key, value in series:
                    lines.append(
                        f"{_render_series(name, key)} {_fmt_value(value)}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")


class LatencyRecorder:
    """Thread-safe per-label latency stats: histogram buckets plus a bounded
    window of raw samples for exact quantiles."""

    def __init__(self, window: int = 4096):
        self._lock = threading.Lock()
        self._window = window
        self._samples: Dict[str, Deque[float]] = {}
        self._counts: Dict[str, int] = {}
        self._sums: Dict[str, float] = {}
        self._buckets: Dict[str, List[int]] = {}
        #: last exemplar per (label, bucket index): trace id + value.
        #: Bounded by labels x buckets by construction; "last one wins"
        #: is the OpenMetrics-conventional choice — the newest slow
        #: request is the one worth opening in /debug/explain
        self._exemplars: Dict[str, Dict[int, Tuple[str, float]]] = {}

    def observe(
        self, label: str, seconds: float, trace_id: str = ""
    ) -> None:
        with self._lock:
            if label not in self._samples:
                self._samples[label] = deque(maxlen=self._window)
                self._counts[label] = 0
                self._sums[label] = 0.0
                self._buckets[label] = [0] * (len(BUCKETS) + 1)
            self._samples[label].append(seconds)
            self._counts[label] += 1
            self._sums[label] += seconds
            for i, bound in enumerate(BUCKETS):
                if seconds <= bound:
                    self._buckets[label][i] += 1
                    break
            else:
                i = len(BUCKETS)
                self._buckets[label][-1] += 1
            if trace_id:
                self._exemplars.setdefault(label, {})[i] = (
                    trace_id, seconds,
                )

    def exemplars(self) -> Dict[str, Dict[int, Tuple[str, float]]]:
        """label -> {bucket index -> (trace_id, seconds)}: the newest
        exemplar recorded in each bucket (copy; merge surface for
        :func:`histograms_text`)."""
        with self._lock:
            return {
                label: dict(per_bucket)
                for label, per_bucket in self._exemplars.items()
            }

    def labels(self) -> List[str]:
        with self._lock:
            return list(self._counts)

    def summary(self, label: str) -> Dict[str, float]:
        with self._lock:
            samples = sorted(self._samples.get(label, ()))
            count = self._counts.get(label, 0)
            total = self._sums.get(label, 0.0)
        return {
            "count": count,
            "mean": (total / count) if count else 0.0,
            "p50": quantile(samples, 0.50),
            "p90": quantile(samples, 0.90),
            "p99": quantile(samples, 0.99),
            "max": samples[-1] if samples else 0.0,
        }

    def snapshot(self) -> Dict[str, Tuple[List[int], int, float]]:
        """label -> (bucket counts copy, count, sum): the merge surface
        behind :func:`histograms_text` (several recorders, one family)."""
        with self._lock:
            return {
                label: (list(buckets), self._counts[label], self._sums[label])
                for label, buckets in self._buckets.items()
            }

    def prometheus_text(self) -> str:
        """Cumulative-histogram text exposition (the format the reference's
        own metrics pipeline scrapes, docs/custom-metrics.md)."""
        return histograms_text([self])


HISTOGRAM_METRIC = "pas_request_duration_seconds"


def histograms_text(
    recorders: Iterable["LatencyRecorder"],
    metric: str = HISTOGRAM_METRIC,
    help_texts: Optional[Dict[str, str]] = None,
    label_name: str = "verb",
) -> str:
    """All recorders' labels merged under ONE histogram family with a
    single ``# TYPE`` line — concatenating per-recorder dumps would emit
    duplicate family headers, which is invalid exposition.  A label
    recorded by several recorders sums (the serving layer and a verb
    handler never share labels in practice, but the merge must still be
    well-formed exposition if they do).

    Bucket lines carry OpenMetrics EXEMPLARS when the recorder has them
    (``... 12 # {trace_id="..."} 0.000431``): the newest trace id that
    landed in that bucket, joining a slow histogram bucket to its
    ``/debug/traces`` span and ``/debug/explain`` chain.  Prometheus'
    text parser ignores everything after ``#`` on a sample line, so the
    page stays scrape-compatible; our own parser
    (``trace.parse_prometheus_text``) strips the annotation explicitly."""
    merged: Dict[str, Tuple[List[int], int, float]] = {}
    exemplars: Dict[str, Dict[int, Tuple[str, float]]] = {}
    for recorder in recorders:
        for label, (buckets, count, total) in recorder.snapshot().items():
            if label in merged:
                old_buckets, old_count, old_sum = merged[label]
                merged[label] = (
                    [a + b for a, b in zip(old_buckets, buckets)],
                    old_count + count,
                    old_sum + total,
                )
            else:
                merged[label] = (buckets, count, total)
        for label, per_bucket in recorder.exemplars().items():
            exemplars.setdefault(label, {}).update(per_bucket)
    if not merged:
        return ""

    def exemplar_suffix(label: str, index: int) -> str:
        entry = exemplars.get(label, {}).get(index)
        if entry is None:
            return ""
        trace_id, seconds = entry
        return (
            f' # {{trace_id="{_escape_label_value(trace_id)}"}} '
            f"{seconds:.9f}"
        )

    help_text = (help_texts or {}).get(metric)
    lines: List[str] = []
    if help_text:
        lines.append(f"# HELP {metric} {help_text}")
    lines.append(f"# TYPE {metric} histogram")
    for label in sorted(merged):
        buckets, count, total = merged[label]
        cumulative = 0
        for i, (bound, n) in enumerate(zip(BUCKETS, buckets)):
            cumulative += n
            lines.append(
                f'{metric}_bucket{{{label_name}="{label}",le="{bound:g}"}} '
                f"{cumulative}{exemplar_suffix(label, i)}"
            )
        cumulative += buckets[-1]
        lines.append(
            f'{metric}_bucket{{{label_name}="{label}",le="+Inf"}} '
            f"{cumulative}{exemplar_suffix(label, len(BUCKETS))}"
        )
        lines.append(f'{metric}_sum{{{label_name}="{label}"}} {total:.9f}')
        lines.append(f'{metric}_count{{{label_name}="{label}"}} {count}')
    return "\n".join(lines) + "\n"

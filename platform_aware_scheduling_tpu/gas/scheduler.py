"""GAS scheduling logic: Filter (per-card fit check) and Bind (card
assignment + annotation + bind).

Reference: gpu-aware-scheduling/pkg/gpuscheduler/scheduler.go.  Behaviors
reproduced:

  * Filter requires ``NodeNames`` (nodeCacheCapable mode, :455-461) and
    answers 404 + an Error result otherwise;
  * card selection is first-fit over sorted card names with per-GPU
    resource division via the ``i915`` count (:200-257, 180-198) — a card
    with room for several per-GPU shares can be picked more than once for
    the same container, exactly like the reference;
  * vanished GPUs (usage recorded for a card no longer in the node label)
    are tolerated and skipped (:230-234);
  * Bind re-runs scheduling on the chosen node, books resources, annotates
    the pod (``gas-ts`` + ``gas-container-cards``) with a 5-attempt
    conflict-retry, calls the Bind subresource, and rolls the booking back
    on any later failure (:385-445, 82-119);
  * Prioritize is 404 (:515-519).

The TPU path: Filter fans the per-node fit check out as ONE vmapped XLA
pass over all candidate nodes (ops/binpack.py) instead of the reference's
sequential per-node loop — the host loop remains as exact fallback/control.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from platform_aware_scheduling_tpu.extender.server import (
    HTTPRequest,
    HTTPResponse,
)
from platform_aware_scheduling_tpu.extender.types import (
    Args,
    BindingArgs,
    BindingResult,
    FilterResult,
)
from platform_aware_scheduling_tpu.gas.cache import ADD, REMOVE, Cache
from platform_aware_scheduling_tpu.gas.resource_map import (
    NodeResources,
    ResourceMap,
)
from platform_aware_scheduling_tpu.gas.utils import (
    CARD_ANNOTATION,
    GPU_LIST_LABEL,
    GPU_PLUGIN_RESOURCE,
    RESOURCE_PREFIX,
    TS_ANNOTATION,
    container_requests,
)
from platform_aware_scheduling_tpu.kube.client import ConflictError
from platform_aware_scheduling_tpu.kube.retry import RetryPolicy
from platform_aware_scheduling_tpu.kube.objects import Node, Pod
from platform_aware_scheduling_tpu.utils import decisions, events, klog, trace
from platform_aware_scheduling_tpu.utils.quantity import Quantity
from platform_aware_scheduling_tpu.utils.tracing import LatencyRecorder

UPDATE_RETRY_COUNT = 5  # scheduler.go:28


class WontFitError(Exception):
    """will not fit (scheduler.go:49)"""


class NoGPUsError(WontFitError):
    """Node has no GPUs (vanished or never labeled) — a distinct
    provenance class from a genuine capacity miss, so the host loop and
    the device binpack produce the same reason code for it."""


def request_summary(pod: Pod) -> str:
    """Compact "res=total, ..." rendering of the pod's GPU resource
    request — the detail half of the gas capacity reason string,
    computed identically on the device and host paths (both read only
    the pod)."""
    totals: Dict[str, int] = {}
    for req in container_requests(pod):
        for name, value in req.items():
            totals[name] = totals.get(name, 0) + value
    return ", ".join(f"{k}={v}" for k, v in sorted(totals.items()))


class GASExtender:
    """extender.Scheduler implementation for GAS (scheduler.go:58-71)."""

    def __init__(
        self,
        kube_client,
        cache: Optional[Cache] = None,
        recorder: Optional[LatencyRecorder] = None,
        use_device: bool = True,
        use_mirror: bool = True,
        retry_policy: Optional[RetryPolicy] = None,
        sleep=time.sleep,
    ):
        self.kube_client = kube_client
        # backoff between annotate conflict-retries (the reference loop
        # at scheduler.go:82-119 retried with ZERO sleep, hammering the
        # API server exactly when it reported contention); deterministic
        # jitter, injectable sleep for hermetic tests
        self.retry_policy = (
            retry_policy
            if retry_policy is not None
            else RetryPolicy(
                max_attempts=UPDATE_RETRY_COUNT,
                base_delay_s=0.05,
                max_delay_s=1.0,
            )
        )
        self._sleep = sleep
        self.cache = cache if cache is not None else Cache(kube_client)
        self.recorder = recorder or LatencyRecorder()
        # workqueue work-latency histogram merges into this extender's
        # pas_request_duration_seconds family (verb="workqueue_work")
        self.cache.work_queue.recorder = self.recorder
        self._rwmutex = threading.RLock()
        # opt-in utils.slo.SLOEngine (--slo=on): judged over this
        # extender's recorder; front-ends serve GET /debug/slo (404
        # while None) and /metrics gains the pas_slo_* gauges
        self.slo = None
        # opt-in utils.control.BudgetController (--sloControl=on): GAS
        # has no serving/rebalance/forecast actuators, so the controller
        # here only observes (ticks, /debug/control, pas_control_*) —
        # knobs attach where the subsystems exist
        self.control = None
        # opt-in utils.record.FlightRecorder (--flightRecorder=on):
        # gas_filter/gas_bind arrivals land in the ring as anonymized
        # (verb, candidate count) events — GAS has no interned-universe
        # layer, so the universe key is always null here; front-ends
        # serve GET /debug/record + POST /debug/whatif (404 while None)
        self.flight = None
        # opt-in admission.AdmissionPlane (--admission=on): GAS gets the
        # queue-only plane — capacity-class (WontFit) failures enqueue,
        # otherwise-admissible pods may be held behind higher-priority
        # waiters, and the front-ends serve GET /debug/admission (404
        # while None).  No gang tracker here, so backfill's covered-
        # demand check runs size-only and preemption never attaches
        # (docs/admission.md).  Off (None) keeps the wire byte-identical.
        self.admission = None
        self._device = None
        if use_device:
            # deferred import: keeps the host layer importable without jax
            from platform_aware_scheduling_tpu.gas.device import DeviceBinpacker

            self._device = DeviceBinpacker(self.cache, use_mirror=use_mirror)

    # -- verbs -----------------------------------------------------------------

    def metrics_text(self) -> str:
        """The /metrics provider for this extender (utils/trace.py);
        pas_slo_* gauges join only while an SLO engine is wired."""
        counter_sets = [self.slo.counters] if self.slo is not None else []
        if self.control is not None:
            counter_sets.append(self.control.counters)
        if self.flight is not None:
            counter_sets.append(self.flight.counters)
        if self.admission is not None:
            counter_sets.append(self.admission.counters)
        return trace.exposition(
            recorders=[self.recorder], counter_sets=counter_sets
        )

    def _record_flight_verb(self, verb: str, request: HTTPRequest) -> None:
        """Anonymized arrival event for the verb's finally (candidate
        count only — never node names); must never raise into the verb."""
        try:
            _uid, candidates = getattr(
                request, "flight_universe", (None, 0)
            )
            self.flight.record_verb(verb, None, candidates)
        except Exception as exc:
            klog.error("flight record failed: %r", exc)

    def readiness_conditions(self):
        """The /readyz conditions GAS contributes (utils/health.py):
        node + pod informer sync — GAS serves from its resource cache,
        so answering before the initial lists land would bind against
        a fictional cluster — plus the informational slo_burn condition
        while an SLO engine is wired."""
        conditions = [("informers_synced", self.cache.synced_condition)]
        if self.slo is not None:
            conditions.append(("slo_burn", self.slo.readiness_condition))
        return conditions

    def prioritize(self, request: HTTPRequest) -> HTTPResponse:
        # not implemented by GAS (scheduler.go:515-519)
        return HTTPResponse(status=404)

    def filter(self, request: HTTPRequest) -> HTTPResponse:
        start = time.perf_counter()
        span = trace.of(request)
        span.set("verb", "gas_filter")
        try:
            klog.v(4).info_s("filter request received", component="extender")
            try:
                with span.stage("decode"):
                    args = (
                        Args.from_json(request.body) if request.body else None
                    )
            except Exception as exc:
                args = None
                klog.error("cannot decode request %s", exc)
            if args is None:
                return HTTPResponse(status=404)
            if self.flight is not None:
                request.flight_universe = (
                    None, len(args.node_names or ())
                )
            admission_codes: Dict[str, int] = {}
            with span.stage("kernel"):
                result = self._filter_nodes(
                    args, span=span, codes_out=admission_codes
                )
            span.set("pod", f"{args.pod.namespace}/{args.pod.name}")
            if self.admission is not None and not result.error:
                with span.stage("admission"):
                    result = self._admission_review(
                        args, result, admission_codes, span.trace_id
                    )
            status = 404 if result.error else 200
            with span.stage("encode"):
                body = result.to_json()
            events.JOURNAL.publish(
                "verdict",
                "gas_filter",
                request_id=span.trace_id,
                pod=f"{args.pod.namespace}/{args.pod.name}",
                data={
                    "failed": len(result.failed_nodes),
                    "path": str(span.attrs.get("path", "")),
                },
            )
            return HTTPResponse.json(body, status=status)
        finally:
            self.recorder.observe(
                "gas_filter", time.perf_counter() - start,
                trace_id=span.trace_id,
            )
            if self.flight is not None:
                self._record_flight_verb("gas_filter", request)

    def bind(self, request: HTTPRequest) -> HTTPResponse:
        start = time.perf_counter()
        span = trace.of(request)
        span.set("verb", "gas_bind")
        try:
            klog.v(4).info_s("bind request received", component="extender")
            try:
                with span.stage("decode"):
                    args = (
                        BindingArgs.from_json(request.body)
                        if request.body
                        else None
                    )
            except Exception as exc:
                args = None
                klog.error("cannot decode request %s", exc)
            if args is None:
                return HTTPResponse(status=404)
            with span.stage("kernel"):
                result = self._bind_node(args)
            status = 404 if result.error else 200
            with span.stage("encode"):
                body = result.to_json()
            events.JOURNAL.publish(
                "verdict",
                "gas_bind",
                request_id=span.trace_id,
                pod=f"{args.pod_namespace}/{args.pod_name}",
                node=args.node,
                data={"status": status},
            )
            return HTTPResponse.json(body, status=status)
        finally:
            self.recorder.observe(
                "gas_bind", time.perf_counter() - start,
                trace_id=span.trace_id,
            )
            if self.flight is not None:
                self._record_flight_verb("gas_bind", request)

    # -- filter (scheduler.go:447-482) -----------------------------------------

    def _filter_nodes(
        self,
        args: Args,
        span=trace.NULL_SPAN,
        codes_out: Optional[Dict[str, int]] = None,
    ) -> FilterResult:
        if not args.node_names:
            error = (
                "No nodes to compare. This should not happen, perhaps the "
                "extender is misconfigured with NodeCacheCapable == false."
            )
            klog.error(error)
            return FilterResult(error=error)
        summary = request_summary(args.pod)
        with self._rwmutex:
            if self._device is not None:
                try:
                    res = self._device.batch_fit(
                        args.pod, args.node_names, with_reasons=True
                    )
                except Exception as exc:
                    klog.error("device binpack failed, host fallback: %s", exc)
                    res = None
                if res is not None:
                    fits, codes = res
                    span.set("path", "device")
                    trace.COUNTERS.inc("pas_gas_filter_device_total")
                    node_names = [n for n, ok in zip(args.node_names, fits) if ok]
                    failed = {
                        n: decisions.gas_reason(code, summary)
                        for n, ok, code in zip(args.node_names, fits, codes)
                        if not ok
                    }
                    if codes_out is not None:
                        for n, ok, code in zip(
                            args.node_names, fits, codes
                        ):
                            if not ok:
                                codes_out[n] = code
                    self._record_filter_decision(
                        span, args.pod, args.node_names, failed, codes
                    )
                    return FilterResult(
                        node_names=node_names, failed_nodes=failed, error=""
                    )
            span.set("path", "host")
            trace.COUNTERS.inc("pas_gas_filter_host_total")
            node_names: List[str] = []
            failed: Dict[str, str] = {}
            codes: List[int] = []
            for node_name in args.node_names:
                code = decisions.CODE_ELIGIBLE
                try:
                    self._run_scheduling_logic(args.pod, node_name)
                    node_names.append(node_name)
                except NoGPUsError:
                    code = decisions.CODE_GAS_NO_GPUS
                except WontFitError:
                    code = decisions.CODE_GAS_CAPACITY
                except KeyError:
                    # cache.fetch_node's miss signal — matches the device
                    # path's not-interned / not-known lanes
                    code = decisions.CODE_GAS_UNKNOWN_NODE
                except Exception:
                    # anything else (malformed capacity quantity, ...) is
                    # its own class: 'unknown to cache' would point an
                    # operator at a cache miss that never happened
                    code = decisions.CODE_GAS_ERROR
                if code != decisions.CODE_ELIGIBLE:
                    failed[node_name] = decisions.gas_reason(code, summary)
                    if codes_out is not None:
                        codes_out[node_name] = code
                codes.append(code)
            self._record_filter_decision(
                span, args.pod, args.node_names, failed, codes
            )
            return FilterResult(node_names=node_names, failed_nodes=failed, error="")

    def _admission_review(
        self,
        args: Args,
        result: FilterResult,
        codes: Dict[str, int],
        request_id: str = "",
    ) -> FilterResult:
        """Consult the admission plane over one gas_filter verdict
        (admission/plane.py review contract): None keeps the verdict
        (admitted, or a WontFit-everywhere failure that enqueued); a
        replacement pair means HELD behind higher-priority queued work —
        every candidate fails CODE_ADMISSION_BLOCKED.  Fails open."""
        try:
            verdict = self.admission.review(
                args.pod,
                list(args.node_names or ()),
                dict(result.failed_nodes),
                codes,
                request_id=request_id,
            )
        except Exception as exc:
            klog.error("admission review failed open: %r", exc)
            return result
        if verdict is None:
            return result
        held, _codes = verdict
        merged = dict(result.failed_nodes)
        merged.update(held)
        node_names = [
            n for n in (result.node_names or []) if n not in held
        ]
        return FilterResult(
            node_names=node_names, failed_nodes=merged, error=result.error
        )

    def _record_filter_decision(
        self, span, pod: Pod, node_names, failed: Dict[str, str], codes
    ) -> None:
        """One gas_filter decision record + exact per-reason-class
        filtered-node counters (utils/decisions.py)."""
        log = decisions.DECISIONS
        if not log.enabled:
            return
        reason_counts: Dict[int, int] = {}
        for code in codes:
            if code != decisions.CODE_ELIGIBLE:
                reason_counts[code] = reason_counts.get(code, 0) + 1
        log.record_filter(
            verb="gas_filter",
            request_id=getattr(span, "trace_id", ""),
            pod_namespace=pod.namespace,
            pod_name=pod.name,
            policy="gas",
            path=str(span.attrs.get("path", "")),
            candidates=len(node_names),
            filtered=len(failed),
            violating=failed,
            violating_scope="request",
            reason_counts=reason_counts,
        )

    # -- scheduling core (scheduler.go:277-338) ---------------------------------

    def _run_scheduling_logic(self, pod: Pod, node_name: str) -> str:
        """Pick cards for every container of ``pod`` on ``node_name``;
        returns the annotation string, raises if the pod won't fit.  Does
        not mutate booked state."""
        node = self.cache.fetch_node(node_name)
        gpus = get_node_gpu_list(node)
        if not gpus:
            klog.warning("Node %s GPUs have vanished", node_name)
            raise NoGPUsError("will not fit")
        per_gpu_capacity = get_per_gpu_resource_capacity(node, len(gpus))
        used = self.cache.get_node_resource_status(node_name)
        gpu_set = set(gpus)
        for gpu in gpus:  # empty maps for unused cards (:269-275)
            used.setdefault(gpu, ResourceMap())
        annotation_parts: List[str] = []
        for i, request in enumerate(container_requests(pod)):
            cards = self._cards_for_container_request(
                request, per_gpu_capacity, node_name, pod.name, used, gpu_set
            )
            annotation_parts.append(",".join(cards))
        return "|".join(annotation_parts)

    def _cards_for_container_request(
        self,
        container_request: ResourceMap,
        per_gpu_capacity: ResourceMap,
        node_name: str,
        pod_name: str,
        used: NodeResources,
        gpu_set,
    ) -> List[str]:
        """First-fit card pick per requested GPU (scheduler.go:200-257);
        mutates ``used`` (the caller's scratch copy) as it books."""
        if not container_request:
            return []
        per_gpu_request, num_i915 = get_per_gpu_resource_request(container_request)
        cards: List[str] = []
        for _ in range(num_i915):
            fitted = False
            for gpu_name in sorted(used):
                if gpu_name not in gpu_set:
                    klog.warning(
                        "node %s gpu %s has vanished", node_name, gpu_name
                    )
                    continue
                if check_resource_capacity(
                    per_gpu_request, per_gpu_capacity, used[gpu_name]
                ):
                    try:
                        used[gpu_name].add_rm(per_gpu_request)
                    except Exception:
                        break
                    fitted = True
                    cards.append(gpu_name)
                    break
            if not fitted:
                klog.v(4).info_s(
                    f"pod {pod_name} will not fit node {node_name}",
                    component="extender",
                )
                raise WontFitError("will not fit")
        return cards

    # -- bind (scheduler.go:385-445) --------------------------------------------

    def _bind_node(self, args: BindingArgs) -> BindingResult:
        try:
            pod = self.cache.fetch_pod(args.pod_namespace, args.pod_name)
        except Exception as exc:
            klog.warning("Pod %s couldn't be read or pod vanished", args.pod_name)
            return BindingResult(error=str(exc))
        with self._rwmutex:
            resources_adjusted = False
            annotation = ""
            try:
                annotation = self._run_scheduling_logic(pod, args.node)
                self.cache.adjust_pod_resources_locked(
                    pod, ADD, annotation, args.node
                )
                resources_adjusted = True
                self._annotate_pod_bind(annotation, pod)
                self.kube_client.bind_pod(
                    args.pod_namespace, args.pod_name, args.pod_uid, args.node
                )
                # outcome feedback: the successful bind closes this pod's
                # open gas_filter decision records (utils/decisions.py)
                decisions.DECISIONS.observe_bind(
                    args.pod_namespace, args.pod_name, args.node
                )
                if self.admission is not None:
                    self.admission.observe_bind(
                        args.pod_namespace, args.pod_name
                    )
                return BindingResult()
            except Exception as exc:
                klog.error("binding failed: %s", exc)
                if resources_adjusted:
                    # roll the booking back (scheduler.go:404-414)
                    try:
                        self.cache.adjust_pod_resources_locked(
                            pod, REMOVE, annotation, args.node
                        )
                    except Exception as rollback_exc:
                        klog.error("rollback failed: %s", rollback_exc)
                return BindingResult(error=str(exc))

    def _annotate_pod_bind(self, annotation: str, pod: Pod) -> None:
        """Write gas-ts + gas-container-cards with a conflict-retry loop
        (scheduler.go:82-119)."""
        pod_copy = pod.deep_copy()
        ts = str(time.time_ns())  # pascheck: allow[clock] -- gas-ts is an externally-visible wall-clock annotation mirroring scheduler.go; nothing replays it
        last_exc: Optional[Exception] = None
        for attempt in range(UPDATE_RETRY_COUNT):
            pod_copy.annotations[TS_ANNOTATION] = ts
            pod_copy.annotations[CARD_ANNOTATION] = annotation
            try:
                self.kube_client.update_pod(pod_copy)
                klog.v(2).info_s(
                    f"Annotated pod {pod.name} with annotation {annotation}",
                    component="extender",
                )
                return
            except ConflictError as exc:
                last_exc = exc
                try:
                    pod_copy = self.kube_client.get_pod(
                        pod_copy.namespace, pod_copy.name
                    )
                except Exception:
                    klog.error("pod refresh failed")
                    break
                klog.error("pod update failed, retrying with refreshed pod")
                # back off before re-applying: a 409 means the API server
                # is under write contention on this object — re-hammering
                # it with zero sleep (the reference behavior) just
                # prolongs the conflict storm
                if attempt + 1 < UPDATE_RETRY_COUNT:
                    self._sleep(
                        self.retry_policy.backoff(
                            attempt + 1, verb="update_pod"
                        )
                    )
            except Exception as exc:
                last_exc = exc
                break
        klog.error(
            "Failed to annotate POD with container cards: %s", last_exc
        )
        raise last_exc if last_exc else RuntimeError("annotate failed")


# -- pure helpers (module-level like the reference) ----------------------------


def get_node_gpu_list(node: Node) -> List[str]:
    """Cards from the ``gpu.intel.com/cards`` label, "card0.card1..."
    (scheduler.go:132-148)."""
    labels = node.get_labels() if node is not None else None
    if not labels or GPU_LIST_LABEL not in labels:
        klog.error("gpulist label not found from node")
        return []
    return labels[GPU_LIST_LABEL].split(".")


def get_node_gpu_resource_capacity(node: Node) -> ResourceMap:
    """Allocatable entries under the gpu.intel.com/ prefix
    (scheduler.go:150-162)."""
    capacity = ResourceMap()
    for name, raw in node.allocatable.items():
        if name.startswith(RESOURCE_PREFIX):
            value, _ok = Quantity(str(raw)).as_int64()
            capacity[name] = value
    return capacity


def get_per_gpu_resource_capacity(node: Node, gpu_count: int) -> ResourceMap:
    """Node capacity divided evenly across cards — homogeneous-GPU
    assumption (scheduler.go:164-178)."""
    if gpu_count == 0:
        return ResourceMap()
    per_gpu = get_node_gpu_resource_capacity(node).new_copy()
    per_gpu.divide(gpu_count)
    return per_gpu


def get_num_i915(container_request: ResourceMap) -> int:
    """(scheduler.go:192-198)"""
    value = container_request.get(GPU_PLUGIN_RESOURCE, 0)
    return value if value > 0 else 0


def get_per_gpu_resource_request(
    container_request: ResourceMap,
) -> Tuple[ResourceMap, int]:
    """Divide the container request evenly across its i915 count
    (scheduler.go:180-190)."""
    per_gpu = container_request.new_copy()
    num_i915 = get_num_i915(container_request)
    if num_i915 > 1:
        per_gpu.divide(num_i915)
    return per_gpu, num_i915


def check_resource_capacity(
    needed: ResourceMap, capacity: ResourceMap, used: ResourceMap
) -> bool:
    """True when every needed resource fits under per-card capacity
    (scheduler.go:341-383): negative need/used fail, missing or non-positive
    capacity fails, int64 overflow of used+need fails."""
    int64_max = 2**63 - 1
    for name, need in needed.items():
        if need < 0:
            klog.error("negative resource request")
            return False
        cap = capacity.get(name)
        if cap is None or cap <= 0:
            klog.v(4).info_s(f" no capacity available for {name}")
            return False
        in_use = used.get(name, 0)
        if in_use < 0:
            klog.error("negative amount of resources in use")
            return False
        if in_use + need > int64_max:  # Go wraparound check (used+need < 0)
            klog.error("resource request overflow error")
            return False
        if cap < in_use + need:
            klog.v(4).info_s(" not enough resources")
            return False
    return True

"""GAS cluster cache: informer/workqueue pipeline maintaining per-node
per-card used resources.

Reference: gpu-aware-scheduling/pkg/gpuscheduler/node_resource_cache.go.
State: ``annotated_pods`` (pod key -> card annotation) and ``node_statuses``
(node -> card -> ResourceMap) (:56-68).  Pod informer events are filtered to
GPU-requesting pods (:146-158) and enqueued as actions (:305-400); a single
worker drains the queue into ``handle_pod`` (:403-449, 493-538) which books
or releases per-card usage via the transactional ``adjust_pod_resources``
(:236-287).  Reads hand out deep copies (:474-491).

Because all durable state derives from pod annotations observed through the
informer, a restarted cache fully reconstructs itself from the API server —
the checkpoint/resume story of the framework (SURVEY §5.4).

Divergence from the reference, on purpose: on podDeleted the stored
annotation is used for the resource release.  The reference passes the
queue item's annotation, which is empty for delete events
(node_resource_cache.go:393-398 builds the item without it, :512 uses it),
so deletions of still-running annotated pods leaked their booking.
"""

from __future__ import annotations

import threading
from enum import Enum
from typing import Dict, Optional

from platform_aware_scheduling_tpu.gas.resource_map import (
    NodeResources,
    ResourceMap,
    ResourceMapError,
)
from platform_aware_scheduling_tpu.gas.utils import (
    CARD_ANNOTATION,
    container_requests,
    has_gpu_resources,
    is_completed_pod,
)
from platform_aware_scheduling_tpu.kube.informer import (
    DeletedFinalStateUnknown,
    Informer,
    ListWatch,
)
from platform_aware_scheduling_tpu.kube.objects import Node, Pod, object_key
from platform_aware_scheduling_tpu.kube.workqueue import WorkQueue
from platform_aware_scheduling_tpu.utils import klog

ADD = True
REMOVE = False
WORKER_WAIT_S = 0.1  # node_resource_cache.go:28
INFORMER_INTERVAL_S = 30.0  # node_resource_cache.go:29


class PodAction(Enum):
    UPDATED = 0
    ADDED = 1
    DELETED = 2
    COMPLETED = 3


class WorkQueueItem:
    __slots__ = ("name", "ns", "annotation", "action", "pod")

    def __init__(self, name, ns, annotation, action, pod):
        self.name = name
        self.ns = ns
        self.annotation = annotation
        self.action = action
        self.pod = pod

    def __hash__(self):  # identity: items are enqueued once each
        return id(self)

    def __eq__(self, other):
        return self is other


class BadArgsError(ValueError):
    """bad args (reference node_resource_cache.go:41)"""


def get_key(pod: Pod) -> str:
    """namespace&name (node_resource_cache.go:451-453)."""
    return f"{pod.namespace}&{pod.name}"


class Cache:
    """All things cached: node/pod listers plus per-card usage accounting
    (reference node_resource_cache.go:49-68)."""

    def __init__(
        self,
        kube_client,
        resync_period_s: float = INFORMER_INTERVAL_S,
        start: bool = True,
    ):
        self.kube_client = kube_client
        self.work_queue = WorkQueue(name="gas_pods")
        self.annotated_pods: Dict[str, str] = {}
        self.node_statuses: Dict[str, NodeResources] = {}
        self._rwmutex = threading.RLock()
        self._stop = threading.Event()
        self._mutation_hooks = []  # fired after booking changes (device mirror)

        self._node_hooks = []  # fired on node add/update/delete (device mirror)
        self._node_informer = Informer(
            ListWatch(
                lambda: (kube_client.list_nodes(), ""),
                lambda rv: (
                    (etype, Node(raw)) for etype, raw in kube_client.watch_nodes()
                ),
                lambda node: node.name,
            ),
            on_add=self._node_event,
            on_update=lambda _old, new: self._node_event(new),
            on_delete=self._node_deleted,
            resync_period=resync_period_s,
            name="gas_nodes",
        )
        self._pod_informer = Informer(
            ListWatch(
                lambda: (kube_client.list_pods(), ""),
                lambda rv: (
                    (etype, Pod(raw)) for etype, raw in kube_client.watch_pods()
                ),
                object_key,
            ),
            on_add=self._add_pod_to_cache,
            on_update=self._update_pod_in_cache,
            on_delete=self._delete_pod_from_cache,
            filter_func=self._filter,
            resync_period=resync_period_s,
            name="gas_pods",
        )
        self._worker: Optional[threading.Thread] = None
        if start:
            self.start()

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        self._node_informer.start()
        self._pod_informer.start()
        self._node_informer.wait_for_cache_sync()
        self._pod_informer.wait_for_cache_sync()
        self._worker = threading.Thread(target=self._worker_run, daemon=True)
        self._worker.start()

    def stop(self) -> None:
        self._stop.set()
        self.work_queue.shut_down()
        self._node_informer.stop()
        self._pod_informer.stop()

    def has_synced(self) -> bool:
        """True once both informers delivered their initial list."""
        return (
            self._node_informer.has_synced()
            and self._pod_informer.has_synced()
        )

    def synced_condition(self):
        """The /readyz condition form of :meth:`has_synced`
        (utils/health.py)."""
        pending = [
            name
            for name, informer in (
                ("nodes", self._node_informer),
                ("pods", self._pod_informer),
            )
            if not informer.has_synced()
        ]
        if pending:
            return False, f"informers not yet synced: {pending}"
        return True, "node + pod informers synced"

    def wait_settled(self, timeout: float = 5.0) -> bool:
        """Test helper: wait until the work queue drains."""
        import time

        deadline = time.monotonic() + timeout  # pascheck: allow[clock] -- test helper polling REAL worker threads; a fake clock would never see them drain
        while time.monotonic() < deadline:
            if len(self.work_queue) == 0:
                return True
            time.sleep(0.01)  # pascheck: allow[clock] -- real-thread poll interval, same boundary as the deadline above
        return False

    # -- node events (device-mirror feed) --------------------------------------

    def _node_event(self, node: Node) -> None:
        for hook in self._node_hooks:
            hook(node)

    def _node_deleted(self, obj) -> None:
        if isinstance(obj, DeletedFinalStateUnknown):
            obj = obj.obj
        for hook in self._node_hooks:
            hook(obj, deleted=True)

    def on_node_change(self, hook) -> None:
        """Register node add/update/delete callback ``hook(node,
        deleted=False)``; replays the currently-cached nodes so a
        late-attaching subscriber starts complete.  Registration + replay
        run serialized against the informer's dispatch, so the replay can
        neither miss a concurrent event nor resurrect a node whose delete
        was already delivered."""

        def register_and_replay():
            self._node_hooks.append(hook)
            for node in self._node_informer.list():
                hook(node)

        self._node_informer.serialized(register_and_replay)

    # -- event plumbing (node_resource_cache.go:146-158, 305-400) --------------

    def _filter(self, obj) -> bool:
        if isinstance(obj, DeletedFinalStateUnknown):
            obj = obj.obj
        if not isinstance(obj, Pod):
            return False
        return has_gpu_resources(obj)

    def _add_pod_to_cache(self, pod: Pod) -> None:
        annotation = pod.get_annotations().get(CARD_ANNOTATION)
        if annotation is None:
            return  # must wait for the annotating update (:313-317)
        self.work_queue.add(
            WorkQueueItem(pod.name, pod.namespace, annotation, PodAction.ADDED, pod)
        )

    def _update_pod_in_cache(self, _old, new: Pod) -> None:
        annotation = new.get_annotations().get(CARD_ANNOTATION)
        if annotation is None:
            return
        action = PodAction.COMPLETED if is_completed_pod(new) else PodAction.UPDATED
        self.work_queue.add(
            WorkQueueItem(new.name, new.namespace, annotation, action, new)
        )

    def _delete_pod_from_cache(self, obj) -> None:
        if isinstance(obj, DeletedFinalStateUnknown):
            obj = obj.obj
        if not isinstance(obj, Pod):
            klog.warning("cannot convert to Pod: %r", obj)
            return
        with self._rwmutex:
            annotated = get_key(obj) in self.annotated_pods
        if not annotated:
            return
        self.work_queue.add(
            WorkQueueItem(obj.name, obj.namespace, "", PodAction.DELETED, obj)
        )

    # -- worker (node_resource_cache.go:403-449) --------------------------------

    def _worker_run(self) -> None:
        while not self._stop.is_set():
            item, quit_ = self.work_queue.get(timeout=WORKER_WAIT_S)
            if quit_:
                return
            if item is None:
                continue
            try:
                self._handle_pod(item)
            except Exception as exc:
                klog.error(
                    "error handling pod %s ns %s: %s", item.name, item.ns, exc
                )
            finally:
                self.work_queue.done(item)
                self.work_queue.forget(item)

    def _handle_pod(self, item: WorkQueueItem) -> None:
        """Book/release one pod's card usage (node_resource_cache.go:493-538)."""
        with self._rwmutex:
            key = get_key(item.pod)
            if item.action in (PodAction.COMPLETED, PodAction.DELETED):
                stored = self.annotated_pods.get(key)
                if stored is not None:
                    annotation = item.annotation or stored
                    self.adjust_pod_resources(
                        item.pod, REMOVE, annotation, item.pod.spec_node_name
                    )
            elif item.action in (PodAction.ADDED, PodAction.UPDATED):
                if key not in self.annotated_pods:
                    self.adjust_pod_resources(
                        item.pod, ADD, item.annotation, item.pod.spec_node_name
                    )
            else:
                raise ValueError("unknown action")

    # -- bookkeeping (node_resource_cache.go:160-287) ----------------------------

    def adjust_pod_resources_locked(
        self, pod: Pod, adj: bool, annotation: str, node_name: str
    ) -> None:
        """Public entry taking the lock (adjustPodResourcesL, :162-171)."""
        with self._rwmutex:
            self.adjust_pod_resources(pod, adj, annotation, node_name)

    def _new_copy_node_status(self, node_name: str) -> NodeResources:
        return {
            card: rm.new_copy()
            for card, rm in self.node_statuses.get(node_name, {}).items()
        }

    def _check_pod_resource_adjustment(
        self, requests, node_name: str, container_cards, adj: bool
    ) -> None:
        """Dry-run the arithmetic on a scratch copy; raise if any step would
        fail so the real pass is all-or-nothing (:190-232)."""
        if len(requests) != len(container_cards) or not node_name:
            klog.error(
                "bad args, node %s pod creqs %s ccards %s",
                node_name,
                requests,
                container_cards,
            )
            raise BadArgsError("bad args")
        scratch = self._new_copy_node_status(node_name)
        for request, cards_csv in zip(requests, container_cards):
            card_names = cards_csv.split(",")
            if card_names and cards_csv:
                per_card = request.new_copy()
                per_card.divide(len(card_names))
                for card in card_names:
                    rm = scratch.setdefault(card, ResourceMap())
                    if adj:
                        rm.add_rm(per_card)
                    else:
                        rm.subtract_rm(per_card)

    def adjust_pod_resources(
        self, pod: Pod, adj: bool, annotation: str, node_name: str
    ) -> None:
        """Transactional booking under the held lock (:236-287)."""
        requests = container_requests(pod)
        container_cards = annotation.split("|")
        self._check_pod_resource_adjustment(
            requests, node_name, container_cards, adj
        )
        for request, cards_csv in zip(requests, container_cards):
            card_names = cards_csv.split(",")
            if card_names and cards_csv:
                request.divide(len(card_names))
                node_res = self.node_statuses.setdefault(node_name, {})
                for card in card_names:
                    rm = node_res.setdefault(card, ResourceMap())
                    if adj:
                        rm.add_rm(request)
                    else:
                        rm.subtract_rm(request)
        if adj:
            self.annotated_pods[get_key(pod)] = annotation
        else:
            self.annotated_pods.pop(get_key(pod), None)
        for hook in self._mutation_hooks:
            hook(node_name)

    # -- reads (node_resource_cache.go:455-491) ----------------------------------

    def fetch_node(self, node_name: str) -> Node:
        node = self._node_informer.get(node_name)
        if node is None:
            raise KeyError(f"node {node_name} not found")
        return node

    def fetch_pod(self, namespace: str, name: str) -> Pod:
        pod = self._pod_informer.get(f"{namespace}&{name}")
        if pod is None:
            raise KeyError(f"pod {namespace}/{name} not found")
        return pod.deep_copy()

    def get_node_resource_status(self, node_name: str) -> NodeResources:
        """Deep copy of the per-card usage for one node (:474-491)."""
        with self._rwmutex:
            return self._new_copy_node_status(node_name)

    def on_booking_change(self, hook) -> None:
        """Register a callback fired (with the node name, lock held) after a
        successful booking change — feeds the device usage mirror.

        Replay of already-booked nodes and registration happen under one
        ``_rwmutex`` hold: hooks always run in cache-lock → subscriber-lock
        order (both here and from ``adjust_pod_resources``), so a subscriber
        taking its own lock inside the hook cannot deadlock against the
        worker, and no booking between replay and registration is missed."""
        with self._rwmutex:
            for node_name in self.node_statuses:
                hook(node_name)
            self._mutation_hooks.append(hook)

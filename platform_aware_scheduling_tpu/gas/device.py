"""Device side of GAS: persistent usage mirror + request staging for the
batched binpack kernel (ops/binpack.py).

:class:`GASUsageMirror` is the GAS analog of the TAS TensorStateMirror
(SURVEY §7 step 5): it subscribes to the cluster cache's booking hook and
the node informer events and keeps ``[nodes, cards, resources]`` usage /
capacity tensors current incrementally — so a Filter request only stages
its (tiny) per-container request tensors and gathers candidate rows on
device, instead of re-walking every node's resource maps in Python.

Lanes are interned append-only; the first-fit name order the reference
iterates in (scheduler.go:216-224) is carried as an explicit
``card_order`` rank tensor.  All values are exact int64 (split hi/lo).

:class:`DeviceBinpacker` answers one pod's fit across many nodes in one
XLA pass, through the mirror when one is attached (the hot path) or by
per-request staging otherwise (also the correctness control in tests).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from platform_aware_scheduling_tpu.gas import scheduler as gas_logic
from platform_aware_scheduling_tpu.gas.utils import container_requests
from platform_aware_scheduling_tpu.kube.objects import Node, Pod
from platform_aware_scheduling_tpu.ops import i64
from platform_aware_scheduling_tpu.ops.binpack import (
    BinpackNodeState,
    BinpackRequest,
    binpack_kernel,
)
from platform_aware_scheduling_tpu.utils import decisions

import jax.numpy as jnp

MIN_NODES = 16
MIN_CARDS = 4
MIN_RESOURCES = 4
MIN_CONTAINERS = 2
MIN_GPUS = 2


def _bucket(n: int, minimum: int) -> int:
    size = minimum
    while size < n:
        size *= 2
    return size


class GASUsageMirror:
    """Incrementally-synced device tensors of per-card usage + capacity."""

    def __init__(self, cache):
        self.cache = cache
        self._lock = threading.RLock()
        self._node_index: Dict[str, int] = {}
        self._res_index: Dict[str, int] = {}
        self._card_index: List[Dict[str, int]] = []  # per node row
        n, c, r = MIN_NODES, MIN_CARDS, MIN_RESOURCES
        self._used = np.zeros((n, c, r), dtype=np.int64)
        self._cap = np.zeros((n, r), dtype=np.int64)
        self._cap_present = np.zeros((n, r), dtype=bool)
        self._card_valid = np.zeros((n, c), dtype=bool)
        self._card_real = np.zeros((n, c), dtype=bool)
        self._card_order = np.full((n, c), 2**30, dtype=np.int32)
        self._has_gpus = np.zeros(n, dtype=bool)
        self._known = np.zeros(n, dtype=bool)
        self._version = 0
        self._device: Optional[Tuple[int, BinpackNodeState]] = None
        cache.on_node_change(self.on_node_change)  # replays cached nodes
        # replays booked nodes + registers atomically under the cache lock,
        # preserving cache→mirror lock order (no ABBA window against the
        # cache worker firing the hook mid-construction)
        cache.on_booking_change(self.on_booking_change)

    # -- interning -------------------------------------------------------------

    def _grow(self, n=None, c=None, r=None) -> None:
        cur_n, cur_c, cur_r = self._used.shape
        new_n = _bucket(n or cur_n, cur_n)
        new_c = _bucket(c or cur_c, cur_c)
        new_r = _bucket(r or cur_r, cur_r)
        if (new_n, new_c, new_r) == (cur_n, cur_c, cur_r):
            return
        pad3 = ((0, new_n - cur_n), (0, new_c - cur_c), (0, new_r - cur_r))
        self._used = np.pad(self._used, pad3)
        self._cap = np.pad(self._cap, (pad3[0], pad3[2]))
        self._cap_present = np.pad(self._cap_present, (pad3[0], pad3[2]))
        self._card_valid = np.pad(self._card_valid, (pad3[0], pad3[1]))
        self._card_real = np.pad(self._card_real, (pad3[0], pad3[1]))
        self._card_order = np.pad(
            self._card_order, (pad3[0], pad3[1]), constant_values=2**30
        )
        self._has_gpus = np.pad(self._has_gpus, pad3[0])
        self._known = np.pad(self._known, pad3[0])

    def _intern_node(self, name: str) -> int:
        row = self._node_index.get(name)
        if row is None:
            row = len(self._node_index)
            self._grow(n=row + 1)
            self._node_index[name] = row
            self._card_index.append({})
        return row

    def _intern_resource(self, name: str) -> int:
        idx = self._res_index.get(name)
        if idx is None:
            idx = len(self._res_index)
            self._grow(r=idx + 1)
            self._res_index[name] = idx
            # growing the resource axis invalidates the memoized snapshot:
            # a request interning a never-seen resource between cluster
            # events would otherwise get a state whose r_pad is too small
            # for the index this just handed out (IndexError in
            # stage_request until the next event bumped the version)
            self._version += 1
        return idx

    def _intern_card(self, row: int, card: str) -> int:
        cards = self._card_index[row]
        lane = cards.get(card)
        if lane is None:
            lane = len(cards)
            self._grow(c=lane + 1)
            cards[card] = lane
            self._card_real[row, lane] = True
            # first-fit order = rank among sorted names of this node's lanes
            for rank, name in enumerate(sorted(cards)):
                self._card_order[row, cards[name]] = rank
        return lane

    # -- event hooks -----------------------------------------------------------

    def on_node_change(self, node, deleted: bool = False) -> None:
        """Node added/updated/deleted: restage capacity + card set."""
        with self._lock:
            row = self._intern_node(node.name)
            if deleted:
                self._known[row] = False
                self._version += 1
                return
            self._known[row] = True
            gpus = gas_logic.get_node_gpu_list(node)
            self._has_gpus[row] = bool(gpus)
            capacity = gas_logic.get_per_gpu_resource_capacity(node, len(gpus))
            self._cap[row, :] = 0
            self._cap_present[row, :] = False
            for name, value in capacity.items():
                idx = self._intern_resource(name)
                self._cap[row, idx] = value
                self._cap_present[row, idx] = True
            gpu_set = set(gpus)
            for card in gpus:
                self._intern_card(row, card)
            for card, lane in self._card_index[row].items():
                self._card_valid[row, lane] = card in gpu_set
            self._version += 1

    def on_booking_change(self, node_name: str) -> None:
        """Booking changed on one node: restage its used tensor row.
        Called with the cache lock held, so reads are consistent."""
        with self._lock:
            row = self._intern_node(node_name)
            used = self.cache.get_node_resource_status(node_name)
            self._used[row, :, :] = 0
            for card, rm in used.items():
                lane = self._intern_card(row, card)
                for name, value in rm.items():
                    idx = self._intern_resource(name)
                    self._used[row, lane, idx] = value
            self._version += 1

    # -- reads -----------------------------------------------------------------

    def resource_index(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._res_index)

    def snapshot(self):
        """(device state over ALL interned rows, node_index, flags) — device
        arrays memoized per version."""
        with self._lock:
            if self._device is None or self._device[0] != self._version:
                used_hi, used_lo = i64.split_int64_np(self._used)
                cap_hi, cap_lo = i64.split_int64_np(self._cap)
                state = BinpackNodeState(
                    used=i64.I64(hi=jnp.asarray(used_hi), lo=jnp.asarray(used_lo)),
                    capacity=i64.I64(hi=jnp.asarray(cap_hi), lo=jnp.asarray(cap_lo)),
                    cap_present=jnp.asarray(self._cap_present.copy()),
                    card_valid=jnp.asarray(self._card_valid.copy()),
                    card_real=jnp.asarray(self._card_real.copy()),
                    card_order=jnp.asarray(self._card_order.copy()),
                )
                self._device = (self._version, state)
            return (
                self._device[1],
                dict(self._node_index),
                self._known.copy(),
                self._has_gpus.copy(),
                dict(self._res_index),
            )


def stage_request(
    requests, shares, resources_index: Dict[str, int], r_pad: int
) -> Tuple[BinpackRequest, int]:
    """Build the padded per-container request tensors."""
    t_pad = _bucket(len(requests), MIN_CONTAINERS)
    max_gpus = max((k for _, k in shares), default=0)
    k_pad = _bucket(max(max_gpus, 1), MIN_GPUS)
    need = np.zeros((t_pad, r_pad), dtype=np.int64)
    need_active = np.zeros((t_pad, r_pad), dtype=bool)
    num_gpus = np.zeros(t_pad, dtype=np.int32)
    container_active = np.zeros(t_pad, dtype=bool)
    for t, (per_gpu, k) in enumerate(shares):
        container_active[t] = True
        num_gpus[t] = k
        for name, value in per_gpu.items():
            idx = resources_index[name]
            need[t, idx] = value
            need_active[t, idx] = True
    need_hi, need_lo = i64.split_int64_np(need)
    return (
        BinpackRequest(
            need=i64.I64(hi=jnp.asarray(need_hi), lo=jnp.asarray(need_lo)),
            need_active=jnp.asarray(need_active),
            num_gpus=jnp.asarray(num_gpus),
            container_active=jnp.asarray(container_active),
        ),
        k_pad,
    )


class DeviceBinpacker:
    """Evaluates one pod's fit against many nodes in one XLA pass.

    The mirror path amortizes the device dispatch across a scheduling
    burst: kube-scheduler filters one pod per request, but the pods of a
    deployment share a template, and the mirror state only changes when
    a booking/node event lands — so fits are cached per (state version,
    request signature) over ALL interned rows, and a burst of filter
    calls costs ONE kernel dispatch plus row lookups (the GAS analog of
    the TAS fastpath's precomputed rankings; the reference instead walks
    every node per request under its global lock, scheduler.go:463-473).
    """

    FITS_CACHE_SIZE = 8

    def __init__(self, cache, use_mirror: bool = True):
        self.cache = cache
        self.mirror = GASUsageMirror(cache) if use_mirror else None
        self._fits_lock = threading.Lock()
        # MRU [state, signature, fits-over-all-rows]; keyed by the state
        # OBJECT identity (snapshot memoizes one state per mirror version,
        # so identity == version) and the pod's request signature
        self._fits_cache: List[list] = []

    def batch_fit(
        self,
        pod: Pod,
        node_names: Sequence[str],
        with_reasons: bool = False,
    ) -> Optional[List[bool]]:
        """Per-node fit verdicts, or None when the pod has no per-card
        demand (the host loop decides cheaply).  With ``with_reasons``
        the return is ``(fits, codes)`` where codes carry the compact
        decision taxonomy per node (utils/decisions.py): 0 fit,
        gas_unknown_node / gas_no_gpus for the pre-failed lanes, and
        gas_capacity when the binpack kernel said no — the classes the
        host loop's typed exceptions produce identically."""
        requests = container_requests(pod)
        shares = [gas_logic.get_per_gpu_resource_request(req) for req in requests]
        max_gpus = max((k for _, k in shares), default=0)
        resources = sorted({name for req in requests for name in req})
        if not resources or max_gpus == 0:
            # no per-card demand: every readable node with GPUs fits, which
            # the host loop decides cheaply — no point shipping tensors
            return None
        if self.mirror is not None:
            fits, codes = self._fit_mirror(requests, shares, resources, node_names)
        else:
            fits, codes = self._fit_staged(requests, shares, resources, node_names)
        return (fits, codes) if with_reasons else fits

    # -- persistent-mirror path ------------------------------------------------

    def _all_rows_fits(self, state, signature, compute) -> np.ndarray:
        """fits over ALL interned rows for this (state, request template),
        served from the MRU cache when the burst repeats the template;
        ``compute`` runs only on a miss (a hit skips request staging and
        the kernel entirely)."""
        with self._fits_lock:
            for idx, entry in enumerate(self._fits_cache):
                if entry[0] is state and entry[1] == signature:
                    if idx:
                        self._fits_cache.insert(0, self._fits_cache.pop(idx))
                    return entry[2]
        fits = compute()
        # purge relative to the mirror's CURRENT memoized state, not this
        # call's: a straggler that snapshotted a superseded state must not
        # evict fresh entries or insert one that can never hit again
        # (superseded-state entries would only pin full-cluster device
        # arrays; snapshot returns ONE state object per mirror version)
        with self.mirror._lock:
            dev = self.mirror._device
            current = dev[1] if dev is not None else state
        with self._fits_lock:
            self._fits_cache = [
                entry for entry in self._fits_cache if entry[0] is current
            ]
            if state is current:
                self._fits_cache.insert(0, [state, signature, fits])
                del self._fits_cache[self.FITS_CACHE_SIZE:]
        return fits

    def _fit_mirror(self, requests, shares, resources, node_names):
        mirror = self.mirror
        with mirror._lock:
            for name in resources:  # unknown request resources: intern (all-absent)
                mirror._intern_resource(name)
            state, node_index, known, has_gpus, res_index = mirror.snapshot()
        max_gpus = max((k for _, k in shares), default=0)
        k_pad = _bucket(max(max_gpus, 1), MIN_GPUS)
        signature = (
            tuple(
                (tuple(sorted(per_gpu.items())), k) for per_gpu, k in shares
            ),
            k_pad,
        )

        def compute() -> np.ndarray:
            r_pad = state.capacity.hi.shape[-1]
            request, staged_k_pad = stage_request(
                requests, shares, res_index, r_pad
            )
            return np.asarray(
                binpack_kernel(state, request, staged_k_pad).fits
            )

        fits_all = self._all_rows_fits(state, signature, compute)
        out = [False] * len(node_names)
        codes = [decisions.CODE_GAS_CAPACITY] * len(node_names)
        for pos, name in enumerate(node_names):
            row = node_index.get(name)
            if row is None or not known[row]:
                codes[pos] = decisions.CODE_GAS_UNKNOWN_NODE
                continue  # pre-failed
            if not has_gpus[row]:
                codes[pos] = decisions.CODE_GAS_NO_GPUS
                continue
            out[pos] = bool(fits_all[row])
            if out[pos]:
                codes[pos] = decisions.CODE_ELIGIBLE
        return out, codes

    # -- per-request staging path (control) ------------------------------------

    def _fit_staged(self, requests, shares, resources, node_names):
        r_pad = _bucket(len(resources), MIN_RESOURCES)
        res_index = {name: i for i, name in enumerate(resources)}
        request, k_pad = stage_request(requests, shares, res_index, r_pad)

        staged = []
        out = [False] * len(node_names)
        codes = [decisions.CODE_GAS_CAPACITY] * len(node_names)
        max_cards = 1
        for pos, name in enumerate(node_names):
            try:
                node = self.cache.fetch_node(name)
            except Exception:
                codes[pos] = decisions.CODE_GAS_UNKNOWN_NODE
                continue
            gpus = gas_logic.get_node_gpu_list(node)
            if not gpus:
                codes[pos] = decisions.CODE_GAS_NO_GPUS
                continue
            capacity = gas_logic.get_per_gpu_resource_capacity(node, len(gpus))
            used = self.cache.get_node_resource_status(name)
            cards = sorted(set(gpus) | set(used))
            max_cards = max(max_cards, len(cards))
            staged.append((pos, cards, capacity, used, set(gpus)))
        if not staged:
            return out, codes

        n = len(staged)
        c_pad = _bucket(max_cards, MIN_CARDS)
        used_np = np.zeros((n, c_pad, r_pad), dtype=np.int64)
        cap_np = np.zeros((n, r_pad), dtype=np.int64)
        cap_present = np.zeros((n, r_pad), dtype=bool)
        card_valid = np.zeros((n, c_pad), dtype=bool)
        card_real = np.zeros((n, c_pad), dtype=bool)
        card_order = np.full((n, c_pad), 2**30, dtype=np.int32)
        for row, (_pos, cards, capacity, used, gpu_set) in enumerate(staged):
            for name, value in capacity.items():
                idx = res_index.get(name)
                if idx is not None:
                    cap_np[row, idx] = value
                    cap_present[row, idx] = True
            for ci, card in enumerate(cards):  # already name-sorted
                card_real[row, ci] = True
                card_valid[row, ci] = card in gpu_set
                card_order[row, ci] = ci
                for name, value in used.get(card, {}).items():
                    idx = res_index.get(name)
                    if idx is not None:
                        used_np[row, ci, idx] = value

        used_hi, used_lo = i64.split_int64_np(used_np)
        cap_hi, cap_lo = i64.split_int64_np(cap_np)
        state = BinpackNodeState(
            used=i64.I64(hi=jnp.asarray(used_hi), lo=jnp.asarray(used_lo)),
            capacity=i64.I64(hi=jnp.asarray(cap_hi), lo=jnp.asarray(cap_lo)),
            cap_present=jnp.asarray(cap_present),
            card_valid=jnp.asarray(card_valid),
            card_real=jnp.asarray(card_real),
            card_order=jnp.asarray(card_order),
        )
        result = binpack_kernel(state, request, k_pad)
        fits_np = np.asarray(result.fits)
        for row, (pos, *_rest) in enumerate(staged):
            out[pos] = bool(fits_np[row])
            if out[pos]:
                codes[pos] = decisions.CODE_ELIGIBLE
        return out, codes

"""Staging layer between the GAS cache and the batched binpack kernel.

Builds the padded ``[nodes, cards, resources]`` tensors for one Filter
request and runs ops/binpack.py.  Padding uses power-of-two buckets per
axis so XLA recompiles per bucket, never per request (same recompile-
avoidance strategy as the TAS mirror, SURVEY §7 hard parts).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from platform_aware_scheduling_tpu.gas import scheduler as gas_logic
from platform_aware_scheduling_tpu.gas.utils import container_requests
from platform_aware_scheduling_tpu.kube.objects import Pod
from platform_aware_scheduling_tpu.ops import i64
from platform_aware_scheduling_tpu.ops.binpack import (
    BinpackNodeState,
    BinpackRequest,
    binpack_kernel,
)

import jax.numpy as jnp


def _bucket(n: int, minimum: int) -> int:
    size = minimum
    while size < n:
        size *= 2
    return size


class DeviceBinpacker:
    """Evaluates one pod's fit against many nodes in one XLA pass."""

    def __init__(self, cache):
        self.cache = cache

    def batch_fit(self, pod: Pod, node_names: Sequence[str]) -> Optional[List[bool]]:
        requests = container_requests(pod)
        shares = [
            gas_logic.get_per_gpu_resource_request(req) for req in requests
        ]
        max_gpus = max((k for _, k in shares), default=0)
        resources = sorted({name for req in requests for name in req})
        if not resources or max_gpus == 0:
            # no per-card demand: every readable node with GPUs fits, which
            # the host loop decides cheaply — no point shipping tensors
            return None

        t_pad = _bucket(len(requests), 2)
        r_pad = _bucket(len(resources), 4)
        k_pad = _bucket(max_gpus, 2)
        res_index = {name: i for i, name in enumerate(resources)}

        need = np.zeros((t_pad, r_pad), dtype=np.int64)
        need_active = np.zeros((t_pad, r_pad), dtype=bool)
        num_gpus = np.zeros(t_pad, dtype=np.int32)
        container_active = np.zeros(t_pad, dtype=bool)
        for t, ((per_gpu, k), req) in enumerate(zip(shares, requests)):
            container_active[t] = True
            num_gpus[t] = k
            for name, value in per_gpu.items():
                need[t, res_index[name]] = value
                need_active[t, res_index[name]] = True

        # per-node staging; nodes that fail before card logic are pre-failed
        staged = []  # (position, cards, capacity_map, used_map, gpu_set)
        prefail = np.zeros(len(node_names), dtype=bool)
        max_cards = 1
        for pos, name in enumerate(node_names):
            try:
                node = self.cache.fetch_node(name)
            except Exception:
                prefail[pos] = True
                continue
            gpus = gas_logic.get_node_gpu_list(node)
            if not gpus:
                prefail[pos] = True
                continue
            capacity = gas_logic.get_per_gpu_resource_capacity(node, len(gpus))
            used = self.cache.get_node_resource_status(name)
            cards = sorted(set(gpus) | set(used))
            max_cards = max(max_cards, len(cards))
            staged.append((pos, cards, capacity, used, set(gpus)))

        if not staged:
            return [False] * len(node_names)

        n = len(staged)
        c_pad = _bucket(max_cards, 4)
        used_np = np.zeros((n, c_pad, r_pad), dtype=np.int64)
        cap_np = np.zeros((n, r_pad), dtype=np.int64)
        cap_present = np.zeros((n, r_pad), dtype=bool)
        card_valid = np.zeros((n, c_pad), dtype=bool)
        card_real = np.zeros((n, c_pad), dtype=bool)
        for row, (_pos, cards, capacity, used, gpu_set) in enumerate(staged):
            for name, value in capacity.items():
                idx = res_index.get(name)
                if idx is not None:
                    cap_np[row, idx] = value
                    cap_present[row, idx] = True
            for ci, card in enumerate(cards):
                card_real[row, ci] = True
                card_valid[row, ci] = card in gpu_set
                for name, value in used.get(card, {}).items():
                    idx = res_index.get(name)
                    if idx is not None:
                        used_np[row, ci, idx] = value

        used_hi, used_lo = i64.split_int64_np(used_np)
        cap_hi, cap_lo = i64.split_int64_np(cap_np)
        need_hi, need_lo = i64.split_int64_np(need)
        state = BinpackNodeState(
            used=i64.I64(hi=jnp.asarray(used_hi), lo=jnp.asarray(used_lo)),
            capacity=i64.I64(hi=jnp.asarray(cap_hi), lo=jnp.asarray(cap_lo)),
            cap_present=jnp.asarray(cap_present),
            card_valid=jnp.asarray(card_valid),
            card_real=jnp.asarray(card_real),
        )
        request = BinpackRequest(
            need=i64.I64(hi=jnp.asarray(need_hi), lo=jnp.asarray(need_lo)),
            need_active=jnp.asarray(need_active),
            num_gpus=jnp.asarray(num_gpus),
            container_active=jnp.asarray(container_active),
        )
        result = binpack_kernel(state, request, k_pad)
        fits_np = np.asarray(result.fits)
        out = [False] * len(node_names)
        for row, (pos, *_rest) in enumerate(staged):
            out[pos] = bool(fits_np[row])
        return out

"""Solve observatory: per-stage device-solve attribution + refresh churn
(docs/observability.md "Solve observatory").

The device solve has sat at ~1.3 ms since BENCH_r01 while everything
around it got 4x faster, and ROADMAP item 4's incremental solve cannot
be designed — or gated — without knowing WHERE those microseconds go and
HOW MUCH of the world actually changes per refresh.  Neither was
measured: the spans watch the wire, the SLO engine watches verdicts, the
event spine watches control flow, and all of them treat the solve as one
opaque box between "request in" and "bytes out".

This module opens the box, along two axes:

  * **stage attribution** — every instrumented solve (ranking pass,
    batched warm, filter-explain pass, batch replan, warm pass) is
    timed per stage with marks at the pipeline's natural seams:

      ``snapshot``   host-side staging: numpy copies, i64 hi/lo split,
                     pending-set assembly (ops/state._view_locked,
                     tas/planner.replan)
      ``transfer``   host->device upload (``jnp.asarray`` conversions)
      ``compile``    XLA trace+lower+compile, attributed when the
                     watched kernel's jit cache grew during the call
      ``execute``    device execution, timed across
                     ``block_until_ready`` so dispatch overlap cannot
                     hide it
      ``readback``   device->host (``np.asarray``, scalar ``int()``)
      ``encode``     rank slicing, reason decoding, skeleton renders

    Samples land in a bounded ring (``/debug/solve`` serves the tail)
    and in ``pas_solve_stage_us{stage}`` histograms.  The timer records
    the measured end-to-end total alongside the marks, so the ring
    itself proves the attribution is exhaustive (stages sum to the
    total; gated at 10% by tests/test_solveobs.py).

  * **refresh churn** — the mirror counts, per metric write, how many
    node columns actually changed (first sighting of a metric counts
    every present column — to a cold solver the whole row is news; a
    byte-identical refresh counts zero; a delete counts the columns it
    tore down).  Each refresh pass flushes the per-metric counts into
    ``pas_state_churn_rows{metric}`` / ``pas_state_churn_fraction``
    histograms, publishes a ``kind="churn"`` event into the causal
    spine (so ``/debug/explain`` can say "the world changed under
    you"), and — when a flight recorder is wired — exports the
    anonymized pass shape so replayed captures carry production churn.
    This is the delta-aware staging groundwork ROADMAP item 4 calls
    for: the measured steady-state fraction bounds what an incremental
    upload could save.

Off by default.  The whole subsystem hangs off one module-global slot
(``ACTIVE``); every instrumented site reads it once and proceeds
untouched when it is None, so the off path stays wire byte-identical
(pinned by tests/test_solveobs.py) and costs one attribute load.  The
exposition provider registered in ``trace.EXTRA_PROVIDERS`` returns ""
while disabled — no ``pas_solve_*``/``pas_state_churn_*`` families leak
into /metrics until an observatory is enabled (the flight recorder's
off-path convention).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from platform_aware_scheduling_tpu.utils import trace
from platform_aware_scheduling_tpu.utils.tracing import CounterSet

#: the stage vocabulary, in pipeline order (docs/observability.md table)
STAGES = ("snapshot", "transfer", "compile", "execute", "readback", "encode")

#: stage-latency bucket bounds in MICROSECONDS — the solve lives in the
#: 10 us..10 ms band, far below tracing.BUCKETS' seconds-scale grid
STAGE_BOUNDS_US = (
    5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 25000.0, 100000.0,
)

#: changed-row-count bounds: zero is its own bucket on purpose — the
#: steady-state question is "how often does a refresh change NOTHING"
CHURN_ROW_BOUNDS = (
    0.0, 1.0, 2.0, 5.0, 10.0, 25.0, 100.0, 500.0, 2500.0, 10000.0, 50000.0,
)

#: fraction-of-world bounds (changed columns / world size, per metric)
CHURN_FRACTION_BOUNDS = (
    0.0, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0,
)

DEFAULT_CAPACITY = 256

#: passes kept for the steady-state churn summary served by /debug/solve
CHURN_RING = 256


class _Histogram:
    """One labeled cumulative histogram family with a fixed bucket grid.

    ``tracing.LatencyRecorder`` hardcodes the request-latency seconds
    grid in ``histograms_text``; solve stages live three orders of
    magnitude lower and churn counts aren't latencies at all, so each
    family here carries its own bounds.  NOT thread-safe — callers hold
    the observatory lock."""

    def __init__(self, bounds: Tuple[float, ...]):
        self.bounds = bounds
        # label -> (per-bound counts + [+Inf], sum, count)
        self._series: Dict[str, List] = {}

    def observe(self, label: str, value: float) -> None:
        series = self._series.get(label)
        if series is None:
            series = [[0] * (len(self.bounds) + 1), 0.0, 0]
            self._series[label] = series
        counts, _total, _n = series
        for i, bound in enumerate(self.bounds):
            if value <= bound:
                counts[i] += 1
                break
        else:
            counts[len(self.bounds)] += 1
        series[1] += value
        series[2] += 1

    def quantile(self, label: str, q: float) -> float:
        """Bucket upper-bound estimate of the q-quantile (the bound the
        cumulative count crosses q*n at) — exposition-grade, not exact."""
        series = self._series.get(label)
        if series is None or series[2] == 0:
            return 0.0
        counts, _total, n = series
        target = q * n
        seen = 0
        for i, count in enumerate(counts):
            seen += count
            if seen >= target:
                if i < len(self.bounds):
                    return self.bounds[i]
                break
        return float("inf")

    def summary(self, label: str) -> Dict:
        series = self._series.get(label)
        if series is None or series[2] == 0:
            return {"count": 0, "sum": 0.0, "mean": 0.0, "p50": 0.0, "p99": 0.0}
        _counts, total, n = series
        return {
            "count": n,
            "sum": round(total, 1),
            "mean": round(total / n, 2),
            "p50": self.quantile(label, 0.5),
            "p99": self.quantile(label, 0.99),
        }

    def labels(self) -> List[str]:
        return sorted(self._series)

    def text(self, metric: str, label_name: str, help_text: str) -> str:
        """Valid Prometheus exposition for the family ("" when empty)."""
        if not self._series:
            return ""
        lines = [
            f"# HELP {metric} {help_text}",
            f"# TYPE {metric} histogram",
        ]
        for label in sorted(self._series):
            counts, total, n = self._series[label]
            cumulative = 0
            for bound, count in zip(self.bounds, counts):
                cumulative += count
                le = format(bound, "g")
                lines.append(
                    f'{metric}_bucket{{{label_name}="{label}",le="{le}"}}'
                    f" {cumulative}"
                )
            lines.append(
                f'{metric}_bucket{{{label_name}="{label}",le="+Inf"}} {n}'
            )
            lines.append(
                f'{metric}_sum{{{label_name}="{label}"}} {round(total, 3)}'
            )
            lines.append(f'{metric}_count{{{label_name}="{label}"}} {n}')
        return "\n".join(lines) + "\n"


class SolveTimer:
    """Stage marks for ONE solve.  ``mark(stage)`` attributes the time
    since the previous mark; ``done()`` commits the sample with the
    independently measured end-to-end total (so the ring itself shows
    whether the marks are exhaustive).  Cheap enough to leave inline:
    two clock reads per stage boundary."""

    __slots__ = ("obs", "kind", "stages", "_t0", "_last")

    def __init__(self, obs: "SolveObservatory", kind: str):
        self.obs = obs
        self.kind = kind
        self.stages: Dict[str, float] = {}
        self._t0 = obs.clock()
        self._last = self._t0

    def mark(self, stage: str) -> float:
        """Close the current stage; returns its duration in us."""
        now = self.obs.clock()
        us = (now - self._last) * 1e6
        self._last = now
        self.stages[stage] = self.stages.get(stage, 0.0) + us
        return us

    def done(self, **extra) -> float:
        """Commit the sample; returns the measured total in us."""
        total_us = (self.obs.clock() - self._t0) * 1e6
        self.obs._commit(self.kind, self.stages, total_us, extra)
        return total_us


class SolveObservatory:
    """Bounded per-stage solve rings + refresh-churn accumulation.

    One instance per process while enabled (the ``ACTIVE`` slot); every
    method is thread-safe behind one leaf lock that is never held
    around device work or other subsystems' locks.  ``flight`` is an
    optional FlightRecorder churn passes are exported into."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        clock: Callable[[], float] = time.perf_counter,
    ):
        self.capacity = max(1, int(capacity))
        self.clock = clock
        self._lock = threading.Lock()
        # observatory-local CounterSet, merged into /metrics only while
        # enabled — the flight recorder's off-path convention
        self.counters = CounterSet()
        self.ring: deque = deque(maxlen=self.capacity)
        self._stage_histo = _Histogram(STAGE_BOUNDS_US)
        self._churn_rows = _Histogram(CHURN_ROW_BOUNDS)
        self._churn_fraction = _Histogram(CHURN_FRACTION_BOUNDS)
        self._churn_ring: deque = deque(maxlen=CHURN_RING)
        self._last_pass: Dict = {}
        self.world = 0
        #: optional FlightRecorder (record_churn) — wired by assembly
        self.flight = None
        #: optional TensorStateMirror whose churn accumulator this
        #: observatory drains on each refresh pass
        self.mirror = None

    # -- stage attribution ------------------------------------------------

    def begin(self, kind: str) -> SolveTimer:
        """Start timing one solve of the given pipeline kind."""
        return SolveTimer(self, kind)

    def _commit(
        self, kind: str, stages: Dict[str, float], total_us: float, extra: Dict
    ) -> None:
        sample = {
            "kind": kind,
            "stages": {s: round(us, 1) for s, us in stages.items()},
            "total_us": round(total_us, 1),
        }
        if extra:
            sample.update(extra)
        with self._lock:
            self.ring.append(sample)
            for stage, us in stages.items():
                self._stage_histo.observe(stage, us)
        self.counters.inc("pas_solve_samples_total", labels={"kind": kind})

    # -- refresh churn ----------------------------------------------------

    def flush_refresh_pass(self) -> None:
        """End-of-refresh-pass hook (``cache.on_refresh_pass``): drain
        the mirror's per-metric changed-column counts into the churn
        histograms, publish one spine event, export to the flight
        recorder.  Runs on the telemetry refresh thread; never raises."""
        try:
            self._flush_refresh_pass()
        except Exception as exc:  # never break the refresh thread
            from platform_aware_scheduling_tpu.utils import klog

            klog.error("solve observatory churn flush failed: %r", exc)

    def _flush_refresh_pass(self) -> None:
        mirror = self.mirror
        if mirror is None:
            return
        pending, world = mirror.drain_churn()
        if not pending:
            return
        total = sum(changed for changed, _deleted in pending.values())
        metrics: Dict[str, Dict] = {}
        with self._lock:
            self.world = world
            for metric, (changed, deleted) in sorted(pending.items()):
                fraction = (changed / world) if world > 0 else 0.0
                self._churn_rows.observe(metric, float(changed))
                self._churn_fraction.observe(metric, fraction)
                entry = {"rows": changed, "fraction": round(fraction, 4)}
                if deleted:
                    entry["deleted"] = True
                metrics[metric] = entry
            denom = world * len(pending)
            pass_fraction = (total / denom) if denom > 0 else 0.0
            self._last_pass = {
                "metrics": metrics,
                "total_rows": total,
                "world": world,
                "fraction": round(pass_fraction, 4),
            }
            self._churn_ring.append(pass_fraction)
        self.counters.inc("pas_state_churn_passes_total")
        self.counters.inc("pas_state_churn_rows_changed_total", total)
        self._publish_churn(len(pending), total, world, pass_fraction)
        flight = self.flight
        if flight is not None:
            recorder = getattr(flight, "record_churn", None)
            if recorder is not None:
                recorder(len(pending), total, world, pass_fraction)

    def _publish_churn(
        self, metric_count: int, rows: int, world: int, fraction: float
    ) -> None:
        from platform_aware_scheduling_tpu.utils import events

        events.JOURNAL.publish(
            "churn",
            f"refresh changed {rows} rows across {metric_count} metrics",
            data={
                "rows": rows,
                "metrics": metric_count,
                "world": world,
                "fraction": round(fraction, 4),
            },
        )

    # -- read path --------------------------------------------------------

    def churn_summary(self) -> Dict:
        with self._lock:
            passes = list(self._churn_ring)
            last = dict(self._last_pass)
            world = self.world
        if passes:
            ordered = sorted(passes)
            p50 = ordered[len(ordered) // 2]
            mean = sum(passes) / len(passes)
        else:
            p50 = mean = 0.0
        return {
            "world": world,
            "passes": len(passes),
            "last_pass": last,
            "fraction_mean": round(mean, 4),
            "fraction_p50": round(p50, 4),
        }

    def to_json_dict(self) -> Dict:
        with self._lock:
            recent = list(self.ring)[-32:]
            stages = {
                stage: self._stage_histo.summary(stage)
                for stage in self._stage_histo.labels()
            }
        compiles = {
            watch.name: watch.compile_count for watch in trace.JIT_WATCHES
        }
        return {
            "enabled": True,
            "capacity": self.capacity,
            "samples": int(
                self.counters.get("pas_solve_samples_total", kind="counter")
            ),
            "stages": stages,
            "recent": recent,
            "churn": self.churn_summary(),
            "compiles": compiles,
        }

    def to_json(self) -> bytes:
        """The ``GET /debug/solve`` payload (both front-ends)."""
        return json.dumps(self.to_json_dict()).encode() + b"\n"

    def metrics_text(self) -> str:
        """Exposition for the observatory-local families — the single
        ``trace.EXTRA_PROVIDERS`` entry renders this while enabled."""
        helps = trace.help_texts()
        with self._lock:
            parts = [
                self._stage_histo.text(
                    "pas_solve_stage_us",
                    "stage",
                    helps.get("pas_solve_stage_us", ""),
                ),
                self._churn_rows.text(
                    "pas_state_churn_rows",
                    "metric",
                    helps.get("pas_state_churn_rows", ""),
                ),
                self._churn_fraction.text(
                    "pas_state_churn_fraction",
                    "metric",
                    helps.get("pas_state_churn_fraction", ""),
                ),
            ]
        parts.append(self.counters.prometheus_text(help_texts=helps))
        return "".join(parts)


#: THE off-path gate: every instrumented site reads this once per solve
#: and takes the untouched path when it is None.  Module-global (not an
#: extender attribute) because the pipeline spans layers that never see
#: the extender — ops/state.py, the models, the planner.
ACTIVE: Optional[SolveObservatory] = None


def enable(
    capacity: int = DEFAULT_CAPACITY,
    clock: Callable[[], float] = time.perf_counter,
) -> SolveObservatory:
    """Install (and return) a fresh process-wide observatory."""
    global ACTIVE
    obs = SolveObservatory(capacity=capacity, clock=clock)
    ACTIVE = obs
    return obs


def disable() -> None:
    """Tear the observatory down; instrumented sites revert to the
    untouched path on their next ``ACTIVE`` read."""
    global ACTIVE
    ACTIVE = None


def _provider() -> str:
    obs = ACTIVE
    return obs.metrics_text() if obs is not None else ""


# one provider for the process, registered at import (the gang tracker's
# histogram precedent) — renders "" until an observatory is enabled
trace.EXTRA_PROVIDERS.append(_provider)

"""Ordinal Prioritize scoring: OrderedList + 10-rank as one sort pass.

Reference hot loop (pkg/telemetryscheduler/telemetryscheduler.go:128-149):
read one metric, intersect candidates with the metric map, sort by value
(GreaterThan -> descending, LessThan -> ascending, otherwise input order,
operator.go:30-42), then emit ``Score = 10 - rank`` (``:145`` — ordinal,
goes negative past rank 10).

Device version: one multi-key ``lax.sort`` over (key_hi, key_lo, index)
where invalid lanes (not a candidate / absent from the metric map / padding)
carry a +inf sentinel so they sort last; ranks come back via a scatter of
iota through the sort permutation.  Ties break by node index — deterministic
where the reference's unstable Go sort is arbitrary.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from platform_aware_scheduling_tpu.ops import i64
from platform_aware_scheduling_tpu.ops.rules import (
    OP_GREATER_THAN,
    OP_LESS_THAN,
    RuleSet,
    first_violated_rule,
    violated_nodes,
)
from platform_aware_scheduling_tpu.utils import trace


class PrioritizeResult(NamedTuple):
    scores: jax.Array  # int32 [N] — 10 - rank, valid lanes only
    valid: jax.Array  # bool [N] — candidate ∩ metric-present
    perm: jax.Array  # int32 [N] — node indices in rank order (valid first)
    valid_count: jax.Array  # int32 scalar — number of valid lanes


def _rank_keys(
    value: i64.I64,  # [N] metric values, milli-units
    valid: jax.Array,  # bool [N]
    op_id: jax.Array,  # scalar int32
    index: jax.Array,  # int32 [N] iota
) -> i64.I64:
    """Build the exact-int64 sort key for one rule's ordering.

    GreaterThan: descending by value  -> key = flip(value)
    LessThan:    ascending by value   -> key = value
    other:       input (index) order  -> key = index   (operator.go:40-41)
    Invalid lanes get INT64_MAX so they land after every valid lane; the
    caller's tiebreak additionally orders valid lanes ahead of invalid ones
    on key collision (flip(INT64_MIN) == INT64_MAX).
    """
    flipped = i64.flip(value)
    by_value = i64.select(op_id == OP_GREATER_THAN, flipped, value)
    index_key = i64.I64(hi=jnp.zeros_like(value.hi), lo=index.astype(jnp.uint32))
    sorts_by_value = (op_id == OP_LESS_THAN) | (op_id == OP_GREATER_THAN)
    key = i64.select(sorts_by_value, by_value, index_key)
    return i64.select(valid, key, i64.full_like(key, i64.INT64_MAX))


def ordinal_scores(
    value: i64.I64,  # [N]
    valid: jax.Array,  # bool [N]
    op_id: jax.Array,  # scalar
) -> PrioritizeResult:
    """Scores for one scheduling rule over all (padded) nodes."""
    n = value.hi.shape[-1]
    index = jnp.arange(n, dtype=jnp.int32)
    key = _rank_keys(value, valid, op_id, index)
    # valid lanes win key ties against invalid sentinels; ties between valid
    # lanes break by node index (deterministic where Go's sort is unstable)
    tiebreak = jnp.where(valid, index, index + jnp.int32(n))
    (perm,) = i64.sort_by_key(key, index, tiebreak=tiebreak)
    ranks = jnp.zeros(n, dtype=jnp.int32).at[perm].set(index)
    scores = jnp.int32(10) - ranks
    return PrioritizeResult(
        scores=scores,
        valid=valid,
        perm=perm,
        valid_count=jnp.sum(valid).astype(jnp.int32),
    )


@partial(jax.jit, static_argnames=())
def _prioritize_kernel(
    metric_values: i64.I64,  # [M, N]
    metric_present: jax.Array,  # bool [M, N]
    metric_row: jax.Array,  # scalar int32 — scheduleonmetric rule[0] metric
    op_id: jax.Array,  # scalar int32
    candidate_mask: jax.Array,  # bool [N]
) -> PrioritizeResult:
    """The full Prioritize verb for one pod (telemetryscheduler.go:128-149):
    candidate ∩ metric-present intersection, ordering, ordinal scores."""
    value = i64.I64(
        hi=metric_values.hi[metric_row], lo=metric_values.lo[metric_row]
    )
    valid = candidate_mask & metric_present[metric_row]
    return ordinal_scores(value, valid, op_id)


@jax.jit
def _filter_kernel(
    metric_values: i64.I64,  # [M, N]
    metric_present: jax.Array,  # bool [M, N]
    rules: RuleSet,
    candidate_mask: jax.Array,  # bool [N]
) -> jax.Array:
    """The Filter verb for one pod (telemetryscheduler.go:184-225): a
    candidate passes unless the dontschedule strategy marks it violating.
    Violations are computed over *all* nodes (request-independent, cacheable
    — noted at SURVEY §3.3) and intersected with the candidates here."""
    violating = violated_nodes(metric_values, metric_present, rules)
    return candidate_mask & ~violating


class FilterExplainResult(NamedTuple):
    passing: jax.Array  # bool [N] — candidate & not violating
    first_rule: jax.Array  # int32 [N] — first matching rule index, -1 clean


@jax.jit
def _filter_explain_kernel(
    metric_values: i64.I64,  # [M, N]
    metric_present: jax.Array,  # bool [M, N]
    rules: RuleSet,
    candidate_mask: jax.Array,  # bool [N]
) -> FilterExplainResult:
    """The Filter verb WITH provenance: the same fused violation pass as
    ``_filter_kernel`` plus the per-node first-matching-rule index vector
    — the integer reason code the decision log decodes host-side
    (utils/decisions.py).  One extra argmax over the already-computed
    ``[R, N]`` match mask; the verdict bits are identical to
    ``_filter_kernel`` by construction (both reduce the same
    ``evaluate_rules`` output)."""
    first = first_violated_rule(metric_values, metric_present, rules)
    return FilterExplainResult(
        passing=candidate_mask & (first < 0), first_rule=first
    )


@jax.jit
def _batch_prioritize_kernel(
    metric_values: i64.I64,  # [M, N]
    metric_present: jax.Array,  # bool [M, N]
    metric_row: jax.Array,  # int32 [P] — per-pod rule metric
    op_id: jax.Array,  # int32 [P]
    candidate_mask: jax.Array,  # bool [P, N]
) -> PrioritizeResult:
    """All pending pods at once — the batched form the Go loop cannot do.
    vmap over the pod axis; one XLA program scores P pods x N nodes."""
    return jax.vmap(
        lambda row, op, cand: _prioritize_kernel(
            metric_values, metric_present, row, op, cand
        )
    )(metric_row, op_id, candidate_mask)


# lowering-count shims (utils/trace.py): cache growth past each kernel's
# first compile increments pas_jax_retrace_total — the state-shape bucket
# system (ops/state.py) exists so steady-state serving NEVER recompiles;
# a nonzero retrace counter in production says a shape leaked through.
# The vmap above closes over the unwrapped _prioritize_kernel so tracing
# the batch kernel can't be miscounted as callers' retraces.
prioritize_kernel = trace.watch_jit("prioritize_kernel", _prioritize_kernel)
filter_kernel = trace.watch_jit("filter_kernel", _filter_kernel)
filter_explain_kernel = trace.watch_jit(
    "filter_explain_kernel", _filter_explain_kernel
)
batch_prioritize_kernel = trace.watch_jit(
    "batch_prioritize_kernel", _batch_prioritize_kernel
)

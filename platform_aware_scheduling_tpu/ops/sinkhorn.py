"""Sinkhorn-guided global assignment (BASELINE.json config #5).

Greedy-in-order (ops/assign.py) is protocol-faithful but myopic: pod 0
can take a node that pod 7 needed far more.  This module treats the
pending set as an optimal-transport problem — pods are unit masses, nodes
have integer capacities, utility is the (normalized) score — and runs
entropic-regularized Sinkhorn iterations: pure row/column scaling over a
dense [P, N] kernel matrix, exactly the bandwidth/VPU-shaped work TPUs
eat, ``lax.scan`` over a fixed iteration count, no data-dependent shapes.

The soft transport plan then *guides* the exact greedy kernel: greedy
runs on the plan's log-probabilities instead of raw scores, so the output
is always capacity-feasible and deterministic, but globally coordinated.
Temperature anneals toward the unregularized optimum as ``tau`` shrinks.

This is an additive capability (the reference has nothing like it); the
wire-faithful paths never route through here unless the planner is asked
for ``optimize="sinkhorn"``.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from platform_aware_scheduling_tpu.ops import i64
from platform_aware_scheduling_tpu.ops.assign import (
    AssignResult,
    greedy_assign_kernel,
)

NEG = -1e30

# shared anneal-step default for BOTH the single-chip kernel below and the
# mesh form (parallel/sharded.sharded_sinkhorn_assign): callers comparing
# or swapping the two at their defaults must get the same guidance
# quality (ADVICE r5 #2 — the sharded default of 20 was too few anneal
# steps for contended cases the single-chip default resolves)
DEFAULT_ITERATIONS = 50


class SinkhornResult(NamedTuple):
    assignment: AssignResult
    plan: jax.Array  # f32 [P, N] — the soft transport plan


def _normalize_scores(score: i64.I64, eligible: jax.Array) -> jax.Array:
    """Exact-i64 scores -> per-pod [0, 1] f32 utilities (rank-preserving
    per row up to f32 precision; only guidance quality depends on this,
    never feasibility or determinism of the final assignment)."""
    hi = score.hi.astype(jnp.float32)
    lo = score.lo.astype(jnp.float32)
    value = hi * jnp.float32(2.0**32) + lo
    masked = jnp.where(eligible, value, jnp.inf)
    lo_v = jnp.min(masked, axis=1, keepdims=True)
    masked_hi = jnp.where(eligible, value, -jnp.inf)
    hi_v = jnp.max(masked_hi, axis=1, keepdims=True)
    span = jnp.maximum(hi_v - lo_v, jnp.float32(1.0))
    return jnp.where(eligible, (value - lo_v) / span, 0.0)


@partial(jax.jit, static_argnames=("iterations",))
def sinkhorn_assign_kernel(
    score: i64.I64,  # [P, N] — larger is better
    eligible: jax.Array,  # bool [P, N]
    capacity: jax.Array,  # int32 [N]
    iterations: int = DEFAULT_ITERATIONS,
    tau: float = 0.05,
) -> SinkhornResult:
    """Globally-coordinated assignment: Sinkhorn plan + exact greedy
    rounding.  Always capacity-feasible; deterministic."""
    utility = _normalize_scores(score, eligible)  # [P, N] in [0, 1]
    logits = jnp.where(eligible, utility / jnp.float32(tau), NEG)
    cap_f = capacity.astype(jnp.float32)
    # a pod with no eligible node has logits all ≈ NEG; -row_lse would blow
    # up to ≈ +1e30 and the NEG+1e30 terms cancel to ~0 in col_lse, adding
    # phantom unit mass to every column — pin such rows at NEG so they carry
    # no mass (greedy re-masks eligibility, so feasibility never depended on
    # this, only plan quality for the real pods)
    has_eligible = jnp.any(eligible, axis=1)

    def step(carry, _):
        log_u, log_v = carry
        # rows: each pod places exactly one unit
        row_lse = jax.nn.logsumexp(logits + log_v[None, :], axis=1)
        log_u = jnp.where(has_eligible, -row_lse, NEG)
        # cols: node absorption bounded by capacity (unbalanced OT:
        # only scale DOWN overloaded columns)
        col_lse = jax.nn.logsumexp(logits + log_u[:, None], axis=0)
        log_v = jnp.minimum(
            jnp.log(jnp.maximum(cap_f, 1e-9)) - col_lse, 0.0
        )
        log_v = jnp.where(cap_f > 0, log_v, NEG)
        return (log_u, log_v), None

    p, n = eligible.shape
    init = (jnp.zeros(p, jnp.float32), jnp.zeros(n, jnp.float32))
    (log_u, log_v), _ = jax.lax.scan(step, init, None, length=iterations)
    log_plan = logits + log_u[:, None] + log_v[None, :]
    plan = jnp.where(eligible, jnp.exp(log_plan), 0.0)

    # exact greedy over the plan's log-probabilities: feasibility and
    # tie-breaking exactly as greedy_assign_kernel, coordination from the
    # plan.  Quantize to i64 milli-nats for the exact comparator.
    guide = jnp.where(eligible, log_plan, jnp.float32(NEG))
    # quantize to micro-nats in int32, sign-extend into the i64 limbs
    g_scaled = jnp.clip(guide * jnp.float32(1e6), -2.0e9, 2.0e9).astype(
        jnp.int32
    )
    g_hi = jnp.where(g_scaled < 0, jnp.int32(-1), jnp.int32(0))
    g_lo = jax.lax.bitcast_convert_type(g_scaled, jnp.uint32)
    guide_scores = i64.I64(hi=g_hi, lo=g_lo)
    assignment = greedy_assign_kernel(guide_scores, eligible, capacity)
    return SinkhornResult(assignment=assignment, plan=plan)


def total_utility(score: i64.I64, assignment: jax.Array) -> jax.Array:
    """Sum of normalized utilities of the chosen nodes — the objective used
    to compare solvers in tests/benches."""
    p, n = score.hi.shape
    eligible = jnp.ones((p, n), dtype=bool)
    utility = _normalize_scores(score, eligible)
    picked = jnp.where(
        assignment >= 0,
        jnp.take_along_axis(
            utility, jnp.maximum(assignment, 0)[:, None], axis=1
        )[:, 0],
        0.0,
    )
    return jnp.sum(picked)

"""Host-side tensor mirror of the TAS cache: interning tables + dense
device tensors, updated incrementally by cache mutation hooks.

SURVEY §7 step 2: alongside the exact host cache (tas/cache.py) the mirror
maintains interned node-ID <-> row-index tables, a dense
``[metric_capacity, node_capacity]`` int64-milli metric matrix (split hi/lo
for TPU, see ops/i64.py), per-row presence masks, and compiled per-policy
rule tensors.  Capacities grow by doubling so XLA recompiles only
per-bucket, never per-node — the recompile-avoidance half of the
"dynamic shapes vs XLA" hard part (SURVEY §7).

Fidelity contract: metric values are stored as exact milli-units when the
``Quantity`` converts exactly (utils/quantity.py ``milli_value_exact``);
any inexact value or unknown rule operator marks the affected metric/policy
host-only and the scheduler falls back to the exact host path for requests
touching it.  Device compares/sorts are then bit-identical to
``Quantity.CmpInt64`` / ``OrderedList`` (reference operator.go:13-42).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from platform_aware_scheduling_tpu.ops import i64, solveobs
from platform_aware_scheduling_tpu.ops.rules import OP_IDS, RuleSet
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicy

MIN_NODE_CAPACITY = 64
MIN_METRIC_CAPACITY = 8
RULE_PAD = 8

#: forecast history staging keeps per-metric values inside int32 after a
#: per-row arithmetic right shift.  The budget is WINDOW-AWARE (see
#: history_value_bits): the Holt recursion's per-step sums (level + trend
#: + error) need ~2 bits of headroom over the value range, and the
#: residual accumulator sums up to W-1 absolute errors on top — so the
#: value range must shrink by another ceil(log2 W) bits or a full-window
#: noisy series near the bit ceiling wraps ``acc`` negative in int32
#: (garbage resid/band, identically on both execution paths)
HISTORY_VALUE_BITS = 30


def history_value_bits(window: int) -> int:
    """Max bits of staged value magnitude for ``window`` samples such
    that level/trend/error, the W-1-term residual accumulator, AND the
    band tail ``resid * (1 + h)`` at the clamped max horizon (~2W,
    forecast/engine._steps_now) all stay inside int32 (floored at 8
    bits — milli precision loss past that would be worse than the
    microscopic overflow risk)."""
    return max(8, HISTORY_VALUE_BITS - 2 - max(int(window) - 1, 0).bit_length())


class HistoryTensor(NamedTuple):
    """Dense device staging of the telemetry refresh history
    (tas/cache.AutoUpdatingCache history rings), aligned to one
    DeviceView's ``[metric row, node column]`` universe plus a trailing
    time axis: the last ``W`` refresh samples, oldest first, right-aligned
    at ``W - 1`` (shorter series lead with invalid slots).

    Values are milli-units arithmetic-right-shifted per metric row by
    ``shift[m]`` so every sample fits int32 (ops/forecast.py consumes the
    scaled domain; predictions shift back up host-side).  ``valid`` marks
    real samples — a node absent from a sample, a metric with fewer than
    W samples, and rows/columns outside the view all stay False."""

    values: np.ndarray  # int32 [M, N, W] — milli >> shift[m]
    valid: np.ndarray  # bool [M, N, W]
    shift: np.ndarray  # int64 [M] — per-metric de-scale amount
    last_stamp: np.ndarray  # float64 [M] — newest sample stamp (nan: none)


def build_history_tensor(
    view: "DeviceView",
    history: Dict[str, List[Tuple[float, Dict[str, int]]]],
    window: int,
) -> HistoryTensor:
    """Stage the cache's history rings into the dense ``[M, N, W]`` form
    (see :class:`HistoryTensor`) against ``view``'s interning.  Metrics or
    nodes unknown to the view are dropped — the forecast universe is
    exactly the snapshot the rankings run against."""
    metric_index = view.metric_index or {}
    node_index = view.node_index
    m_cap = view.values.hi.shape[0]
    n_cap = view.node_capacity
    w = int(window)
    values64 = np.zeros((m_cap, n_cap, w), dtype=np.int64)
    valid = np.zeros((m_cap, n_cap, w), dtype=bool)
    last_stamp = np.full(m_cap, np.nan, dtype=np.float64)
    # per-sample scatter via fancy indexing: the column lookup is the only
    # per-node Python left (the refresh thread restages every pass, so the
    # N x W inner work must stay vectorized at 10k-node scale)
    for name, ring in history.items():
        row = metric_index.get(name)
        if row is None or row >= m_cap:
            continue
        samples = ring[-w:]
        base = w - len(samples)
        for j, (stamp, sample) in enumerate(samples):
            if not sample:
                continue
            slot = base + j
            cols = np.fromiter(
                (node_index.get(node, -1) for node in sample),
                dtype=np.int64,
                count=len(sample),
            )
            vals = np.fromiter(
                sample.values(), dtype=np.int64, count=len(sample)
            )
            keep = (cols >= 0) & (cols < n_cap)
            values64[row, cols[keep], slot] = vals[keep]
            valid[row, cols[keep], slot] = True
        if samples:
            last_stamp[row] = samples[-1][0]
    # per-metric de-scale so the largest magnitude fits the window-aware
    # bit budget (residual accumulator headroom, see history_value_bits)
    bits = history_value_bits(w)
    masked = np.where(valid, np.abs(values64), 0)
    max_abs = masked.max(axis=(1, 2))
    shift = np.zeros(m_cap, dtype=np.int64)
    over = max_abs >> np.int64(bits)
    for row in np.nonzero(over)[0]:
        extra = int(max_abs[row]).bit_length() - bits
        shift[row] = extra
    scaled = (values64 >> shift[:, None, None]).astype(np.int32)
    return HistoryTensor(
        values=scaled, valid=valid, shift=shift, last_stamp=last_stamp
    )


def _next_capacity(current: int, needed: int) -> int:
    while current < needed:
        current *= 2
    return current


@dataclass
class CompiledRuleSet:
    """Host (numpy) staging of one strategy's rule list, padded to RULE_PAD
    multiples for stable jit shapes."""

    metric_rows: np.ndarray  # int32 [R_pad]
    op_ids: np.ndarray  # int32 [R_pad]
    targets: np.ndarray  # int64 [R_pad] milli-units
    active: np.ndarray  # bool [R_pad]
    host_only: bool = False  # unknown operator somewhere -> host fallback
    metric_names: Tuple[str, ...] = ()  # for host-only metric checks

    def to_device(self) -> RuleSet:
        t_hi, t_lo = i64.split_int64_np(self.targets)
        return RuleSet(
            metric_row=jnp.asarray(self.metric_rows),
            op_id=jnp.asarray(self.op_ids),
            target=i64.I64(hi=jnp.asarray(t_hi), lo=jnp.asarray(t_lo)),
            active=jnp.asarray(self.active),
        )


@dataclass
class CompiledPolicy:
    """Device-ready view of one TASPolicy's strategies."""

    dontschedule: Optional[CompiledRuleSet] = None
    deschedule: Optional[CompiledRuleSet] = None
    # scheduleonmetric uses only Rules[0] (telemetryscheduler.go:115-124).
    # Unknown operators compile to op_id -1 == index-order ranking, which is
    # within the reference's envelope (Go map order is randomized there), so
    # scheduleonmetric never forces a host fallback.
    scheduleonmetric_row: int = -1
    scheduleonmetric_op: int = -1
    scheduleonmetric_metric: str = ""
    _device_cache: Dict[str, RuleSet] = field(default_factory=dict)

    def device_rules(self, strategy: str) -> Optional[RuleSet]:
        compiled = getattr(self, strategy, None)
        if compiled is None or compiled.host_only:
            return None
        if strategy not in self._device_cache:
            self._device_cache[strategy] = compiled.to_device()
        return self._device_cache[strategy]


class DeviceView:
    """An immutable snapshot handed to kernels: the split metric matrix, the
    presence mask, and the interning tables it was built against.

    Besides the global ``version``, the view carries fine-grained change
    counters so per-version caches invalidate only what actually changed
    under metric churn (every sync period rewrites every metric,
    autoupdating.go:37-59):

      * ``row_versions[r]`` bumps only when metric row ``r``'s content
        changes — a ranking for (row, op) stays valid across other rows'
        updates;
      * ``intern_version`` bumps only when the node interning (and thus
        the name list / response fragments) changes — the encode table
        survives pure value churn.
    """

    def __init__(
        self,
        values: i64.I64,
        present: jnp.ndarray,
        node_names: List[str],
        node_index: Dict[str, int],
        version: int,
        row_versions: Tuple[int, ...] = (),
        intern_version: int = 0,
        values_milli: Optional[np.ndarray] = None,
        metric_index: Optional[Dict[str, int]] = None,
        partition_versions: Optional[Dict[int, int]] = None,
    ):
        self.values = values
        self.present = present
        self.node_names = node_names
        self.node_index = node_index
        self.version = version
        self.row_versions = row_versions
        self.intern_version = intern_version
        # host-readable copy of the milli-unit matrix, for decision
        # provenance: decoding a device rule-index vector into "metric
        # cpu=93 > threshold 80" needs the observed values WITHOUT a
        # device readback (utils/decisions.py).  None in synthetic views
        # built without it — reasons then omit the observed value.
        self.values_milli = values_milli
        # metric name -> row, so row-aligned overlays (the forecast
        # history tensor, ops/forecast.py) can be built against this
        # exact snapshot.  None in synthetic views built without it.
        self.metric_index = metric_index
        # partition id -> change counter, populated only in partition-
        # scoped mode (shard/plane.py): a digest built for partition p is
        # stale iff partition_versions[p] moved, independent of churn in
        # the other partitions this replica happens to own.  None when
        # the mirror is unscoped (full-world mode — the global ``version``
        # is the only clock).
        self.partition_versions = partition_versions

    def partition_version(self, partition: int) -> int:
        if self.partition_versions is None:
            return self.version
        return self.partition_versions.get(int(partition), 0)

    def row_version(self, row: int) -> int:
        return self.row_versions[row] if row < len(self.row_versions) else 0

    @property
    def node_capacity(self) -> int:
        return self.present.shape[1]

    def candidate_mask(self, names: Sequence[str]) -> Tuple[jnp.ndarray, List[str]]:
        """Bool [N_cap] mask of interned candidates + the names the mirror
        has never seen (they carry no metrics, so the caller handles them
        with metric-absent semantics)."""
        mask = np.zeros(self.node_capacity, dtype=bool)
        unknown: List[str] = []
        for name in names:
            row = self.node_index.get(name)
            if row is None:
                unknown.append(name)
            else:
                mask[row] = True
        return jnp.asarray(mask), unknown


class TensorStateMirror:
    """Subscribes to AutoUpdatingCache mutation hooks and keeps the device
    tensors in sync.  Thread-safe; reads publish copy-on-write snapshots."""

    def __init__(
        self,
        node_capacity: int = MIN_NODE_CAPACITY,
        metric_capacity: int = MIN_METRIC_CAPACITY,
    ):
        self._lock = threading.Lock()
        self._node_index: Dict[str, int] = {}
        self._node_names: List[str] = []
        self._metric_index: Dict[str, int] = {}
        self._free_metric_rows: List[int] = []
        self._values = np.zeros((metric_capacity, node_capacity), dtype=np.int64)
        self._present = np.zeros((metric_capacity, node_capacity), dtype=bool)
        # fine-grained change counters (see DeviceView doc)
        self._row_versions: Dict[int, int] = {}
        self._intern_version = 0
        self._host_only_metrics: Dict[str, bool] = {}
        self._policies: Dict[Tuple[str, str], CompiledPolicy] = {}
        # sources kept so policies can be recompiled when a freed metric row
        # is reused (their rule tensors hold row indices)
        self._policy_sources: Dict[Tuple[str, str], TASPolicy] = {}
        # tensor version: bumped only when the device snapshot's content
        # (values/present/interning) changes — policy churn must not force a
        # metric-matrix re-upload
        self._version = 0
        self._view: Optional[DeviceView] = None
        # post-publish callbacks, fired OUTSIDE the lock after a mutation
        # that changed the device snapshot or the compiled-policy set; the
        # extender's fastpath warmer subscribes here so the device ranking
        # pass runs in the state-refresh thread, never on a request
        # (reference refresh loop: cmd/main.go:76-78)
        self.on_state_change: List = []
        # per-metric churn since the last drain: metric name ->
        # [changed columns, saw-delete flag].  Written only while a solve
        # observatory is enabled (ops/solveobs.ACTIVE), under the mirror
        # lock the writer already holds — no extra locking on the write
        # path; drained per refresh pass by the observatory's
        # cache.on_refresh_pass hook
        self._churn_pending: Dict[str, List[int]] = {}
        # partition-scoped mode (shard/plane.py): (PartitionMap, callable
        # returning the owned-partition set).  When set, metric writes
        # skip non-owned nodes BEFORE interning — the ~1/P memory cut —
        # and per-partition change counters ride the version bumps.  None
        # (the default) is full-world mode: zero cost, zero behavior
        # change.
        self._partition_scope = None
        self._partition_versions: Dict[int, int] = {}

    # -- wiring ---------------------------------------------------------------

    def attach(self, cache) -> None:
        """Subscribe to a tas.cache.AutoUpdatingCache's mutation hooks."""
        cache.on_metric_write.append(self.on_metric_write)
        cache.on_metric_delete.append(self.on_metric_delete)
        cache.on_policy_write.append(self.on_policy_write)
        cache.on_policy_delete.append(self.on_policy_delete)

    def set_partition_scope(self, pmap, owned) -> None:
        """Enter partition-scoped mode: metric writes keep only nodes in
        partitions ``owned()`` currently returns (re-read per write, so
        ownership handoff takes effect on the next refresh pass without
        re-wiring).  Already-interned non-owned nodes keep their columns
        but stop receiving values — their presence decays to False on the
        next write of each metric, which is exactly the host semantics of
        a node leaving the metric map."""
        with self._lock:
            self._partition_scope = (pmap, owned)

    # -- interning ------------------------------------------------------------

    def _intern_node(self, name: str) -> int:
        row = self._node_index.get(name)
        if row is not None:
            return row
        row = len(self._node_names)
        if row >= self._values.shape[1]:
            new_cap = _next_capacity(self._values.shape[1], row + 1)
            self._values = np.pad(
                self._values, ((0, 0), (0, new_cap - self._values.shape[1]))
            )
            self._present = np.pad(
                self._present, ((0, 0), (0, new_cap - self._present.shape[1]))
            )
        self._node_index[name] = row
        self._node_names.append(name)
        self._intern_version += 1
        return row

    def _intern_metric(self, name: str) -> int:
        row = self._metric_index.get(name)
        if row is not None:
            return row
        if self._free_metric_rows:
            row = self._free_metric_rows.pop()
        else:
            row = len(self._metric_index)
            if row >= self._values.shape[0]:
                new_cap = _next_capacity(self._values.shape[0], row + 1)
                self._values = np.pad(
                    self._values, ((0, new_cap - self._values.shape[0]), (0, 0))
                )
                self._present = np.pad(
                    self._present, ((0, new_cap - self._present.shape[0]), (0, 0))
                )
        self._metric_index[name] = row
        self._values[row, :] = 0
        self._present[row, :] = False
        self._row_versions[row] = self._row_versions.get(row, 0) + 1
        return row

    # -- cache hooks ----------------------------------------------------------

    def _notify(self) -> None:
        """Run the post-publish callbacks; never let a subscriber break the
        writer (the cache refresh loop must keep ticking)."""
        for callback in list(self.on_state_change):
            try:
                callback()
            except Exception as exc:  # noqa: BLE001 — subscriber errors are theirs
                from platform_aware_scheduling_tpu.utils import klog

                klog.error("state-change subscriber failed: %r", exc)

    def on_metric_write(self, metric_name: str, info) -> None:
        """info: NodeMetricsInfo (node -> NodeMetric) or None (registration
        only, autoupdating.go:105-122)."""
        changed = self._metric_write_locked(metric_name, info)
        if changed:
            self._notify()

    def _metric_write_locked(self, metric_name: str, info) -> bool:
        with self._lock:
            shape_before = self._values.shape
            row = self._intern_metric(metric_name)
            if info is None:
                if self._values.shape != shape_before:
                    self._version += 1
                    return True
                return False
            # stage the new row, then bump the version only on real change:
            # the periodic refresh re-writes every metric each sync period
            # (autoupdating.go:37-59) and steady-state values must not
            # invalidate snapshots/plans or force device re-uploads
            host_only = False
            staged: Dict[int, int] = {}
            scope = self._partition_scope
            owned_parts = None
            if scope is not None:
                pmap, owned = scope
                try:
                    owned_parts = owned()
                except Exception:
                    owned_parts = frozenset()
            changed_partitions: Dict[int, bool] = {}
            for node_name, metric in info.items():
                if owned_parts is not None:
                    partition = pmap.partition_of(node_name)
                    if partition not in owned_parts:
                        continue  # not ours: never interned, never stored
                col = self._intern_node(node_name)
                milli, exact = metric.value.milli_value_exact()
                if not exact:
                    host_only = True
                staged[col] = milli
            grew = self._values.shape != shape_before
            new_values = np.zeros(self._values.shape[1], dtype=np.int64)
            new_present = np.zeros(self._values.shape[1], dtype=bool)
            for col, milli in staged.items():
                new_values[col] = milli
                new_present[col] = True
            changed = (
                grew
                or not np.array_equal(self._present[row], new_present)
                or not np.array_equal(self._values[row], new_values)
            )
            if solveobs.ACTIVE is not None:
                # churn telemetry: how many node columns this write
                # actually moved.  A freshly interned row is all-zero /
                # all-absent, so a metric's FIRST pass naturally counts
                # every present column (full churn — to a cold solver the
                # whole row is news); a byte-identical refresh counts 0.
                moved = int(
                    np.count_nonzero(
                        (self._values[row] != new_values)
                        | (self._present[row] != new_present)
                    )
                )
                entry = self._churn_pending.setdefault(metric_name, [0, 0])
                entry[0] += moved
            self._host_only_metrics[metric_name] = host_only
            if changed:
                if owned_parts is not None:
                    # attribute the change to the partitions whose columns
                    # actually moved, so a digest for a quiet partition
                    # stays valid through churn in a noisy one
                    diff = np.nonzero(
                        (self._values[row] != new_values)
                        | (self._present[row] != new_present)
                    )[0]
                    for col in diff:
                        if col < len(self._node_names):
                            changed_partitions[
                                pmap.partition_of(self._node_names[col])
                            ] = True
                    for partition in changed_partitions:
                        self._partition_versions[partition] = (
                            self._partition_versions.get(partition, 0) + 1
                        )
                self._values[row] = new_values
                self._present[row] = new_present
                self._version += 1
                self._row_versions[row] = self._row_versions.get(row, 0) + 1
            return changed

    def on_metric_delete(self, metric_name: str) -> None:
        deleted = False
        with self._lock:
            row = self._metric_index.pop(metric_name, None)
            self._host_only_metrics.pop(metric_name, None)
            if row is not None:
                deleted = True
                if solveobs.ACTIVE is not None:
                    # a delete churns every column it tears down
                    entry = self._churn_pending.setdefault(
                        metric_name, [0, 0]
                    )
                    entry[0] += int(np.count_nonzero(self._present[row]))
                    entry[1] = 1
                self._present[row, :] = False
                self._free_metric_rows.append(row)
                self._version += 1
                self._row_versions[row] = self._row_versions.get(row, 0) + 1
                # compiled rule tensors may reference the freed row; if it is
                # later reused for another metric they would silently read the
                # wrong values — recompile every policy against live rows
                for key, source in self._policy_sources.items():
                    self._policies[key] = self._compile_policy(source)
        if deleted:
            self._notify()

    def on_policy_write(self, namespace: str, name: str, policy: TASPolicy) -> None:
        with self._lock:
            shape_before = self._values.shape
            self._policy_sources[(namespace, name)] = policy
            self._policies[(namespace, name)] = self._compile_policy(policy)
            if self._values.shape != shape_before:  # rule interned a new metric
                self._version += 1
        # fire even without a version bump: a new policy can introduce new
        # (metric row, op) pairs that need warming at the current version
        self._notify()

    def on_policy_delete(self, namespace: str, name: str) -> None:
        with self._lock:
            self._policies.pop((namespace, name), None)
            self._policy_sources.pop((namespace, name), None)

    def drain_churn(self) -> Tuple[Dict[str, Tuple[int, bool]], int]:
        """Take (and reset) the per-metric churn accumulated since the
        last drain, plus the current world size.  Called once per refresh
        pass by the solve observatory's ``cache.on_refresh_pass`` hook."""
        with self._lock:
            pending = self._churn_pending
            self._churn_pending = {}
            world = len(self._node_names)
        return (
            {
                metric: (changed, bool(deleted))
                for metric, (changed, deleted) in pending.items()
            },
            world,
        )

    # -- policy compilation ---------------------------------------------------

    def _compile_rules(self, rules) -> CompiledRuleSet:
        count = len(rules)
        pad = max(RULE_PAD, -(-count // RULE_PAD) * RULE_PAD)
        metric_rows = np.zeros(pad, dtype=np.int32)
        op_ids = np.zeros(pad, dtype=np.int32)
        targets = np.zeros(pad, dtype=np.int64)
        active = np.zeros(pad, dtype=bool)
        host_only = False
        for idx, rule in enumerate(rules):
            metric_rows[idx] = self._intern_metric(rule.metricname)
            op = OP_IDS.get(rule.operator)
            if op is None:
                host_only = True
                op = -1
            op_ids[idx] = op
            if abs(int(rule.target)) > (2**63 - 1) // 1000:
                host_only = True  # milli-domain target would overflow int64
            else:
                targets[idx] = np.int64(rule.target) * np.int64(1000)
            active[idx] = True
        return CompiledRuleSet(
            metric_rows=metric_rows,
            op_ids=op_ids,
            targets=targets,
            active=active,
            host_only=host_only,
            metric_names=tuple(rule.metricname for rule in rules),
        )

    def _compile_policy(self, policy: TASPolicy) -> CompiledPolicy:
        compiled = CompiledPolicy()
        strategies = policy.strategies
        if "dontschedule" in strategies:
            compiled.dontschedule = self._compile_rules(
                strategies["dontschedule"].rules
            )
        if "deschedule" in strategies:
            compiled.deschedule = self._compile_rules(strategies["deschedule"].rules)
        som = strategies.get("scheduleonmetric")
        if som is not None and som.rules and som.rules[0].metricname:
            rule = som.rules[0]
            compiled.scheduleonmetric_row = self._intern_metric(rule.metricname)
            op = OP_IDS.get(rule.operator)
            compiled.scheduleonmetric_op = -1 if op is None else op
            compiled.scheduleonmetric_metric = rule.metricname
        return compiled

    # -- reads ----------------------------------------------------------------

    def policy(self, namespace: str, name: str) -> Optional[CompiledPolicy]:
        with self._lock:
            return self._policies.get((namespace, name))

    def metric_host_only(self, metric_name: str) -> bool:
        with self._lock:
            return self._host_only_metrics.get(metric_name, False)

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def device_view(self) -> DeviceView:
        """Publish (and memoize per version) the device snapshot.  The numpy
        staging arrays are copied at snapshot time so in-flight kernels never
        see a torn update."""
        with self._lock:
            return self._view_locked()

    def policy_with_view_by_name(
        self, name: str
    ) -> Tuple[Optional[CompiledPolicy], Optional[DeviceView]]:
        """Lookup by bare policy name — strategies registered with the
        enforcer only carry the name, not the namespace (the reference's
        enforcement loop has the same ambiguity, deschedule/enforce.go)."""
        with self._lock:
            for (_ns, pname), compiled in self._policies.items():
                if pname == name:
                    return compiled, self._view_locked()
        return None, None

    def policies_with_view(
        self, keys: Sequence[Tuple[str, str]]
    ) -> Tuple[Dict[Tuple[str, str], Optional[CompiledPolicy]], DeviceView, frozenset]:
        """Atomic ({(ns, name): policy}, view, host-only metric names) for a
        whole batch under ONE lock acquisition — a per-policy loop could
        straddle a metric delete + row reuse, leaving earlier policies'
        compiled row indices pointing at a different metric in the view the
        solve actually uses."""
        with self._lock:
            policies = {key: self._policies.get(key) for key in keys}
            host_only = frozenset(
                name for name, flag in self._host_only_metrics.items() if flag
            )
            return policies, self._view_locked(), host_only

    def policies_snapshot(
        self,
    ) -> Tuple[Dict[Tuple[str, str], CompiledPolicy], DeviceView, Dict[str, bool]]:
        """Atomic ({(ns, name): policy}, view, host-only metric map) under
        one lock acquisition — for the fastpath warmer, which must see a
        policy set consistent with the view it precomputes against.  Keys
        ride along so the warmer can pre-render the per-policy violation
        REASONS (the strings carry the policy name)."""
        with self._lock:
            return (
                dict(self._policies),
                self._view_locked(),
                dict(self._host_only_metrics),
            )

    def policy_with_view(
        self, namespace: str, name: str
    ) -> Tuple[Optional[CompiledPolicy], DeviceView]:
        """Atomic (compiled policy, device snapshot) pair under ONE lock
        acquisition — the policy's rule tensors hold metric ROW indices, so
        reading them and the matrix in two steps could straddle a metric-row
        reuse and evaluate the wrong metric."""
        with self._lock:
            return self._policies.get((namespace, name)), self._view_locked()

    def _view_locked(self) -> DeviceView:
        if self._view is not None and self._view.version == self._version:
            return self._view
        obs = solveobs.ACTIVE
        timer = obs.begin("view_build") if obs is not None else None
        hi, lo = i64.split_int64_np(self._values)
        present_host = self._present.copy()
        values_milli = self._values.copy()
        if timer is not None:
            timer.mark("snapshot")
        values = i64.I64(hi=jnp.asarray(hi), lo=jnp.asarray(lo))
        present = jnp.asarray(present_host)
        if timer is not None:
            # jnp.asarray may return before the upload lands; block so
            # the transfer stage carries its real cost, not dispatch time
            try:
                present.block_until_ready()
            except Exception:
                pass
            timer.mark("transfer")
        rows = self._values.shape[0]
        self._view = DeviceView(
            values=values,
            present=present,
            node_names=list(self._node_names),
            node_index=dict(self._node_index),
            version=self._version,
            row_versions=tuple(
                self._row_versions.get(r, 0) for r in range(rows)
            ),
            intern_version=self._intern_version,
            values_milli=values_milli,
            metric_index=dict(self._metric_index),
            partition_versions=(
                dict(self._partition_versions)
                if self._partition_scope is not None
                else None
            ),
        )
        if timer is not None:
            timer.mark("encode")
            timer.done(rows=rows, nodes=len(self._node_names))
        return self._view

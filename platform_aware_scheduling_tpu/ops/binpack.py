"""GAS first-fit card bin-packing as a batched XLA program.

Reference semantics (gpu-aware-scheduling/pkg/gpuscheduler/scheduler.go:
200-257, 341-383): per container, the per-GPU share of the request is
placed on the first card (sorted name order) whose ``used + need <= cap``
for every requested resource; a card can be picked repeatedly for one
container when it has room for several shares; capacity missing or <= 0
for any requested resource fails; int64 overflow of used+need fails.

The reference runs this per node, sequentially, under a global lock
(scheduler.go:463-473).  Here one jitted program evaluates EVERY candidate
node at once: ``vmap`` over the node axis of a ``[nodes, cards, resources]``
usage tensor, ``lax.scan`` over the (small, static) container and GPU-count
axes.  Values are exact int64 in split (hi, lo) form (ops/i64.py).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from platform_aware_scheduling_tpu.ops import i64

NO_CARD = jnp.int32(-1)


class BinpackRequest(NamedTuple):
    """Per-container per-GPU shares, padded to T containers x R resources."""

    need: i64.I64  # [T, R] per-GPU request share (host-divided, exact)
    need_active: jax.Array  # bool [T, R] — resource present in the request
    num_gpus: jax.Array  # int32 [T] — the container's i915 count
    container_active: jax.Array  # bool [T] — real (non-padding) container


class BinpackNodeState(NamedTuple):
    """Per-node card state, padded to N nodes x C cards x R resources."""

    used: i64.I64  # [N, C, R] booked usage
    capacity: i64.I64  # [N, R] per-GPU capacity (homogeneous cards)
    cap_present: jax.Array  # bool [N, R] — resource exists in node capacity
    card_valid: jax.Array  # bool [N, C] — card still in the node's GPU label
    card_real: jax.Array  # bool [N, C] — non-padding lane
    # first-fit priority of each card lane (lower = earlier).  The
    # reference iterates cards in sorted-name order (scheduler.go:216-224);
    # a persistent mirror interns card lanes append-only, so name order is
    # carried explicitly instead of assuming lane order.
    card_order: jax.Array  # int32 [N, C]


class BinpackResult(NamedTuple):
    fits: jax.Array  # bool [N]
    cards: jax.Array  # int32 [N, T, K] chosen card index per GPU, -1 = none


def _card_fits(
    used: i64.I64,  # [C, R]
    need: i64.I64,  # [R]
    need_active: jax.Array,  # [R]
    capacity: i64.I64,  # [R]
    cap_present: jax.Array,  # [R]
    card_ok: jax.Array,  # [C]
) -> jax.Array:
    """checkResourceCapacity (scheduler.go:341-383) for every card at once.
    Returns bool [C]."""
    zero = i64.I64(
        hi=jnp.zeros_like(capacity.hi), lo=jnp.zeros_like(capacity.lo)
    )
    need_b = i64.I64(hi=need.hi[None, :], lo=need.lo[None, :])  # [1, R]
    cap_b = i64.I64(hi=capacity.hi[None, :], lo=capacity.lo[None, :])
    total = i64.add(used, need_b)  # [C, R]
    need_neg = need.hi < 0  # [R]
    cap_ok = cap_present & (i64.cmp(capacity, zero) == 1)  # [R]
    used_neg = used.hi < 0  # [C, R]
    # need >= 0 and used >= 0 here, so overflow <=> sum sign flipped negative
    overflow = (~used_neg) & (total.hi < 0)
    enough = i64.cmp(total, cap_b) <= 0
    per_resource = (
        (~need_neg[None, :])
        & cap_ok[None, :]
        & (~used_neg)
        & (~overflow)
        & enough
    )
    resource_ok = jnp.all(per_resource | ~need_active[None, :], axis=-1)  # [C]
    return card_ok & resource_ok


def _fit_one_node(
    used: i64.I64,  # [C, R]
    capacity: i64.I64,  # [R]
    cap_present: jax.Array,  # [R]
    card_ok: jax.Array,  # [C]
    card_order: jax.Array,  # int32 [C]
    request: BinpackRequest,
    max_gpus: int,
) -> tuple:
    """runSchedulingLogic's card selection for one node
    (scheduler.go:313-338 + 200-257): scan containers, scan GPU picks."""
    num_cards = card_ok.shape[0]
    card_iota = jnp.arange(num_cards, dtype=jnp.int32)
    big_order = jnp.int32(2**30)

    def per_container(carry, request_t):
        used, ok = carry
        need, need_active, num_gpus, active = request_t
        # only resources PRESENT in the request are booked — the reference
        # walks the request map (addRM over its keys, resource_map.go:38-55);
        # an inactive lane must neither gate (handled in _card_fits) nor
        # consume capacity here
        booked_need = i64.I64(
            hi=jnp.where(need_active, need.hi, jnp.int32(0)),
            lo=jnp.where(need_active, need.lo, jnp.uint32(0)),
        )

        def per_gpu(carry2, step):
            used2, ok2 = carry2
            fits = _card_fits(used2, need, need_active, capacity, cap_present, card_ok)
            # first-fit = smallest card_order among fitting lanes
            best_order = jnp.min(jnp.where(fits, card_order, big_order))
            on_best = fits & (card_order == best_order)
            chosen = jnp.min(jnp.where(on_best, card_iota, jnp.int32(num_cards)))
            fitted = chosen < num_cards
            wanted = active & (step < num_gpus)
            book = wanted & fitted
            sel = (card_iota == chosen) & book  # [C]
            total = i64.add(
                used2,
                i64.I64(hi=booked_need.hi[None, :], lo=booked_need.lo[None, :]),
            )
            used2 = i64.select(sel[:, None], total, used2)
            ok2 = ok2 & (fitted | ~wanted)
            picked = jnp.where(book, chosen, NO_CARD)
            return (used2, ok2), picked

        (used, ok_inner), picks = jax.lax.scan(
            per_gpu, (used, ok), jnp.arange(max_gpus, dtype=jnp.int32)
        )
        return (used, ok_inner), picks

    (used_out, ok), all_picks = jax.lax.scan(
        per_container,
        (used, jnp.array(True)),
        (request.need, request.need_active, request.num_gpus,
         request.container_active),
    )
    # used_out carries every booked share; meaningful when ok (the
    # reference discards the scratch copy on failure, scheduler.go:247) —
    # the fused solve gates on fits before applying it
    return ok, all_picks, used_out  # [T, K], [C, R]


@partial(jax.jit, static_argnames=("max_gpus",))
def binpack_kernel(
    state: BinpackNodeState, request: BinpackRequest, max_gpus: int
) -> BinpackResult:
    """Fit ``request`` against every node at once (the batched Filter)."""
    fits, cards, _ = jax.vmap(
        lambda used, cap, cap_p, ok, order: _fit_one_node(
            used, cap, cap_p, ok, order, request, max_gpus
        )
    )(
        state.used,
        state.capacity,
        state.cap_present,
        state.card_valid & state.card_real,
        state.card_order,
    )
    return BinpackResult(fits=fits, cards=cards)

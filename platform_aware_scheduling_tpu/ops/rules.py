"""Vectorized TAS rule evaluation: EvaluateRule over dense tensors.

Host control: ``tas.strategies.core.evaluate_rule`` (exact semantics of
reference pkg/strategies/core/operator.go:13-26).  Here a rule set is three
aligned arrays — ``metric_row [R]`` (row index into the metric matrix),
``op_id [R]``, ``target [R] (I64 milli-units)`` — and evaluation of all R
rules over all N nodes is one fused compare/select pass on the
``[M, N]`` metric matrix.  Violation semantics are OR-across-rules with a
node only participating in a rule when it is present in that rule's metric
map (reference pkg/strategies/dontschedule/strategy.go:25-44).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from platform_aware_scheduling_tpu.ops import i64

OP_LESS_THAN = 0
OP_GREATER_THAN = 1
OP_EQUALS = 2

OP_IDS = {"LessThan": OP_LESS_THAN, "GreaterThan": OP_GREATER_THAN, "Equals": OP_EQUALS}


class RuleSet(NamedTuple):
    """Dense device form of ``[]TASPolicyRule`` (reference
    pkg/telemetrypolicy/api/v1alpha1/types.go:31-40).  All arrays share
    leading dim R (padded; ``active`` masks real rules)."""

    metric_row: jax.Array  # int32 [R] — row in the metric matrix
    op_id: jax.Array  # int32 [R]
    target: i64.I64  # [R] milli-units
    active: jax.Array  # bool [R]


def rule_matches(value: i64.I64, op_id: jax.Array, target: i64.I64) -> jax.Array:
    """``value <op> target`` elementwise; broadcastable.  The device analog
    of evaluate_rule (operator.go:13-26)."""
    sign = i64.cmp(value, target)
    return jnp.where(
        op_id == OP_LESS_THAN,
        sign == -1,
        jnp.where(op_id == OP_GREATER_THAN, sign == 1, sign == 0),
    )


def evaluate_rules(
    metric_values: i64.I64,  # [M, N] milli-units
    metric_present: jax.Array,  # bool [M, N] — node present in metric map
    rules: RuleSet,  # R rules
) -> jax.Array:
    """Per-rule match mask ``[R, N]``: node n matches rule r iff the node is
    present in rule r's metric and the compare holds."""
    values = i64.I64(
        hi=metric_values.hi[rules.metric_row], lo=metric_values.lo[rules.metric_row]
    )  # [R, N]
    present = metric_present[rules.metric_row]  # [R, N]
    target = i64.I64(hi=rules.target.hi[:, None], lo=rules.target.lo[:, None])
    matched = rule_matches(values, rules.op_id[:, None], target)
    return matched & present & rules.active[:, None]


def violated_nodes(
    metric_values: i64.I64,
    metric_present: jax.Array,
    rules: RuleSet,
) -> jax.Array:
    """OR-of-rules violation mask ``[N]`` — the batched ``Violated`` of the
    dontschedule/deschedule strategies (dontschedule/strategy.go:25-44,
    deschedule/strategy.go:31-49; OR semantics per
    telemetry-aware-scheduling/README.md:133)."""
    return jnp.any(evaluate_rules(metric_values, metric_present, rules), axis=0)


def first_violated_rule(
    metric_values: i64.I64,
    metric_present: jax.Array,
    rules: RuleSet,
) -> jax.Array:
    """Per-node index of the FIRST matching rule ``[N]`` (int32; -1 when
    the node violates nothing) — the device half of decision provenance:
    the verdict's compact reason code, decoded host-side into the policy
    rule it names (utils/decisions.py).  "First" is rule-list order,
    matching the host path's lowest-index-wins recording
    (tas/strategies/dontschedule.violated_details)."""
    matched = evaluate_rules(metric_values, metric_present, rules)  # [R, N]
    # argmax over bool returns the first True index (0 when none match)
    first = jnp.argmax(matched, axis=0).astype(jnp.int32)
    return jnp.where(jnp.any(matched, axis=0), first, jnp.int32(-1))

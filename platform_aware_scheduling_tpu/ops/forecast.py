"""Batched telemetry forecasting: EWMA level + Holt linear trend for
every (metric, node) series in one fused pass.

PAPER.md's TAS acts on *snapshots*: `scheduleonmetric` ranks the value at
last refresh, `dontschedule`/`deschedule` fire on instantaneous threshold
crossings.  A node trending toward violation at bind time is a worse
placement than a node in a transient spike, but both score identically on
a snapshot (ROADMAP item 4).  This kernel turns the refresh *history*
(tas/cache.py rings, staged dense by ops/state.build_history_tensor)
into per-series trajectory estimates:

  * **level** — exponentially weighted estimate of where the series is;
  * **trend** — Holt's linear-trend term: milli-units per refresh step;
  * **resid** — mean absolute one-step-ahead residual, the noise scale;
  * **predicted** — ``level + trend * h`` at a horizon of ``h`` steps;
  * **band** — ``resid * (1 + h)``: an uncertainty band that WIDENS with
    extrapolation distance (degraded mode serves forecasts only while
    this stays inside its bound, tas/degraded.py).

One ``lax.scan`` over the time axis updates all ``M x N`` series at once
— the same all-in-one-program shape as ops/scoring.py (which ranks all
nodes per pass) and ops/topology.py (which scores all anchors per pass).
Ragged/missing samples ride a validity mask: an invalid slot carries the
state forward untouched, so a metric with 3 samples and one with W
coexist in the same tensor.

**Exactness.**  All arithmetic is int32 on the milli-quantized, per-row
de-scaled domain (ops/state.history_value_bits — window-aware so the
W-1-term residual accumulator has headroom too): the smoothing weights
are dyadic (alpha = 2^-ALPHA_SHIFT, beta = 2^-BETA_SHIFT) so every
update is adds + arithmetic shifts — associative, branch-free, and
bit-identical between XLA and numpy.  :func:`forecast_host` is the exact
numpy mirror (byte-exact parity pinned by tests/test_forecast.py, the
same contract ops/topology.py keeps), and :func:`forecast_fit` falls
back to it on any device exception — forecasting trouble must never
fail a verb.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from platform_aware_scheduling_tpu.utils import trace

#: dyadic smoothing weights: alpha = 1/2 (level), beta = 1/4 (trend).
#: Dyadic so the recursion stays in exact integer shifts; 1/2 tracks the
#: level fast enough that a refresh-period-scale trend shows within a few
#: samples, 1/4 keeps the trend estimate calm through single-sample noise.
ALPHA_SHIFT = 1
BETA_SHIFT = 2


class ForecastResult(NamedTuple):
    """Per-(metric, node) fit in the SCALED int32 domain (values were
    arithmetic-right-shifted per metric row at staging; callers shift
    outputs back up, ops/state.HistoryTensor.shift).  Identical from
    either execution path."""

    level: np.ndarray  # int32 [M, N] — smoothed current value
    trend: np.ndarray  # int32 [M, N] — slope per refresh step
    resid: np.ndarray  # int32 [M, N] — mean |one-step-ahead error|
    predicted: np.ndarray  # int32 [M, N] — level + trend * horizon
    band: np.ndarray  # int32 [M, N] — resid * (1 + horizon)
    samples: np.ndarray  # int32 [M, N] — valid samples folded in


def _forecast_kernel(values: jnp.ndarray, valid: jnp.ndarray, horizon: jnp.ndarray):
    """(level, trend, resid, predicted, band, samples) over int32
    ``[M, N, W]`` history + bool validity mask; ``horizon`` is an int32
    scalar (refresh steps ahead).  One scan over W updates every series.

    Per valid sample past the first:
      err  = x - (L + b)           # one-step-ahead surprise
      adj  = err >> ALPHA_SHIFT    # alpha * err
      L'   = L + b + adj           # Holt level update
      b'   = b + (adj >> BETA_SHIFT)   # Holt trend update (beta * adj)
    The first valid sample seeds L = x, b = 0.  Invalid slots carry
    state through untouched (ragged series, failed-refresh gaps)."""
    m, n, w = values.shape
    zero = jnp.zeros((m, n), dtype=jnp.int32)

    def step(carry, xs):
        level, trend, count, acc = carry
        x, v = xs
        first = v & (count == 0)
        later = v & (count > 0)
        pred1 = level + trend
        err = x - pred1
        adj = jnp.right_shift(err, ALPHA_SHIFT)
        level = jnp.where(first, x, jnp.where(later, pred1 + adj, level))
        trend = jnp.where(
            later,
            trend + jnp.right_shift(adj, BETA_SHIFT),
            jnp.where(first, jnp.int32(0), trend),
        )
        acc = jnp.where(later, acc + jnp.abs(err), acc)
        count = jnp.where(v, count + jnp.int32(1), count)
        return (level, trend, count, acc), None

    xs = (
        jnp.moveaxis(values.astype(jnp.int32), -1, 0),
        jnp.moveaxis(valid, -1, 0),
    )
    (level, trend, count, acc), _ = jax.lax.scan(
        step, (zero, zero, zero, zero), xs
    )
    # mean |residual| over the count-1 one-step-ahead errors (int division
    # of non-negatives: floor == trunc, identical in XLA and numpy)
    resid = acc // jnp.maximum(count - jnp.int32(1), jnp.int32(1))
    h = horizon.astype(jnp.int32)
    predicted = level + trend * h
    band = resid * (jnp.int32(1) + h)
    return level, trend, resid, predicted, band, count


forecast_kernel = trace.watch_jit(
    "forecast_kernel", jax.jit(_forecast_kernel)
)


def forecast_device(
    values: np.ndarray, valid: np.ndarray, horizon: int
) -> ForecastResult:
    """Device path: the jitted kernel over the staged history."""
    out = forecast_kernel(
        jnp.asarray(values, dtype=jnp.int32),
        jnp.asarray(valid, dtype=bool),
        jnp.int32(int(horizon)),
    )
    level, trend, resid, predicted, band, samples = (
        np.asarray(part) for part in out
    )
    return ForecastResult(
        level=level,
        trend=trend,
        resid=resid,
        predicted=predicted,
        band=band,
        samples=samples,
    )


def forecast_host(
    values: np.ndarray, valid: np.ndarray, horizon: int
) -> ForecastResult:
    """Exact numpy mirror of the device kernel (same int32 adds/shifts in
    the same order) — the parity control and the no-device fallback,
    mirroring the ops/topology.py dual-path structure."""
    values = np.asarray(values, dtype=np.int32)
    valid = np.asarray(valid, dtype=bool)
    m, n, w = values.shape
    level = np.zeros((m, n), dtype=np.int32)
    trend = np.zeros((m, n), dtype=np.int32)
    count = np.zeros((m, n), dtype=np.int32)
    acc = np.zeros((m, n), dtype=np.int32)
    for t in range(w):
        x = values[:, :, t]
        v = valid[:, :, t]
        first = v & (count == 0)
        later = v & (count > 0)
        pred1 = level + trend
        err = x - pred1
        adj = err >> ALPHA_SHIFT
        level = np.where(first, x, np.where(later, pred1 + adj, level))
        trend = np.where(
            later,
            trend + (adj >> BETA_SHIFT),
            np.where(first, np.int32(0), trend),
        )
        acc = np.where(later, acc + np.abs(err), acc)
        count = np.where(v, count + np.int32(1), count)
    resid = (acc // np.maximum(count - np.int32(1), np.int32(1))).astype(
        np.int32
    )
    h = np.int32(int(horizon))
    predicted = (level + trend * h).astype(np.int32)
    band = (resid * (np.int32(1) + h)).astype(np.int32)
    return ForecastResult(
        level=level,
        trend=trend,
        resid=resid,
        predicted=predicted,
        band=band,
        samples=count,
    )


def forecast_fit(
    values: np.ndarray,
    valid: np.ndarray,
    horizon: int,
    use_device: bool = True,
) -> ForecastResult:
    """The dual-path entry: device kernel by default, exact host mirror
    as the control/fallback (device trouble must never fail the caller —
    the same invariant the TAS fastpath and ops/topology.py keep)."""
    if use_device:
        try:
            return forecast_device(values, valid, horizon)
        except Exception:
            pass
    return forecast_host(values, valid, horizon)


def extend_horizon(
    fit: ForecastResult, horizon: int
) -> ForecastResult:
    """Re-extrapolate a stored fit to a new horizon WITHOUT refitting —
    the degraded-mode path: during an outage no new samples arrive, the
    fit stands, and only (predicted, band) move as the horizon grows.
    Same int32 arithmetic as both kernels' tails, so a fit extended to
    ``h`` equals a fresh fit run at ``h``."""
    h = np.int32(int(horizon))
    predicted = (fit.level + fit.trend * h).astype(np.int32)
    band = (fit.resid * (np.int32(1) + h)).astype(np.int32)
    return fit._replace(predicted=predicted, band=band)

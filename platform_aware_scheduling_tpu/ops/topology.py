"""Topology-feasibility kernel: contiguous sub-mesh placement on device.

A multi-host TPU training job needs ``k`` nodes forming a valid ICI
topology — a contiguous ``h x w`` sub-mesh of the cluster's ``M x N``
node mesh — placed atomically or not at all (docs/gang.md).  The
question a gang reservation must answer is: *given the free mask over
the mesh, where can an ``h x w`` slice go, and which anchor strands the
fewest free neighbors?*

One fused pass evaluates EVERY candidate anchor position at once, the
same all-candidates-in-one-program shape as ``ops/binpack.py`` (which
scans all cards of all nodes per request) and the masked-selection
idiom of its first-fit (invalid lanes pushed past a big-order sentinel
rather than branched around):

  * 2-D integral images (two ``cumsum``s) turn "is the whole ``h x w``
    window free" into four gathers per anchor — ``anchor_ok`` for all
    anchors in O(M*N);
  * the same trick over a one-cell halo counts the free cells a placed
    window would leave stranded on its perimeter — ``anchor_score``
    (lower = tighter packing, fewer fragments), ``INFEASIBLE``
    (a binpack-style big-order mask value) where the window does not
    fit;
  * a windowed min (``lax.reduce_window``) folds anchor scores onto the
    nodes they would cover — ``node_score`` ranks every node by the
    quality of the best slice it could complete, which is exactly what
    Prioritize needs, and ``node_score < INFEASIBLE`` is the per-node
    feasibility verdict Filter needs.

Counts are bounded by ``M * N`` mesh cells, so exact int32 suffices —
unlike binpack's i64 capacities there is nothing to overflow, and the
host mirror (:func:`topology_feasibility_host`, numpy, used for
device<->host parity exactly like the dontschedule/GAS dual paths) is
byte-comparable by construction.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from platform_aware_scheduling_tpu.utils import labels as shared_labels

#: big-order sentinel for "no feasible window here" (the masking idiom of
#: ops/binpack.py's first-fit: invalid lanes sort past every real score)
INFEASIBLE = 2**30


class TopologyFeasibility(NamedTuple):
    """Host-side (numpy) result — identical from either execution path."""

    anchor_ok: np.ndarray  # bool [M, N]: h x w window at (i, j) is free
    anchor_score: np.ndarray  # int32 [M, N]: stranded-perimeter count; INFEASIBLE when not ok
    node_ok: np.ndarray  # bool [M, N]: node is coverable by >= 1 feasible window
    node_score: np.ndarray  # int32 [M, N]: best (lowest) covering-window score


def _window_sums(integral: jnp.ndarray, h: int, w: int) -> jnp.ndarray:
    """All ``h x w`` window sums from a padded integral image
    (``integral[a, b] = sum grid[:a, :b]``)."""
    return (
        integral[h:, w:]
        - integral[:-h, w:]
        - integral[h:, :-w]
        + integral[:-h, :-w]
    )


@partial(jax.jit, static_argnames=("h", "w"))
def _topology_kernel(free: jnp.ndarray, h: int, w: int):
    """(anchor_ok, anchor_score, node_score) over a bool [M, N] free mask
    for an ``h x w`` window — one fused pass for every anchor."""
    m, n = free.shape
    fi = free.astype(jnp.int32)
    integral = jnp.zeros((m + 1, n + 1), jnp.int32)
    integral = integral.at[1:, 1:].set(
        jnp.cumsum(jnp.cumsum(fi, axis=0), axis=1)
    )
    window = _window_sums(integral, h, w)  # [m-h+1, n-w+1]
    ok_valid = window == h * w
    # stranded-fragment score: free cells in the one-cell halo ring around
    # the window that placing it would leave behind (fewest = best anchor)
    halo_grid = jnp.zeros((m + 2, n + 2), jnp.int32).at[1:-1, 1:-1].set(fi)
    halo_integral = jnp.zeros((m + 3, n + 3), jnp.int32)
    halo_integral = halo_integral.at[1:, 1:].set(
        jnp.cumsum(jnp.cumsum(halo_grid, axis=0), axis=1)
    )
    halo = _window_sums(halo_integral, h + 2, w + 2)  # same anchor grid
    ring = halo - window
    score_valid = jnp.where(ok_valid, ring, jnp.int32(INFEASIBLE))
    anchor_ok = jnp.zeros((m, n), bool)
    anchor_score = jnp.full((m, n), INFEASIBLE, jnp.int32)
    anchor_ok = anchor_ok.at[: m - h + 1, : n - w + 1].set(ok_valid)
    anchor_score = anchor_score.at[: m - h + 1, : n - w + 1].set(score_valid)
    # fold anchor scores onto covered nodes: node (x, y) is covered by
    # anchors (x-h+1..x, y-w+1..y), a windowed min with top/left padding
    node_score = jax.lax.reduce_window(
        anchor_score,
        jnp.int32(INFEASIBLE),
        jax.lax.min,
        window_dimensions=(h, w),
        window_strides=(1, 1),
        padding=((h - 1, 0), (w - 1, 0)),
    )
    return anchor_ok, anchor_score, node_score


def topology_feasibility_device(
    free: np.ndarray, h: int, w: int
) -> TopologyFeasibility:
    """Device path: the jitted kernel over the free mask."""
    m, n = free.shape
    if h > m or w > n:  # static shape guard: the window cannot fit at all
        return _all_infeasible(m, n)
    anchor_ok, anchor_score, node_score = _topology_kernel(
        jnp.asarray(free, dtype=bool), int(h), int(w)
    )
    node_score_np = np.asarray(node_score)
    return TopologyFeasibility(
        anchor_ok=np.asarray(anchor_ok),
        anchor_score=np.asarray(anchor_score),
        node_ok=node_score_np < INFEASIBLE,
        node_score=node_score_np,
    )


def _all_infeasible(m: int, n: int) -> TopologyFeasibility:
    return TopologyFeasibility(
        anchor_ok=np.zeros((m, n), bool),
        anchor_score=np.full((m, n), INFEASIBLE, np.int32),
        node_ok=np.zeros((m, n), bool),
        node_score=np.full((m, n), INFEASIBLE, np.int32),
    )


def topology_feasibility_host(
    free: np.ndarray, h: int, w: int
) -> TopologyFeasibility:
    """Exact host mirror of the device kernel (numpy, same integral-image
    arithmetic) — the parity control and the no-device fallback, mirroring
    the dontschedule/GAS dual-path structure."""
    free = np.asarray(free, dtype=bool)
    m, n = free.shape
    if h > m or w > n:
        return _all_infeasible(m, n)
    fi = free.astype(np.int32)
    integral = np.zeros((m + 1, n + 1), np.int32)
    integral[1:, 1:] = np.cumsum(np.cumsum(fi, axis=0), axis=1)
    window = (
        integral[h:, w:]
        - integral[:-h, w:]
        - integral[h:, :-w]
        + integral[:-h, :-w]
    )
    ok_valid = window == h * w
    halo_grid = np.zeros((m + 2, n + 2), np.int32)
    halo_grid[1:-1, 1:-1] = fi
    halo_integral = np.zeros((m + 3, n + 3), np.int32)
    halo_integral[1:, 1:] = np.cumsum(np.cumsum(halo_grid, axis=0), axis=1)
    h2, w2 = h + 2, w + 2
    halo = (
        halo_integral[h2:, w2:]
        - halo_integral[:-h2, w2:]
        - halo_integral[h2:, :-w2]
        + halo_integral[:-h2, :-w2]
    )
    ring = halo - window
    anchor_ok = np.zeros((m, n), bool)
    anchor_score = np.full((m, n), INFEASIBLE, np.int32)
    anchor_ok[: m - h + 1, : n - w + 1] = ok_valid
    anchor_score[: m - h + 1, : n - w + 1] = np.where(
        ok_valid, ring, np.int32(INFEASIBLE)
    )
    # windowed min via the h*w shift union (h, w are small static ints)
    node_score = np.full((m, n), INFEASIBLE, np.int32)
    for a in range(h):
        for b in range(w):
            # anchor (x-a, y-b) covers node (x, y)
            shifted = np.full((m, n), INFEASIBLE, np.int32)
            shifted[a:, b:] = anchor_score[: m - a, : n - b]
            node_score = np.minimum(node_score, shifted)
    return TopologyFeasibility(
        anchor_ok=anchor_ok,
        anchor_score=anchor_score,
        node_ok=node_score < INFEASIBLE,
        node_score=node_score,
    )


def topology_feasibility(
    free: np.ndarray, h: int, w: int, use_device: bool = True
) -> TopologyFeasibility:
    """The dual-path entry: device kernel by default, exact host mirror
    as the control/fallback (device trouble must never fail a verb —
    the same invariant the TAS fastpath keeps)."""
    if use_device:
        try:
            return topology_feasibility_device(free, h, w)
        except Exception:
            pass
    return topology_feasibility_host(free, h, w)


# ---------------------------------------------------------------------------
# wraparound (twisted-torus) windows
# ---------------------------------------------------------------------------
#
# Real TPU pods close their ICI links into a torus: a 4x4 slice whose
# rows wrap from column N-1 back to column 0 is just as valid as a
# rectangle in the interior.  The SAME integral-image kernel answers the
# wrapped question when run over a torus-padded copy of the free mask:
#
#   * one wrapped row/column on the TOP/LEFT so every anchor's one-cell
#     halo ring sees true torus neighbors (not synthetic zeros);
#   * ``h`` rows / ``w`` columns wrapped onto the BOTTOM/RIGHT so every
#     anchor in [0, M) x [0, N) has its full window and halo in-bounds.
#
# Cropping the anchor grids back to [0, M) x [0, N) de-duplicates the
# wrapped copies (each torus anchor appears exactly once), and the
# node fold becomes a modular shift union.  The device path runs the
# jitted kernel on the padded mask and shares the numpy crop/fold with
# the host mirror, so torus parity reduces to the (already pinned)
# rectangular kernel parity.


def _torus_pad(free: np.ndarray, h: int, w: int) -> np.ndarray:
    """The torus-padded free mask: [1 + M + h, 1 + N + w]."""
    rows = np.concatenate([free[-1:, :], free, free[:h, :]], axis=0)
    return np.concatenate([rows[:, -1:], rows, rows[:, :w]], axis=1)


def _torus_fold(
    anchor_ok: np.ndarray, anchor_score: np.ndarray, h: int, w: int
) -> TopologyFeasibility:
    """Fold cropped torus anchor scores onto the nodes they cover:
    anchor (i, j) covers nodes ((i+a) mod M, (j+b) mod N) — a modular
    shift union (np.roll), the torus analogue of the rectangular
    mirror's shift loop."""
    m, n = anchor_score.shape
    node_score = np.full((m, n), INFEASIBLE, np.int32)
    for a in range(h):
        rolled_rows = np.roll(anchor_score, a, axis=0)
        for b in range(w):
            node_score = np.minimum(
                node_score, np.roll(rolled_rows, b, axis=1)
            )
    return TopologyFeasibility(
        anchor_ok=anchor_ok,
        anchor_score=anchor_score,
        node_ok=node_score < INFEASIBLE,
        node_score=node_score,
    )


def torus_feasibility_device(
    free: np.ndarray, h: int, w: int
) -> TopologyFeasibility:
    """Device path: the rectangular kernel over the torus-padded mask;
    crop and fold happen host-side, shared verbatim with the mirror."""
    free = np.asarray(free, dtype=bool)
    m, n = free.shape
    if h > m or w > n:  # a wrapped window larger than the torus self-overlaps
        return _all_infeasible(m, n)
    padded = _torus_pad(free, h, w)
    _, anchor_score_p, _ = _topology_kernel(
        jnp.asarray(padded, dtype=bool), int(h), int(w)
    )
    anchor_score = np.asarray(anchor_score_p)[1 : m + 1, 1 : n + 1]
    return _torus_fold(anchor_score < INFEASIBLE, anchor_score, h, w)


def torus_feasibility_host(
    free: np.ndarray, h: int, w: int
) -> TopologyFeasibility:
    """Exact host mirror: the rectangular host kernel over the same
    torus-padded mask, then the shared crop/fold."""
    free = np.asarray(free, dtype=bool)
    m, n = free.shape
    if h > m or w > n:
        return _all_infeasible(m, n)
    padded = _torus_pad(free, h, w)
    feas = topology_feasibility_host(padded, h, w)
    anchor_score = feas.anchor_score[1 : m + 1, 1 : n + 1]
    return _torus_fold(anchor_score < INFEASIBLE, anchor_score, h, w)


def torus_feasibility(
    free: np.ndarray, h: int, w: int, use_device: bool = True
) -> TopologyFeasibility:
    """Dual-path entry for wraparound windows, same fallback stance as
    :func:`topology_feasibility`."""
    if use_device:
        try:
            return torus_feasibility_device(free, h, w)
        except Exception:
            pass
    return torus_feasibility_host(free, h, w)


def torus_slice_cells(
    i: int, j: int, h: int, w: int, m: int, n: int
) -> List[Tuple[int, int]]:
    """The wrapped window's cells in deterministic row-major order,
    coordinates taken modulo the [m, n] torus."""
    return [
        ((i + a) % m, (j + b) % n) for a in range(h) for b in range(w)
    ]


def best_anchor(feas: TopologyFeasibility) -> Optional[Tuple[int, int, int]]:
    """The deterministic best anchor ``(row, col, score)``: lowest
    stranded-fragment score, row-major smallest position on ties; None
    when no window fits."""
    flat = int(np.argmin(feas.anchor_score))
    n = feas.anchor_score.shape[1]
    i, j = divmod(flat, n)
    score = int(feas.anchor_score[i, j])
    if score >= INFEASIBLE:
        return None
    return i, j, score


def slice_cells(i: int, j: int, h: int, w: int) -> List[Tuple[int, int]]:
    """The window's cells in deterministic row-major order."""
    return [(i + a, j + b) for a in range(h) for b in range(w)]


class MeshView:
    """Node-name <-> mesh-coordinate mapping built from ``pas-tpu-coord``
    node labels (testing/fake_kube synthesizes them for hermetic
    meshes).  Nodes without a parseable coordinate sit outside the mesh
    and can never join a topology-constrained gang slice."""

    def __init__(self, nodes):
        coord_of: Dict[str, Tuple[int, int]] = {}
        name_at: Dict[Tuple[int, int], str] = {}
        max_row = -1
        max_col = -1
        for node in nodes:
            coord = shared_labels.parse_coord(node.get_labels())
            if coord is None:
                continue
            # first writer wins on a duplicate coordinate (deterministic
            # given the provider's stable node order)
            if coord in name_at:
                continue
            coord_of[node.name] = coord
            name_at[coord] = node.name
            max_row = max(max_row, coord[0])
            max_col = max(max_col, coord[1])
        self.coord_of = coord_of
        self.name_at = name_at
        self.rows = max_row + 1
        self.cols = max_col + 1

    def __len__(self) -> int:
        return len(self.coord_of)

    def free_mask(self, free_names) -> np.ndarray:
        """bool [rows, cols]: cell is free iff its node is in
        ``free_names`` (holes — coordinates with no node — stay False)."""
        mask = np.zeros((self.rows, self.cols), dtype=bool)
        for name in free_names:
            coord = self.coord_of.get(name)
            if coord is not None:
                mask[coord] = True
        return mask

    def names_for(self, cells) -> Optional[List[str]]:
        """The node names at ``cells`` (row-major); None when any cell is
        a hole."""
        names = []
        for cell in cells:
            name = self.name_at.get(cell)
            if name is None:
                return None
            names.append(name)
        return names

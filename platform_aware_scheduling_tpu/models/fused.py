"""The fused TAS+GAS solve: telemetry scoring AND per-card bin-packing
feasibility in ONE program (BASELINE config #4 as written).

The reference ships this composition as two chained extenders — the
combined scheduler config registers TAS and GAS on the same verb chain
(telemetry-aware-scheduling/deploy/extender-configuration/
tas+gas-extender-configmap.yaml), so a pod is first filtered/scored by
telemetry rules (telemetryscheduler.go:128-149) and then GAS prunes nodes
where no card fits the request and books cards at bind
(gpuscheduler/scheduler.go:200-257, 341-383).  One pod per round trip,
each extender paying its own HTTP + cache walk, GAS under a global lock.

Here the whole pending set is solved in one jitted program over dense
tensors:

  1. TAS half: dontschedule violations + per-pod score keys + candidate
     eligibility (models/batch_scheduler.score_and_filter);
  2. GAS half: per-card first-fit feasibility of each pod's request
     class against EVERY node at once — ``binpack_kernel`` over the
     ``[nodes, cards, resources]`` usage tensor, vmapped over request
     classes -> ``fits[T, N]``;
  3. fused greedy scan in pod order: each pod takes its best-scoring
     node among (eligible ∩ capacity>0 ∩ fits[class]); booking a pod
     updates the chosen node's card usage exactly as GAS bind does
     (first-fit card picks, gpuscheduler/scheduler.go:216-247) and
     re-evaluates feasibility for THAT node only — fits of untouched
     nodes cannot change, so the per-step work is O(N) for the argmax
     plus O(T·C·R·G) for the one-node re-pack, not O(N·C·R).

Pods are grouped into **request classes** (pending bursts share pod
templates; the class axis T is static and small).  The scan reproduces
the sequential reference composition decision-for-decision: pod i gets
its best feasible node given pods 0..i-1's bookings — pinned against a
host TAS-then-GAS control in tests/test_fused.py and benchmarks/
configs.py config4_fused.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from platform_aware_scheduling_tpu.models.batch_scheduler import (
    ClusterState,
    PendingPods,
    score_and_filter,
)
from platform_aware_scheduling_tpu.ops import i64, solveobs
from platform_aware_scheduling_tpu.ops.assign import lex_argmin
from platform_aware_scheduling_tpu.ops.binpack import (
    BinpackNodeState,
    BinpackRequest,
    _fit_one_node,
)


class FusedRequests(NamedTuple):
    """T request classes, each a stacked :class:`BinpackRequest`."""

    need: i64.I64  # [T, Tc, R] per-GPU share per container
    need_active: jax.Array  # bool [T, Tc, R]
    num_gpus: jax.Array  # int32 [T, Tc]
    container_active: jax.Array  # bool [T, Tc]

    def request(self, t) -> BinpackRequest:
        return BinpackRequest(
            need=i64.I64(hi=self.need.hi[t], lo=self.need.lo[t]),
            need_active=self.need_active[t],
            num_gpus=self.num_gpus[t],
            container_active=self.container_active[t],
        )


class FusedOutput(NamedTuple):
    node_for_pod: jax.Array  # int32 [P] — node index or -1
    capacity_left: jax.Array  # int32 [N]
    used: i64.I64  # [N, C, R] card usage after all bookings
    fits: jax.Array  # bool [T, N] feasibility AFTER all bookings
    violating: jax.Array  # bool [N] — TAS dontschedule mask


def _stacked(requests: FusedRequests):
    """The vmap-able leaves of the request-class axis."""
    return (
        i64.I64(hi=requests.need.hi, lo=requests.need.lo),
        requests.need_active,
        requests.num_gpus,
        requests.container_active,
    )


def _all_fits(gas: BinpackNodeState, requests: FusedRequests, max_gpus: int):
    """fits[T, N]: every request class against every node (the batched
    GAS Filter, step 2 of the module doc)."""
    card_ok = gas.card_valid & gas.card_real

    def per_class(req_t):
        req = BinpackRequest(*req_t)

        def per_node(used_hi, used_lo, cap_hi, cap_lo, cap_p, ok, order):
            fits, _, _ = _fit_one_node(
                i64.I64(hi=used_hi, lo=used_lo),
                i64.I64(hi=cap_hi, lo=cap_lo),
                cap_p,
                ok,
                order,
                req,
                max_gpus,
            )
            return fits

        return jax.vmap(per_node)(
            gas.used.hi,
            gas.used.lo,
            gas.capacity.hi,
            gas.capacity.lo,
            gas.cap_present,
            card_ok,
            gas.card_order,
        )

    return jax.vmap(per_class)(_stacked(requests))


def shard_fused_inputs(mesh, state, pods, req_class, gas, requests):
    """Place a fused problem on a node-sharded mesh: every node-axis leaf
    (metric matrix dim 1, candidates dim 1, capacity dim 0, the whole GAS
    usage tensor dim 0) gets a NamedSharding over ``NODE_AXIS``; rule
    tensors, request classes, and per-pod vectors replicate.  The single
    sharding recipe used by both the multi-chip dryrun and the GSPMD
    parity test — ``fused_schedule`` then runs unchanged and GSPMD
    inserts the collectives."""
    from jax.sharding import NamedSharding, PartitionSpec

    from platform_aware_scheduling_tpu.parallel.mesh import (
        NODE_AXIS,
        replicated,
    )

    rep = replicated(mesh)

    def node_shard(x, axis):
        spec = [None] * x.ndim
        spec[axis] = NODE_AXIS
        return jax.device_put(x, NamedSharding(mesh, PartitionSpec(*spec)))

    state_s = state._replace(
        metric_values=jax.tree.map(
            lambda x: node_shard(x, 1), state.metric_values
        ),
        metric_present=node_shard(state.metric_present, 1),
        dontschedule=jax.tree.map(
            lambda x: jax.device_put(x, rep), state.dontschedule
        ),
        capacity=node_shard(state.capacity, 0),
    )
    pods_s = pods._replace(
        candidates=node_shard(pods.candidates, 1),
        metric_row=jax.device_put(pods.metric_row, rep),
        op_id=jax.device_put(pods.op_id, rep),
    )
    gas_s = jax.tree.map(lambda x: node_shard(x, 0), gas)
    requests_s = jax.tree.map(lambda x: jax.device_put(x, rep), requests)
    req_class_s = jax.device_put(req_class, rep)
    return state_s, pods_s, req_class_s, gas_s, requests_s


@partial(jax.jit, static_argnames=("max_gpus",))
def fused_schedule(
    state: ClusterState,
    pods: PendingPods,
    req_class: jax.Array,  # int32 [P] — request class per pod
    gas: BinpackNodeState,
    requests: FusedRequests,
    max_gpus: int,
) -> FusedOutput:
    """One fused TAS+GAS solve over the pending set (module doc)."""
    violating, score, eligible = score_and_filter(state, pods)
    fits0 = _all_fits(gas, requests, max_gpus)  # [T, N]
    card_ok = gas.card_valid & gas.card_real  # [N, C]
    n_nodes = eligible.shape[1]

    def step(carry, pod):
        used, fits, cap = carry
        s_hi, s_lo, elig, cls = pod
        ok = elig & (cap > 0) & fits[cls]
        flipped = i64.flip(i64.I64(hi=s_hi, lo=s_lo))
        best, found = lex_argmin(flipped, ok)
        node = jnp.maximum(best, 0)  # safe index when unassigned

        # re-pack the chosen node with the pod's class: _fit_one_node's
        # final carry IS the booked usage (GAS bind's card walk,
        # scheduler.go:216-247); the fits gate guarantees the request
        # fully fits, so applying it wholesale is exact
        used_n = i64.I64(hi=used.hi[node], lo=used.lo[node])  # [C, R]
        cap_n = i64.I64(hi=gas.capacity.hi[node], lo=gas.capacity.lo[node])
        _, _, new_used_n = _fit_one_node(
            used_n,
            cap_n,
            gas.cap_present[node],
            card_ok[node],
            gas.card_order[node],
            requests.request(cls),
            max_gpus,
        )
        booked = found
        used = i64.I64(
            hi=jnp.where(booked, used.hi.at[node].set(new_used_n.hi), used.hi),
            lo=jnp.where(booked, used.lo.at[node].set(new_used_n.lo), used.lo),
        )
        # only the booked node's feasibility can change — re-evaluate that
        # one node for every class and scatter the [T] column
        def refit(req_t):
            fit_n, _, _ = _fit_one_node(
                new_used_n,
                cap_n,
                gas.cap_present[node],
                card_ok[node],
                gas.card_order[node],
                BinpackRequest(*req_t),
                max_gpus,
            )
            return fit_n

        col = jax.vmap(refit)(_stacked(requests))  # [T]
        fits = jnp.where(booked, fits.at[:, node].set(col), fits)
        take = jnp.where(
            booked,
            jax.nn.one_hot(node, n_nodes, dtype=cap.dtype),
            jnp.zeros_like(cap),
        )
        return (used, fits, cap - take), best

    (used, fits, cap_left), node_for_pod = jax.lax.scan(
        step,
        (gas.used, fits0, state.capacity),
        (score.hi, score.lo, eligible, req_class),
    )
    return FusedOutput(
        node_for_pod=node_for_pod,
        capacity_left=cap_left,
        used=used,
        fits=fits,
        violating=violating,
    )


def observed_fused_schedule(
    state: ClusterState,
    pods: PendingPods,
    req_class: jax.Array,
    gas: BinpackNodeState,
    requests: FusedRequests,
    max_gpus: int,
    timer=None,
) -> FusedOutput:
    """``fused_schedule`` with solve-observatory stage attribution — the
    same caller-owned-timer contract as
    ``models.batch_scheduler.observed_scheduling_step``: compile when
    the jit cache grew during the dispatch, execute across
    ``block_until_ready``; readback/encode belong to the caller."""
    own = timer is None
    if own:
        obs = solveobs.ACTIVE
        if obs is None:
            return fused_schedule(
                state, pods, req_class, gas, requests, max_gpus
            )
        timer = obs.begin("fused_solve")
    before = fused_schedule._cache_size()
    out = fused_schedule(state, pods, req_class, gas, requests, max_gpus)
    timer.mark(
        "compile" if fused_schedule._cache_size() > before else "execute"
    )
    jax.block_until_ready(out.node_for_pod)
    timer.mark("execute")
    if own:
        timer.done(
            pods=int(pods.metric_row.shape[0]),
            nodes=int(state.capacity.shape[0]),
        )
    return out

"""The batched scheduling solve: filter + score + assign in one program.

This is the capability the reference cannot express (SURVEY §7 step 4):
kube-scheduler drives one pod per extender round-trip
(telemetryscheduler.go:39-59 per request); here the WHOLE pending set is
solved at once over dense tensors:

  1. dontschedule violations over the metric matrix  (ops/rules.py)
  2. per-pod score keys from each pod's scheduleonmetric rule
  3. greedy capacity-constrained assignment           (ops/assign.py)

Greedy-in-pod-order reproduces what the sequential system would decide, so
answers to individual /scheduler verbs can be served from this solution.

Multi-chip: ``scheduling_step`` is pure and shape-static, so the production
path is the GSPMD recipe — jit with NamedSharding-annotated inputs over a
(pods, nodes) mesh; XLA inserts the all_gathers/psums over ICI.  The
hand-written collective forms live in parallel/sharded.py.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from platform_aware_scheduling_tpu.ops import i64, solveobs
from platform_aware_scheduling_tpu.ops.assign import (
    AssignResult,
    auction_assign_kernel,
    greedy_assign_kernel,
)
from platform_aware_scheduling_tpu.ops.rules import (
    OP_GREATER_THAN,
    OP_LESS_THAN,
    RuleSet,
    violated_nodes,
)


class ClusterState(NamedTuple):
    """Dense device form of the cluster, maintained by the state mirror."""

    metric_values: i64.I64  # [M, N] milli-units
    metric_present: jax.Array  # bool [M, N]
    dontschedule: RuleSet  # shared violation rules
    capacity: jax.Array  # int32 [N] — pods each node may still accept


class PendingPods(NamedTuple):
    """The pending set: one scheduleonmetric rule + candidate mask per pod."""

    metric_row: jax.Array  # int32 [P]
    op_id: jax.Array  # int32 [P]
    candidates: jax.Array  # bool [P, N]


class ScheduleOutput(NamedTuple):
    assignment: AssignResult
    violating: jax.Array  # bool [N]
    score: i64.I64  # [P, N] keys used (larger = better)
    eligible: jax.Array  # bool [P, N] — candidates ∩ present ∩ ¬violating


def _score_keys(values: i64.I64, present, metric_row, op_id) -> i64.I64:
    """Per-pod score keys where larger is better: GreaterThan keeps the
    metric value, LessThan flips it, anything else prefers low node index
    (the deterministic stand-in for the reference's map-order walk)."""
    v = i64.I64(hi=values.hi[metric_row], lo=values.lo[metric_row])  # [P, N]
    flipped = i64.flip(v)
    by_value = i64.select((op_id == OP_GREATER_THAN)[:, None], v, flipped)
    n = v.hi.shape[-1]
    idx = jnp.arange(n, dtype=jnp.uint32)
    index_key = i64.flip(
        i64.I64(hi=jnp.zeros_like(v.hi), lo=jnp.broadcast_to(idx, v.lo.shape))
    )
    sorts = ((op_id == OP_LESS_THAN) | (op_id == OP_GREATER_THAN))[:, None]
    return i64.select(sorts, by_value, index_key)


@jax.jit
def score_and_filter(state: ClusterState, pods: PendingPods):
    """The non-assignment half of the solve: (violating, score, eligible).
    Separable so alternative assignment solvers (ops/sinkhorn.py) don't pay
    for a greedy solve they discard."""
    violating = violated_nodes(
        state.metric_values, state.metric_present, state.dontschedule
    )
    score = _score_keys(
        state.metric_values, state.metric_present, pods.metric_row, pods.op_id
    )
    present = state.metric_present[pods.metric_row]  # [P, N]
    eligible = pods.candidates & present & ~violating[None, :]
    return violating, score, eligible


@jax.jit
def scheduling_step(state: ClusterState, pods: PendingPods) -> ScheduleOutput:
    """One full solve over the pending set."""
    violating, score, eligible = score_and_filter(state, pods)
    # All three assignment kernels are exact greedy-in-order.  Measured on
    # v5e at 1k x 10k: the Pallas kernel (~6 ms; capacity resident in VMEM,
    # one launch) beats the XLA scan (~12 ms; P dispatch-bound steps), which
    # beats the auction under heavy contention (62 rounds, ~36 ms — though
    # auction wins when pods' rankings are mostly distinct).  Pallas lowers
    # only on TPU; elsewhere the scan runs.
    # (single-chip only: a hand-written pallas_call does not auto-partition
    # under GSPMD — the multi-chip path uses the scan / parallel/sharded.py)
    if jax.default_backend() == "tpu" and jax.device_count() == 1:
        from platform_aware_scheduling_tpu.ops.pallas_assign import (
            greedy_assign_pallas,
        )

        assignment = greedy_assign_pallas(score, eligible, state.capacity)
    else:
        assignment = greedy_assign_kernel(score, eligible, state.capacity)
    return ScheduleOutput(
        assignment=assignment, violating=violating, score=score, eligible=eligible
    )


def observed_scheduling_step(
    state: ClusterState, pods: PendingPods, timer=None
) -> ScheduleOutput:
    """``scheduling_step`` with solve-observatory stage attribution.

    When no observatory is enabled (and no caller-owned timer is
    passed) this is exactly one extra ``is None`` check around the
    plain call — the planner routes through here unconditionally so the
    off path stays byte-identical.  With a timer the call is bracketed
    with ``compile``/``execute`` marks: compile when the jit cache grew
    during the dispatch, execute timed across ``block_until_ready`` so
    XLA's async dispatch cannot launder device time into the caller's
    readback.  The caller keeps ownership of the timer — its readback
    and encode happen on its side of the fence."""
    own = timer is None
    if own:
        obs = solveobs.ACTIVE
        if obs is None:
            return scheduling_step(state, pods)
        timer = obs.begin("batch_solve")
    before = scheduling_step._cache_size()
    out = scheduling_step(state, pods)
    timer.mark(
        "compile" if scheduling_step._cache_size() > before else "execute"
    )
    jax.block_until_ready(out.assignment.node_for_pod)
    timer.mark("execute")
    if own:
        timer.done(
            pods=int(pods.metric_row.shape[0]),
            nodes=int(state.capacity.shape[0]),
        )
    return out


def example_inputs(
    num_metrics: int = 4,
    num_nodes: int = 64,
    num_pods: int = 16,
    seed: int = 0,
):
    """Small synthetic (state, pods) pair for compile checks and benches."""
    import numpy as np

    rng = np.random.default_rng(seed)
    values = rng.integers(0, 1_000_000, size=(num_metrics, num_nodes)).astype(
        np.int64
    )
    hi, lo = i64.split_int64_np(values)
    t_hi, t_lo = i64.split_int64_np(np.array([500_000, 900_000], dtype=np.int64))
    state = ClusterState(
        metric_values=i64.I64(hi=jnp.asarray(hi), lo=jnp.asarray(lo)),
        metric_present=jnp.asarray(rng.random((num_metrics, num_nodes)) > 0.05),
        dontschedule=RuleSet(
            metric_row=jnp.asarray(np.array([0, 1], dtype=np.int32)),
            op_id=jnp.asarray(
                np.array([OP_GREATER_THAN, OP_GREATER_THAN], dtype=np.int32)
            ),
            target=i64.I64(hi=jnp.asarray(t_hi), lo=jnp.asarray(t_lo)),
            active=jnp.asarray(np.array([True, True])),
        ),
        capacity=jnp.asarray(
            rng.integers(1, 4, size=num_nodes).astype(np.int32)
        ),
    )
    pods = PendingPods(
        metric_row=jnp.asarray(
            rng.integers(0, num_metrics, size=num_pods).astype(np.int32)
        ),
        op_id=jnp.asarray(
            rng.choice([OP_LESS_THAN, OP_GREATER_THAN], size=num_pods).astype(
                np.int32
            )
        ),
        candidates=jnp.asarray(rng.random((num_pods, num_nodes)) > 0.1),
    )
    return state, pods

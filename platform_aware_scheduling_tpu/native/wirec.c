/* _wirec: native fast path for the scheduler-extender wire protocol.
 *
 * The per-request hot cost at 10k nodes is NOT the scheduling math (that
 * is precomputed per state version, tas/fastpath.py) but the wire tails:
 * json-decoding an Args body into ~10k Python dicts/objects and re-encoding
 * ~10k HostPriority entries.  This module removes both:
 *
 *   parse_prioritize(body)        -> ParsedArgs (pod meta + node-name
 *                                    slices captured zero-copy; no per-node
 *                                    Python objects)
 *   build_table(node_names)       -> NameTable (FNV-1a open-addressing
 *                                    name->row map + pre-rendered per-row
 *                                    JSON fragments), built once per state
 *                                    version
 *   select_encode(parsed, table, ranked, planned_row)
 *                                 -> response bytes: global rank order
 *                                    restricted to the request's candidate
 *                                    set, ordinal 10-i scores, optional
 *                                    batch-plan promotion to rank 1
 *
 * The JSON scanner is strict: any structural surprise raises ValueError and
 * the caller falls back to the exact Python path (which reproduces every
 * reference quirk).  Semantics mirror tas/fastpath.py byte-for-byte; the
 * equivalence is pinned by tests/test_wirec.py.
 *
 * Reference for the wire shape: extender/types.go:26-64 (Args,
 * HostPriorityList); scoring semantics telemetryscheduler.go:128-149.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

/* ------------------------------------------------------------------ */
/* growable byte buffer                                                */

typedef struct {
    char *data;
    size_t len;
    size_t cap;
} Buf;

static int buf_init(Buf *b, size_t cap) {
    b->data = malloc(cap ? cap : 64);
    if (!b->data) return -1;
    b->len = 0;
    b->cap = cap ? cap : 64;
    return 0;
}

static void buf_free(Buf *b) {
    free(b->data);
    b->data = NULL;
}

static int buf_reserve(Buf *b, size_t extra) {
    if (b->len + extra <= b->cap) return 0;
    size_t ncap = b->cap * 2;
    while (ncap < b->len + extra) ncap *= 2;
    char *nd = realloc(b->data, ncap);
    if (!nd) return -1;
    b->data = nd;
    b->cap = ncap;
    return 0;
}

static int buf_put(Buf *b, const char *src, size_t n) {
    if (buf_reserve(b, n) < 0) return -1;
    memcpy(b->data + b->len, src, n);
    b->len += n;
    return 0;
}

/* Process-wide pool of reusable scratch buffers for the per-request
 * encode paths.
 *
 * A 10k-node response is ~400 KB; glibc malloc serves that size from
 * mmap, so a fresh allocation per request means fresh pages — the
 * page-fault + munmap churn lands straight in p99 on the cache-miss
 * tier.  The pool keeps a handful of high-water buffers alive across
 * requests AND across connections (the server is thread-per-connection,
 * so thread-local scratch would leak per connection and never stay
 * warm).  pool_get always returns an owned Buf (possibly freshly
 * allocated; data==NULL only on OOM); pool_put returns it to a free
 * slot or frees it when the pool is full — bounded memory, no leak. */
#include <pthread.h>
#define POOL_SLOTS 8
static pthread_mutex_t pool_lock = PTHREAD_MUTEX_INITIALIZER;
static Buf buf_pool[POOL_SLOTS];

static Buf pool_get(size_t want) {
    Buf b = {NULL, 0, 0};
    pthread_mutex_lock(&pool_lock);
    for (int i = 0; i < POOL_SLOTS; i++) {
        if (buf_pool[i].data) {
            b = buf_pool[i];
            buf_pool[i].data = NULL;
            break;
        }
    }
    pthread_mutex_unlock(&pool_lock);
    if (b.data) {
        b.len = 0;
        if (want && buf_reserve(&b, want) < 0) {
            buf_free(&b);
            b.data = NULL;
        }
    } else if (buf_init(&b, want ? want : 4096) < 0) {
        b.data = NULL;
    }
    return b;
}

static void pool_put(Buf *b) {
    if (!b->data) return;
    pthread_mutex_lock(&pool_lock);
    for (int i = 0; i < POOL_SLOTS; i++) {
        if (!buf_pool[i].data) {
            buf_pool[i] = *b;
            b->data = NULL;
            break;
        }
    }
    pthread_mutex_unlock(&pool_lock);
    if (b->data) buf_free(b);
}

/* ------------------------------------------------------------------ */
/* JSON scanner over a byte body                                       */

typedef struct {
    const char *s;
    Py_ssize_t n;
    Py_ssize_t i;
    const char *err;  /* static message; raised as ValueError by the caller
                         (lets the scan run without the GIL) */
} Scan;

typedef struct {
    Py_ssize_t off;   /* offset of first char INSIDE the quotes */
    Py_ssize_t len;   /* raw length inside the quotes */
    int escaped;      /* contains backslash escapes (slow-path materialize) */
    int present;
} StrSlice;

static void skip_ws(Scan *sc) {
    while (sc->i < sc->n) {
        char c = sc->s[sc->i];
        if (c == ' ' || c == '\t' || c == '\n' || c == '\r') sc->i++;
        else break;
    }
}

/* record the first error on the scan state; raised as ValueError by the
 * entry point after the GIL is re-acquired */
static int fail_raw(Scan *sc, const char *msg) {
    if (!sc->err) sc->err = msg;
    return -1;
}

#define fail(msg) fail_raw(sc, msg)

/* any byte outside plain-ASCII string content: < 0x20 (control), '\\'
 * (escape), or >= 0x80 (multibyte UTF-8) — found via an 8-byte SWAR
 * sweep.  '"' cannot appear in the probed span (it is memchr's stop). */
static int span_has_special(const char *s, Py_ssize_t n) {
    const uint64_t ones = 0x0101010101010101ULL;
    const uint64_t highs = 0x8080808080808080ULL;
    Py_ssize_t i = 0;
    for (; i + 8 <= n; i += 8) {
        uint64_t w;
        memcpy(&w, s + i, 8);
        uint64_t lt20 = (w - ones * 0x20) & ~w & highs;
        uint64_t ge80 = w & highs;
        uint64_t xbs = w ^ (ones * (unsigned char)'\\');
        uint64_t isbs = (xbs - ones) & ~xbs & highs;
        if (lt20 | ge80 | isbs) return 1;
    }
    for (; i < n; i++) {
        unsigned char c = (unsigned char)s[i];
        if (c < 0x20 || c >= 0x80 || c == '\\') return 1;
    }
    return 0;
}

/* scan a JSON string starting at the opening quote; record the slice.
 *
 * Escape sequences and UTF-8 well-formedness are validated HERE, exactly
 * as strictly as json.loads over bytes (which UTF-8-decodes first): a body
 * that json.loads would reject must fail the native parse too, so the
 * exact Python path owns the response for it — never a silent divergence
 * or a deferred exception at slice-materialization time.
 *
 * Fast path: memchr to the next '"', one SWAR sweep over the span; when
 * the span is plain ASCII (the overwhelmingly common case for node
 * names/keys) the per-byte validating loop is skipped entirely.  Any
 * special byte — including an escaped quote, whose preceding backslash
 * trips the sweep — falls back to the exact loop from the start. */
static int scan_string(Scan *sc, StrSlice *out) {
    if (sc->i >= sc->n || sc->s[sc->i] != '"') return fail("expected string");
    sc->i++;
    Py_ssize_t start = sc->i;
    {
        const char *base = sc->s + start;
        const char *q = memchr(base, '"', (size_t)(sc->n - start));
        if (q) {
            Py_ssize_t len = (Py_ssize_t)(q - base);
            if (!span_has_special(base, len)) {
                if (out) {
                    out->off = start;
                    out->len = len;
                    out->escaped = 0;
                    out->present = 1;
                }
                sc->i = start + len + 1;
                return 0;
            }
        }
    }
    int escaped = 0;
    while (sc->i < sc->n) {
        unsigned char c = (unsigned char)sc->s[sc->i];
        if (c == '\\') {
            escaped = 1;
            if (sc->i + 1 >= sc->n) return fail("bad escape");
            char e = sc->s[sc->i + 1];
            if (e == 'u') {
                if (sc->i + 5 >= sc->n) return fail("bad \\u escape");
                for (int k = 2; k <= 5; k++) {
                    char h = sc->s[sc->i + k];
                    if (!((h >= '0' && h <= '9') || (h >= 'a' && h <= 'f') ||
                          (h >= 'A' && h <= 'F')))
                        return fail("bad \\u escape");
                }
                sc->i += 6;
            } else if (e == '"' || e == '\\' || e == '/' || e == 'b' ||
                       e == 'f' || e == 'n' || e == 'r' || e == 't') {
                sc->i += 2;
            } else {
                return fail("bad escape");
            }
            continue;
        }
        if (c == '"') {
            if (out) {
                out->off = start;
                out->len = sc->i - start;
                out->escaped = escaped;
                out->present = 1;
            }
            sc->i++;
            return 0;
        }
        if (c < 0x20) return fail("control char in string");
        if (c >= 0x80) {
            /* strict UTF-8: reject bad lead/continuation bytes, overlong
             * forms, surrogates, and code points past U+10FFFF — the same
             * set CPython's strict utf-8 decoder rejects */
            const unsigned char *p = (const unsigned char *)sc->s + sc->i;
            Py_ssize_t left = sc->n - sc->i;
            int len;
            if ((p[0] & 0xE0) == 0xC0) {
                if (p[0] < 0xC2) return fail("invalid UTF-8");
                len = 2;
            } else if ((p[0] & 0xF0) == 0xE0) {
                len = 3;
            } else if ((p[0] & 0xF8) == 0xF0) {
                if (p[0] > 0xF4) return fail("invalid UTF-8");
                len = 4;
            } else {
                return fail("invalid UTF-8");
            }
            if (left < len) return fail("invalid UTF-8");
            for (int k = 1; k < len; k++)
                if ((p[k] & 0xC0) != 0x80) return fail("invalid UTF-8");
            if (len == 3) {
                if (p[0] == 0xE0 && p[1] < 0xA0) return fail("invalid UTF-8");
                if (p[0] == 0xED && p[1] >= 0xA0) return fail("invalid UTF-8");
            } else if (len == 4) {
                if (p[0] == 0xF0 && p[1] < 0x90) return fail("invalid UTF-8");
                if (p[0] == 0xF4 && p[1] >= 0x90) return fail("invalid UTF-8");
            }
            sc->i += len;
            continue;
        }
        sc->i++;
    }
    return fail("unterminated string");
}

static int skip_value(Scan *sc);

/* ASCII-case-insensitive key match against a lowercase literal.  The
 * real kube-scheduler marshals the upstream extender types (lowercase
 * tags: "pod"/"nodes"/"nodenames"); the reference's untagged Go structs
 * accept them through encoding/json's case-insensitive field matching,
 * so the Args TOP-LEVEL keys must match case-insensitively here too
 * (inner object keys are Go-marshaled v1 structs — always canonical
 * lowercase on the wire — and stay exact, like the Python path). */
static int key_is_ci(const char *s, Py_ssize_t len, const char *lower_lit,
                     Py_ssize_t lit_len) {
    if (len != lit_len) return 0;
    for (Py_ssize_t i = 0; i < len; i++) {
        char a = s[i];
        if (a >= 'A' && a <= 'Z') a += 32;
        if (a != lower_lit[i]) return 0;
    }
    return 1;
}

static int skip_object(Scan *sc) {
    sc->i++; /* '{' */
    skip_ws(sc);
    if (sc->i < sc->n && sc->s[sc->i] == '}') { sc->i++; return 0; }
    for (;;) {
        skip_ws(sc);
        if (scan_string(sc, NULL) < 0) return -1;
        skip_ws(sc);
        if (sc->i >= sc->n || sc->s[sc->i] != ':') return fail("expected ':'");
        sc->i++;
        if (skip_value(sc) < 0) return -1;
        skip_ws(sc);
        if (sc->i >= sc->n) return fail("unterminated object");
        if (sc->s[sc->i] == ',') { sc->i++; continue; }
        if (sc->s[sc->i] == '}') { sc->i++; return 0; }
        return fail("bad object");
    }
}

static int skip_array(Scan *sc) {
    sc->i++; /* '[' */
    skip_ws(sc);
    if (sc->i < sc->n && sc->s[sc->i] == ']') { sc->i++; return 0; }
    for (;;) {
        if (skip_value(sc) < 0) return -1;
        skip_ws(sc);
        if (sc->i >= sc->n) return fail("unterminated array");
        if (sc->s[sc->i] == ',') { sc->i++; continue; }
        if (sc->s[sc->i] == ']') { sc->i++; return 0; }
        return fail("bad array");
    }
}

static int skip_number(Scan *sc) {
    if (sc->i < sc->n && sc->s[sc->i] == '-') sc->i++;
    /* strict like json.loads: no leading zeros */
    if (sc->i >= sc->n) return fail("bad number");
    if (sc->s[sc->i] == '0') {
        sc->i++;
        if (sc->i < sc->n && sc->s[sc->i] >= '0' && sc->s[sc->i] <= '9')
            return fail("leading zero");
    } else if (sc->s[sc->i] >= '1' && sc->s[sc->i] <= '9') {
        while (sc->i < sc->n && sc->s[sc->i] >= '0' && sc->s[sc->i] <= '9')
            sc->i++;
    } else {
        return fail("bad number");
    }
    int digits;
    if (sc->i < sc->n && sc->s[sc->i] == '.') {
        sc->i++;
        digits = 0;
        while (sc->i < sc->n && sc->s[sc->i] >= '0' && sc->s[sc->i] <= '9') {
            digits = 1; sc->i++;
        }
        if (!digits) return fail("bad number");
    }
    if (sc->i < sc->n && (sc->s[sc->i] == 'e' || sc->s[sc->i] == 'E')) {
        sc->i++;
        if (sc->i < sc->n && (sc->s[sc->i] == '+' || sc->s[sc->i] == '-')) sc->i++;
        digits = 0;
        while (sc->i < sc->n && sc->s[sc->i] >= '0' && sc->s[sc->i] <= '9') {
            digits = 1; sc->i++;
        }
        if (!digits) return fail("bad number");
    }
    return 0;
}

static int skip_literal(Scan *sc, const char *lit, Py_ssize_t len) {
    if (sc->i + len > sc->n || memcmp(sc->s + sc->i, lit, len) != 0)
        return fail("bad literal");
    sc->i += len;
    return 0;
}

static int skip_value(Scan *sc) {
    skip_ws(sc);
    if (sc->i >= sc->n) return fail("unexpected end");
    switch (sc->s[sc->i]) {
    case '{': return skip_object(sc);
    case '[': return skip_array(sc);
    case '"': return scan_string(sc, NULL);
    case 't': return skip_literal(sc, "true", 4);
    case 'f': return skip_literal(sc, "false", 5);
    case 'n': return skip_literal(sc, "null", 4);
    default:  return skip_number(sc);
    }
}

/* ------------------------------------------------------------------ */
/* ParsedArgs object                                                   */

typedef struct {
    PyObject_HEAD
    PyObject *body;        /* the bytes object; slices point into it */
    StrSlice pod_name;
    StrSlice pod_namespace;
    StrSlice policy_label; /* labels["telemetry-policy"] */
    int has_label;
    int nodes_present;     /* "Nodes" was a non-null object with items */
    StrSlice *names;       /* node name slices (Nodes.items[].metadata.name) */
    Py_ssize_t num_names;
    int node_names_present; /* "NodeNames" was a non-null array */
    StrSlice *nn_names;     /* NodeNames[] string slices */
    Py_ssize_t num_nn_names;
    /* raw byte span [start, end) of the candidate-list JSON values —
     * identical spans mean identical candidate sets, the key of the
     * response-reuse cache (tas/fastpath.py); -1 when absent */
    Py_ssize_t nodes_span_start, nodes_span_end;
    Py_ssize_t nn_span_start, nn_span_end;
} ParsedArgs;

static void ParsedArgs_dealloc(ParsedArgs *self) {
    Py_XDECREF(self->body);
    free(self->names);  /* raw-allocated: grown while the GIL is released */
    free(self->nn_names);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyObject *slice_to_unicode(PyObject *body, const StrSlice *sl) {
    if (!sl->present) Py_RETURN_NONE;
    const char *base = PyBytes_AS_STRING(body);
    if (!sl->escaped)
        return PyUnicode_DecodeUTF8(base + sl->off, sl->len, "strict");
    /* rare: route through the json module for exact escape handling */
    PyObject *json_mod = PyImport_ImportModule("json");
    if (!json_mod) return NULL;
    PyObject *raw = PyUnicode_DecodeUTF8(base + sl->off - 1, sl->len + 2, "strict");
    if (!raw) { Py_DECREF(json_mod); return NULL; }
    PyObject *res = PyObject_CallMethod(json_mod, "loads", "O", raw);
    Py_DECREF(raw);
    Py_DECREF(json_mod);
    return res;
}

static PyObject *ParsedArgs_get(ParsedArgs *self, void *closure) {
    const char *which = (const char *)closure;
    if (strcmp(which, "pod_name") == 0)
        return slice_to_unicode(self->body, &self->pod_name);
    if (strcmp(which, "pod_namespace") == 0)
        return slice_to_unicode(self->body, &self->pod_namespace);
    if (strcmp(which, "policy_label") == 0) {
        if (!self->has_label) Py_RETURN_NONE;
        return slice_to_unicode(self->body, &self->policy_label);
    }
    if (strcmp(which, "nodes_present") == 0)
        return PyBool_FromLong(self->nodes_present);
    if (strcmp(which, "num_nodes") == 0)
        return PyLong_FromSsize_t(self->num_names);
    if (strcmp(which, "node_names_present") == 0)
        return PyBool_FromLong(self->node_names_present);
    if (strcmp(which, "num_node_names") == 0)
        return PyLong_FromSsize_t(self->num_nn_names);
    Py_RETURN_NONE;
}

static PyObject *materialize_names(PyObject *body, const StrSlice *slices,
                                   Py_ssize_t count) {
    PyObject *list = PyList_New(count);
    if (!list) return NULL;
    for (Py_ssize_t k = 0; k < count; k++) {
        PyObject *u = slice_to_unicode(body, &slices[k]);
        if (!u) { Py_DECREF(list); return NULL; }
        PyList_SET_ITEM(list, k, u);
    }
    return list;
}

static PyObject *ParsedArgs_node_names(ParsedArgs *self, PyObject *noargs) {
    (void)noargs;
    return materialize_names(self->body, self->names, self->num_names);
}

static PyObject *ParsedArgs_node_names_list(ParsedArgs *self, PyObject *noargs) {
    (void)noargs;
    return materialize_names(self->body, self->nn_names, self->num_nn_names);
}

static PyObject *span_copy(ParsedArgs *self, Py_ssize_t start, Py_ssize_t end) {
    if (start < 0) Py_RETURN_NONE;
    return PyBytes_FromStringAndSize(
        PyBytes_AS_STRING(self->body) + start, end - start);
}

static PyObject *ParsedArgs_nodes_span(ParsedArgs *self, PyObject *noargs) {
    (void)noargs;
    return span_copy(self, self->nodes_span_start, self->nodes_span_end);
}

static PyObject *ParsedArgs_nn_span(ParsedArgs *self, PyObject *noargs) {
    (void)noargs;
    return span_copy(self, self->nn_span_start, self->nn_span_end);
}

static PyObject *ParsedArgs_span_matches(ParsedArgs *self, PyObject *args) {
    /* span_matches(use_node_names, candidate: bytes) -> bool
     * memcmp of the raw candidate-list span against a cached span — the
     * zero-false-positive verify of the response-reuse cache, without
     * materializing the span (memoryview __eq__ is per-byte-slow and
     * bytes() would copy ~hundreds of KB per probe). */
    int use_node_names;
    PyObject *cand;
    if (!PyArg_ParseTuple(args, "pO", &use_node_names, &cand)) return NULL;
    if (!PyBytes_Check(cand)) {
        PyErr_SetString(PyExc_TypeError, "candidate span must be bytes");
        return NULL;
    }
    Py_ssize_t start = use_node_names ? self->nn_span_start
                                      : self->nodes_span_start;
    Py_ssize_t end = use_node_names ? self->nn_span_end : self->nodes_span_end;
    if (start < 0) Py_RETURN_FALSE;
    Py_ssize_t len = end - start;
    if (len != PyBytes_GET_SIZE(cand)) Py_RETURN_FALSE;
    int equal;
    const char *a = PyBytes_AS_STRING(self->body) + start;
    const char *b = PyBytes_AS_STRING(cand);
    Py_BEGIN_ALLOW_THREADS
    equal = memcmp(a, b, (size_t)len) == 0;
    Py_END_ALLOW_THREADS
    return PyBool_FromLong(equal);
}

static PyGetSetDef ParsedArgs_getset[] = {
    {"pod_name", (getter)ParsedArgs_get, NULL, NULL, "pod_name"},
    {"pod_namespace", (getter)ParsedArgs_get, NULL, NULL, "pod_namespace"},
    {"policy_label", (getter)ParsedArgs_get, NULL, NULL, "policy_label"},
    {"nodes_present", (getter)ParsedArgs_get, NULL, NULL, "nodes_present"},
    {"num_nodes", (getter)ParsedArgs_get, NULL, NULL, "num_nodes"},
    {"node_names_present", (getter)ParsedArgs_get, NULL, NULL,
     "node_names_present"},
    {"num_node_names", (getter)ParsedArgs_get, NULL, NULL, "num_node_names"},
    {NULL},
};

static PyMethodDef ParsedArgs_methods[] = {
    {"node_names", (PyCFunction)ParsedArgs_node_names, METH_NOARGS,
     "Materialize the Nodes.items name list (slow path / debugging)."},
    {"node_names_list", (PyCFunction)ParsedArgs_node_names_list, METH_NOARGS,
     "Materialize the NodeNames list (nodeCacheCapable mode)."},
    {"nodes_span", (PyCFunction)ParsedArgs_nodes_span, METH_NOARGS,
     "Copy of the raw Nodes JSON value bytes, or None."},
    {"node_names_span", (PyCFunction)ParsedArgs_nn_span, METH_NOARGS,
     "Copy of the raw NodeNames JSON value bytes, or None."},
    {"span_matches", (PyCFunction)ParsedArgs_span_matches, METH_VARARGS,
     "memcmp the request's candidate span against cached span bytes."},
    {NULL},
};

static PyTypeObject ParsedArgs_Type = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_wirec.ParsedArgs",
    .tp_basicsize = sizeof(ParsedArgs),
    .tp_dealloc = (destructor)ParsedArgs_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_getset = ParsedArgs_getset,
    .tp_methods = ParsedArgs_methods,
};

/* -- Args-shaped scanning ------------------------------------------- */

#define NAME_CHUNK 1024

static int scan_pod_metadata(Scan *sc, ParsedArgs *pa) {
    skip_ws(sc);
    if (sc->i >= sc->n) return fail("eof in metadata");
    /* duplicate "metadata" keys: last wins like json.loads — the new value
     * (object or null) fully replaces fields from an earlier occurrence */
    memset(&pa->pod_name, 0, sizeof(StrSlice));
    memset(&pa->pod_namespace, 0, sizeof(StrSlice));
    memset(&pa->policy_label, 0, sizeof(StrSlice));
    pa->has_label = 0;
    if (sc->s[sc->i] == 'n') return skip_literal(sc, "null", 4);
    if (sc->s[sc->i] != '{') return fail("metadata not object");
    sc->i++;
    skip_ws(sc);
    if (sc->i < sc->n && sc->s[sc->i] == '}') { sc->i++; return 0; }
    for (;;) {
        skip_ws(sc);
        StrSlice key;
        if (scan_string(sc, &key) < 0) return -1;
        if (key.escaped) return fail("escaped key");
        skip_ws(sc);
        if (sc->i >= sc->n || sc->s[sc->i] != ':') return fail("expected ':'");
        sc->i++;
        skip_ws(sc);
        const char *kp = sc->s + key.off;
        if (key.len == 4 && memcmp(kp, "name", 4) == 0) {
            if (sc->i < sc->n && sc->s[sc->i] == '"') {
                if (scan_string(sc, &pa->pod_name) < 0) return -1;
            } else if (sc->i < sc->n && sc->s[sc->i] == 'n') {
                /* null into a string is Go's zero value "": a repeated
                 * key's null clears an earlier captured string */
                memset(&pa->pod_name, 0, sizeof(StrSlice));
                if (skip_literal(sc, "null", 4) < 0) return -1;
            } else {
                return fail("pod name not string");  /* Go decode error */
            }
        } else if (key.len == 9 && memcmp(kp, "namespace", 9) == 0) {
            if (sc->i < sc->n && sc->s[sc->i] == '"') {
                if (scan_string(sc, &pa->pod_namespace) < 0) return -1;
            } else if (sc->i < sc->n && sc->s[sc->i] == 'n') {
                memset(&pa->pod_namespace, 0, sizeof(StrSlice));
                if (skip_literal(sc, "null", 4) < 0) return -1;
            } else {
                return fail("pod namespace not string");
            }
        } else if (key.len == 6 && memcmp(kp, "labels", 6) == 0) {
            /* scan the labels object for "telemetry-policy"; a repeated
             * "labels" key replaces any label from an earlier occurrence */
            memset(&pa->policy_label, 0, sizeof(StrSlice));
            pa->has_label = 0;
            skip_ws(sc);
            if (sc->i < sc->n && sc->s[sc->i] == '{') {
                sc->i++;
                skip_ws(sc);
                if (sc->i < sc->n && sc->s[sc->i] == '}') { sc->i++; }
                else for (;;) {
                    skip_ws(sc);
                    StrSlice lkey;
                    if (scan_string(sc, &lkey) < 0) return -1;
                    if (lkey.escaped) return fail("escaped key");
                    skip_ws(sc);
                    if (sc->i >= sc->n || sc->s[sc->i] != ':')
                        return fail("expected ':'");
                    sc->i++;
                    skip_ws(sc);
                    if (lkey.len == 16 &&
                        memcmp(sc->s + lkey.off, "telemetry-policy", 16) == 0) {
                        if (sc->i < sc->n && sc->s[sc->i] == '"') {
                            if (scan_string(sc, &pa->policy_label) < 0)
                                return -1;
                            pa->has_label = 1;
                        } else if (sc->i < sc->n && sc->s[sc->i] == 'n') {
                            /* null label value: Go zero value "" (the
                             * exact path normalizes identically) */
                            if (skip_literal(sc, "null", 4) < 0) return -1;
                            memset(&pa->policy_label, 0, sizeof(StrSlice));
                            pa->policy_label.present = 1;  /* "" */
                            pa->has_label = 1;
                        } else {
                            return fail("label not string");
                        }
                    } else {
                        /* map[string]string: label values must be strings
                         * (or null -> zero value ""); anything else fails
                         * the Go decode — matched by from_json */
                        if (sc->i < sc->n && sc->s[sc->i] == '"') {
                            if (skip_value(sc) < 0) return -1;
                        } else if (sc->i < sc->n && sc->s[sc->i] == 'n') {
                            if (skip_literal(sc, "null", 4) < 0) return -1;
                        } else {
                            return fail("label not string");
                        }
                    }
                    skip_ws(sc);
                    if (sc->i >= sc->n) return fail("unterminated labels");
                    if (sc->s[sc->i] == ',') { sc->i++; continue; }
                    if (sc->s[sc->i] == '}') { sc->i++; break; }
                    return fail("bad labels");
                }
            } else if (sc->i < sc->n && sc->s[sc->i] == 'n') {
                /* null labels: Go zero-value map (clears, no error) */
                if (skip_literal(sc, "null", 4) < 0) return -1;
            } else {
                return fail("labels not object");
            }
        } else {
            if (skip_value(sc) < 0) return -1;
        }
        skip_ws(sc);
        if (sc->i >= sc->n) return fail("unterminated metadata");
        if (sc->s[sc->i] == ',') { sc->i++; continue; }
        if (sc->s[sc->i] == '}') { sc->i++; return 0; }
        return fail("bad metadata");
    }
}

static int scan_pod(Scan *sc, ParsedArgs *pa) {
    skip_ws(sc);
    if (sc->i >= sc->n) return fail("eof in Pod");
    /* "Pod": null — Go decodes null into a VALUE struct as "no effect"
     * (the reference's Args.Pod is v1.Pod by value), so fields captured
     * from an earlier duplicate occurrence must survive; contrast the
     * pointer-typed Nodes/NodeNames where null assigns nil */
    if (sc->s[sc->i] == 'n') return skip_literal(sc, "null", 4);
    /* duplicate top-level "Pod" keys carrying objects: last wins */
    memset(&pa->pod_name, 0, sizeof(StrSlice));
    memset(&pa->pod_namespace, 0, sizeof(StrSlice));
    memset(&pa->policy_label, 0, sizeof(StrSlice));
    pa->has_label = 0;
    if (sc->s[sc->i] != '{') return fail("Pod not object");
    sc->i++;
    skip_ws(sc);
    if (sc->i < sc->n && sc->s[sc->i] == '}') { sc->i++; return 0; }
    for (;;) {
        skip_ws(sc);
        StrSlice key;
        if (scan_string(sc, &key) < 0) return -1;
        if (key.escaped) return fail("escaped key");
        skip_ws(sc);
        if (sc->i >= sc->n || sc->s[sc->i] != ':') return fail("expected ':'");
        sc->i++;
        if (key.len == 8 &&
            memcmp(sc->s + key.off, "metadata", 8) == 0) {
            if (scan_pod_metadata(sc, pa) < 0) return -1;
        } else {
            if (skip_value(sc) < 0) return -1;
        }
        skip_ws(sc);
        if (sc->i >= sc->n) return fail("unterminated Pod");
        if (sc->s[sc->i] == ',') { sc->i++; continue; }
        if (sc->s[sc->i] == '}') { sc->i++; return 0; }
        return fail("bad Pod");
    }
}

/* process-wide high-water candidate count: the first growth of a name
 * array jumps straight to the size recent requests needed, collapsing
 * the realloc chain (each step past the mmap threshold is a fresh
 * mapping + copy — p99 churn at 10k nodes).  Atomic because the server
 * is thread-per-connection (a per-thread hint would reset every
 * connection); relaxed ordering — the hint is only an optimization.
 * Capped at NAME_HINT_MAX slots: the hint is driven by untrusted
 * request content, and without a ceiling one huge NodeNames body would
 * permanently raise the initial allocation for every later request
 * (64k slots = 2 MB of StrSlice, comfortably above any real cluster;
 * larger requests still parse — they just grow from the cap). */
#include <stdatomic.h>
#define NAME_HINT_MAX 65536
static _Atomic Py_ssize_t names_hint = NAME_CHUNK;

static Py_ssize_t grow_cap(Py_ssize_t cap) {
    return cap ? cap * 2
               : atomic_load_explicit(&names_hint, memory_order_relaxed);
}

static int push_name(Scan *sc, ParsedArgs *pa, Py_ssize_t *cap,
                     const StrSlice *sl) {
    if (pa->num_names == *cap) {
        Py_ssize_t ncap = grow_cap(*cap);
        StrSlice *nn = realloc(pa->names, ncap * sizeof(StrSlice));
        if (!nn) return fail("out of memory");
        pa->names = nn;
        *cap = ncap;
    }
    pa->names[pa->num_names++] = *sl;
    return 0;
}

static int scan_node_item(Scan *sc, ParsedArgs *pa, Py_ssize_t *cap) {
    /* one Nodes.items entry: capture metadata.name, skip the rest */
    skip_ws(sc);
    if (sc->i >= sc->n || sc->s[sc->i] != '{') return fail("node not object");
    sc->i++;
    skip_ws(sc);
    StrSlice name = {0, 0, 0, 0};
    if (sc->i < sc->n && sc->s[sc->i] == '}') { sc->i++; goto done; }
    for (;;) {
        skip_ws(sc);
        StrSlice key;
        if (scan_string(sc, &key) < 0) return -1;
        if (key.escaped) return fail("escaped key");
        skip_ws(sc);
        if (sc->i >= sc->n || sc->s[sc->i] != ':') return fail("expected ':'");
        sc->i++;
        if (key.len == 8 &&
            memcmp(sc->s + key.off, "metadata", 8) == 0) {
            skip_ws(sc);
            if (sc->i >= sc->n) return fail("eof in node metadata");
            /* repeated "metadata" key: last wins — the new value replaces
             * any name captured from an earlier occurrence.  null clears
             * to the zero value; any other non-object is a decode error
             * (as in Go), so the exact path owns the response */
            memset(&name, 0, sizeof(StrSlice));
            if (sc->s[sc->i] == 'n') {
                if (skip_literal(sc, "null", 4) < 0) return -1;
            } else if (sc->s[sc->i] != '{') {
                return fail("node metadata not object");
            } else {
                sc->i++;
                skip_ws(sc);
                if (sc->i < sc->n && sc->s[sc->i] == '}') { sc->i++; }
                else for (;;) {
                    skip_ws(sc);
                    StrSlice mkey;
                    if (scan_string(sc, &mkey) < 0) return -1;
                    if (mkey.escaped) return fail("escaped key");
                    skip_ws(sc);
                    if (sc->i >= sc->n || sc->s[sc->i] != ':')
                        return fail("expected ':'");
                    sc->i++;
                    skip_ws(sc);
                    if (mkey.len == 4 &&
                        memcmp(sc->s + mkey.off, "name", 4) == 0) {
                        if (sc->i < sc->n && sc->s[sc->i] == '"') {
                            if (scan_string(sc, &name) < 0) return -1;
                        } else if (sc->i < sc->n && sc->s[sc->i] == 'n') {
                            /* null into a string: Go zero value "" */
                            memset(&name, 0, sizeof(StrSlice));
                            if (skip_literal(sc, "null", 4) < 0) return -1;
                        } else {
                            /* Go: UnmarshalTypeError — decode fails */
                            return fail("node name not string");
                        }
                    } else if (skip_value(sc) < 0) return -1;
                    skip_ws(sc);
                    if (sc->i >= sc->n) return fail("unterminated node metadata");
                    if (sc->s[sc->i] == ',') { sc->i++; continue; }
                    if (sc->s[sc->i] == '}') { sc->i++; break; }
                    return fail("bad node metadata");
                }
            }
        } else {
            if (skip_value(sc) < 0) return -1;
        }
        skip_ws(sc);
        if (sc->i >= sc->n) return fail("unterminated node");
        if (sc->s[sc->i] == ',') { sc->i++; continue; }
        if (sc->s[sc->i] == '}') { sc->i++; break; }
        return fail("bad node");
    }
done:
    /* a node item whose metadata carries no name (absent key, null name,
     * or null metadata) is the Go zero value "" — a PRESENT empty name
     * that participates in candidate matching exactly as the Python
     * decode's Node({}).name == "" does (the round-5 differential fuzzer
     * caught the old drop-it behavior diverging when "" is an interned
     * node).  Non-string names fail the parse above, as in Go. */
    if (!name.present) {
        name.off = 0; name.len = 0; name.escaped = 0; name.present = 1;
    }
    return push_name(sc, pa, cap, &name);
}

static int push_nn_name(Scan *sc, ParsedArgs *pa, Py_ssize_t *cap,
                        const StrSlice *sl) {
    if (pa->num_nn_names == *cap) {
        Py_ssize_t ncap = grow_cap(*cap);
        StrSlice *nn = realloc(pa->nn_names, ncap * sizeof(StrSlice));
        if (!nn) return fail("out of memory");
        pa->nn_names = nn;
        *cap = ncap;
    }
    pa->nn_names[pa->num_nn_names++] = *sl;
    return 0;
}

/* Batch-validated scan of a NodeNames array positioned at '['.
 *
 * Per-name scan_string pays ~2x the structural cost in validation
 * bookkeeping; at 10k names that is most of the request's parse floor
 * (BENCH_r05 filter_floor_breakdown: parse 173 us).  Here names are
 * recorded by bare memchr quote pairs and validated by ONE SWAR sweep
 * over the whole array span at the end: a clean sweep (no control
 * bytes, no backslash, no >= 0x80 — exactly scan_string's special set)
 * proves every recorded slice is an unescaped plain-ASCII string, i.e.
 * precisely what the strict loop would have produced.  Any special
 * byte anywhere (escapes, UTF-8, \t/\n between elements, an escaped
 * quote that desynced a memchr pair) returns 0 and the caller rescans
 * the same region with the strict loop from scratch — so acceptance
 * and slices can never diverge from the strict scanner's.
 * Returns 1 on success, 0 on fall-back (state rewound), -1 on error. */
static int scan_node_names_fast(Scan *sc, ParsedArgs *pa, Py_ssize_t *cap) {
    Py_ssize_t arr_start = sc->i;  /* at '[' */
    Py_ssize_t i = arr_start + 1;
    const char *s = sc->s;
    Py_ssize_t n = sc->n;
    while (i < n && s[i] == ' ') i++;
    if (i < n && s[i] == ']') {
        sc->i = i + 1;
        return 1;
    }
    for (;;) {
        while (i < n && s[i] == ' ') i++;
        if (i >= n || s[i] != '"') goto fallback;
        const char *q = memchr(s + i + 1, '"', (size_t)(n - i - 1));
        if (!q) goto fallback;
        StrSlice name;
        name.off = i + 1;
        name.len = (Py_ssize_t)(q - (s + i + 1));
        name.escaped = 0;
        name.present = 1;
        if (push_nn_name(sc, pa, cap, &name) < 0) return -1;
        i = (Py_ssize_t)(q - s) + 1;
        while (i < n && s[i] == ' ') i++;
        if (i >= n) goto fallback;
        if (s[i] == ',') { i++; continue; }
        if (s[i] == ']') break;
        goto fallback;
    }
    /* the one validation sweep: [just past '[', the closing ']') */
    if (span_has_special(s + arr_start + 1, i - arr_start - 1)) goto fallback;
    sc->i = i + 1;
    return 1;

fallback:
    sc->i = arr_start;
    pa->num_nn_names = 0;
    return 0;
}

/* "NodeNames": null | array of strings (nodeCacheCapable mode,
 * extender/types.go:44-49); strict: non-string elements fail the parse */
static int scan_node_names(Scan *sc, ParsedArgs *pa, Py_ssize_t *cap) {
    skip_ws(sc);
    if (sc->i >= sc->n) return fail("eof in NodeNames");
    /* duplicate "NodeNames" keys: last wins */
    pa->node_names_present = 0;
    pa->num_nn_names = 0;
    pa->nn_span_start = sc->i;
    if (sc->s[sc->i] == 'n') {
        if (skip_literal(sc, "null", 4) < 0) return -1;
        pa->nn_span_end = sc->i;
        return 0;
    }
    if (sc->s[sc->i] != '[') return fail("NodeNames not array");
    pa->node_names_present = 1;
    {
        int fast = scan_node_names_fast(sc, pa, cap);
        if (fast < 0) return -1;
        if (fast) {
            pa->nn_span_end = sc->i;
            return 0;
        }
    }
    sc->i++;
    skip_ws(sc);
    if (sc->i < sc->n && sc->s[sc->i] == ']') {
        sc->i++;
        pa->nn_span_end = sc->i;
        return 0;
    }
    for (;;) {
        skip_ws(sc);
        StrSlice name;
        if (scan_string(sc, &name) < 0) return -1;
        if (push_nn_name(sc, pa, cap, &name) < 0) return -1;
        skip_ws(sc);
        if (sc->i >= sc->n) return fail("unterminated NodeNames");
        if (sc->s[sc->i] == ',') { sc->i++; continue; }
        if (sc->s[sc->i] == ']') {
            sc->i++;
            pa->nn_span_end = sc->i;
            return 0;
        }
        return fail("bad NodeNames");
    }
}

static int scan_nodes(Scan *sc, ParsedArgs *pa, Py_ssize_t *cap) {
    skip_ws(sc);
    if (sc->i >= sc->n) return fail("eof in Nodes");
    pa->nodes_span_start = sc->i;
    if (sc->s[sc->i] == 'n') {
        int rc = skip_literal(sc, "null", 4);
        pa->nodes_span_end = sc->i;
        return rc;
    }
    if (sc->s[sc->i] != '{') return fail("Nodes not object");
    sc->i++;
    skip_ws(sc);
    if (sc->i < sc->n && sc->s[sc->i] == '}') {
        sc->i++;
        pa->nodes_span_end = sc->i;
        return 0;
    }
    for (;;) {
        skip_ws(sc);
        StrSlice key;
        if (scan_string(sc, &key) < 0) return -1;
        if (key.escaped) return fail("escaped key");
        skip_ws(sc);
        if (sc->i >= sc->n || sc->s[sc->i] != ':') return fail("expected ':'");
        sc->i++;
        if (key.len == 5 &&
            memcmp(sc->s + key.off, "items", 5) == 0) {
            skip_ws(sc);
            if (sc->i < sc->n && sc->s[sc->i] == 'n') {
                if (skip_literal(sc, "null", 4) < 0) return -1;
                pa->nodes_present = 1;  /* Nodes object exists, items null */
                pa->num_names = 0;      /* last-wins: null replaces any array */
            } else if (sc->i < sc->n && sc->s[sc->i] == '[') {
                pa->nodes_present = 1;
                /* duplicate "items" keys: last wins like json.loads */
                pa->num_names = 0;
                sc->i++;
                skip_ws(sc);
                if (sc->i < sc->n && sc->s[sc->i] == ']') { sc->i++; }
                else for (;;) {
                    if (scan_node_item(sc, pa, cap) < 0) return -1;
                    skip_ws(sc);
                    if (sc->i >= sc->n) return fail("unterminated items");
                    if (sc->s[sc->i] == ',') { sc->i++; continue; }
                    if (sc->s[sc->i] == ']') { sc->i++; break; }
                    return fail("bad items");
                }
            } else {
                return fail("items not array");
            }
        } else {
            if (skip_value(sc) < 0) return -1;
        }
        skip_ws(sc);
        if (sc->i >= sc->n) return fail("unterminated Nodes");
        if (sc->s[sc->i] == ',') { sc->i++; continue; }
        if (sc->s[sc->i] == '}') {
            sc->i++;
            pa->nodes_span_end = sc->i;
            return 0;
        }
        return fail("bad Nodes");
    }
}

static PyObject *wirec_parse_prioritize(PyObject *mod, PyObject *arg) {
    (void)mod;
    if (!PyBytes_Check(arg)) {
        PyErr_SetString(PyExc_TypeError, "body must be bytes");
        return NULL;
    }
    ParsedArgs *pa = PyObject_New(ParsedArgs, &ParsedArgs_Type);
    if (!pa) return NULL;
    Py_INCREF(arg);
    pa->body = arg;
    memset(&pa->pod_name, 0, sizeof(StrSlice));
    memset(&pa->pod_namespace, 0, sizeof(StrSlice));
    memset(&pa->policy_label, 0, sizeof(StrSlice));
    pa->has_label = 0;
    pa->nodes_present = 0;
    pa->names = NULL;
    pa->num_names = 0;
    pa->node_names_present = 0;
    pa->nn_names = NULL;
    pa->num_nn_names = 0;
    pa->nodes_span_start = pa->nodes_span_end = -1;
    pa->nn_span_start = pa->nn_span_end = -1;
    Py_ssize_t cap = 0;
    Py_ssize_t nn_cap = 0;

    Scan scan_state = {PyBytes_AS_STRING(arg), PyBytes_GET_SIZE(arg), 0, NULL};
    Scan *sc = &scan_state;
    int ok = 1;
    /* the scan touches only raw body bytes + raw-allocated name slices, so
     * it runs without the GIL: concurrent requests parse in parallel */
    Py_BEGIN_ALLOW_THREADS
    skip_ws(sc);
    if (sc->i >= sc->n || sc->s[sc->i] != '{') {
        fail("body not a JSON object");
        ok = 0;
    } else {
        sc->i++;
        skip_ws(sc);
        if (sc->i < sc->n && sc->s[sc->i] == '}') { sc->i++; }
        else for (;;) {
            skip_ws(sc);
            StrSlice key;
            if (scan_string(sc, &key) < 0) { ok = 0; break; }
            if (key.escaped) { fail("escaped key"); ok = 0; break; }
            skip_ws(sc);
            if (sc->i >= sc->n || sc->s[sc->i] != ':') {
                fail("expected ':'");
                ok = 0;
                break;
            }
            sc->i++;
            const char *kp = sc->s + key.off;
            int handled = 0;
            if (key_is_ci(kp, key.len, "pod", 3)) {
                if (scan_pod(sc, pa) < 0) { ok = 0; break; }
                handled = 1;
            } else if (key_is_ci(kp, key.len, "nodes", 5)) {
                pa->nodes_present = 0;
                pa->num_names = 0;
                pa->nodes_span_start = pa->nodes_span_end = -1;
                if (scan_nodes(sc, pa, &cap) < 0) { ok = 0; break; }
                handled = 1;
            } else if (key_is_ci(kp, key.len, "nodenames", 9)) {
                if (scan_node_names(sc, pa, &nn_cap) < 0) { ok = 0; break; }
                handled = 1;
            }
            if (!handled && skip_value(sc) < 0) { ok = 0; break; }
            skip_ws(sc);
            if (sc->i >= sc->n) { fail("unterminated body"); ok = 0; break; }
            if (sc->s[sc->i] == ',') { sc->i++; continue; }
            if (sc->s[sc->i] == '}') { sc->i++; break; }
            fail("bad body");
            ok = 0;
            break;
        }
        if (ok) {
            skip_ws(sc);
            if (sc->i != sc->n) { fail("trailing data"); ok = 0; }
        }
    }
    Py_END_ALLOW_THREADS
    if (!ok) {
        Py_DECREF(pa);
        PyErr_SetString(PyExc_ValueError, sc->err ? sc->err : "parse error");
        return NULL;
    }
    /* remember this request's candidate count so the next request's
     * array starts at the right size (process-wide atomic, relaxed —
     * the hint is only an allocation-size optimization) */
    Py_ssize_t seen = pa->num_names > pa->num_nn_names ? pa->num_names
                                                       : pa->num_nn_names;
    if (seen > NAME_HINT_MAX) seen = NAME_HINT_MAX;
    if (seen > atomic_load_explicit(&names_hint, memory_order_relaxed)) {
        Py_ssize_t h = NAME_CHUNK;
        while (h < seen) h *= 2;
        atomic_store_explicit(&names_hint, h, memory_order_relaxed);
    }
    return (PyObject *)pa;
}

/* ------------------------------------------------------------------ */
/* NameTable: name -> row hash map + response fragments                */

typedef struct {
    PyObject_HEAD
    Py_ssize_t n_rows;
    /* open addressing table of 2^bits slots, each slot = row+1 (0=empty) */
    uint32_t *slots;
    uint32_t mask;
    /* interned copies of names (concatenated) for collision verification */
    char *name_bytes;
    Py_ssize_t *name_off;  /* n_rows + 1 offsets */
    /* pre-rendered fragments: {"Host": "<name>", "Score":  */
    char *frag_bytes;
    Py_ssize_t *frag_off;  /* n_rows + 1 offsets */
} NameTable;

static void NameTable_dealloc(NameTable *self) {
    PyMem_Free(self->slots);
    /* name_bytes/frag_bytes are Buf storage (malloc) — free with free();
     * mixing allocators is undefined behavior under PYTHONMALLOC=debug */
    free(self->name_bytes);
    PyMem_Free(self->name_off);
    free(self->frag_bytes);
    PyMem_Free(self->frag_off);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static uint64_t fnv1a(const char *s, Py_ssize_t n) {
    uint64_t h = 1469598103934665603ULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        h ^= (unsigned char)s[i];
        h *= 1099511628211ULL;
    }
    return h;
}

/* row lookup by raw (unescaped) name bytes; -1 if absent */
static Py_ssize_t table_lookup(NameTable *t, const char *s, Py_ssize_t n) {
    uint64_t h = fnv1a(s, n);
    uint32_t idx = (uint32_t)h & t->mask;
    for (;;) {
        uint32_t slot = t->slots[idx];
        if (slot == 0) return -1;
        Py_ssize_t row = (Py_ssize_t)slot - 1;
        Py_ssize_t off = t->name_off[row];
        Py_ssize_t len = t->name_off[row + 1] - off;
        if (len == n && memcmp(t->name_bytes + off, s, n) == 0) return row;
        idx = (idx + 1) & t->mask;
    }
}

static PyTypeObject NameTable_Type = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_wirec.NameTable",
    .tp_basicsize = sizeof(NameTable),
    .tp_dealloc = (destructor)NameTable_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
};

static PyObject *wirec_build_table(PyObject *mod, PyObject *arg) {
    (void)mod;
    /* arg: sequence of str node names in row order; fragments use
     * json-exact escaping via json.dumps for non-ASCII-simple names */
    PyObject *seq = PySequence_Fast(arg, "expected a sequence of names");
    if (!seq) return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    NameTable *t = PyObject_New(NameTable, &NameTable_Type);
    if (!t) { Py_DECREF(seq); return NULL; }
    t->n_rows = n;
    t->slots = NULL;
    t->name_bytes = NULL;
    t->name_off = NULL;
    t->frag_bytes = NULL;
    t->frag_off = NULL;

    uint32_t bits = 3;
    while ((1u << bits) < (uint32_t)(n * 2 + 4)) bits++;
    uint32_t size = 1u << bits;
    t->mask = size - 1;
    t->slots = PyMem_Calloc(size, sizeof(uint32_t));
    t->name_off = PyMem_Malloc((n + 1) * sizeof(Py_ssize_t));
    t->frag_off = PyMem_Malloc((n + 1) * sizeof(Py_ssize_t));
    if (!t->slots || !t->name_off || !t->frag_off) {
        PyErr_NoMemory();
        goto error;
    }

    Buf names_buf, frag_buf;
    if (buf_init(&names_buf, 64 * (n + 1)) < 0) { PyErr_NoMemory(); goto error; }
    if (buf_init(&frag_buf, 96 * (n + 1)) < 0) {
        buf_free(&names_buf);
        PyErr_NoMemory();
        goto error;
    }

    PyObject *json_mod = NULL;
    for (Py_ssize_t row = 0; row < n; row++) {
        PyObject *name = PySequence_Fast_GET_ITEM(seq, row);
        Py_ssize_t nlen;
        const char *ns = PyUnicode_AsUTF8AndSize(name, &nlen);
        if (!ns) goto error_bufs;
        t->name_off[row] = (Py_ssize_t)names_buf.len;
        if (buf_put(&names_buf, ns, nlen) < 0) goto error_bufs;

        /* fragment */
        t->frag_off[row] = (Py_ssize_t)frag_buf.len;
        int needs_escape = 0;
        for (Py_ssize_t k = 0; k < nlen; k++) {
            unsigned char c = (unsigned char)ns[k];
            if (c == '"' || c == '\\' || c < 0x20 || c >= 0x7f) {
                needs_escape = 1;
                break;
            }
        }
        if (buf_put(&frag_buf, "{\"Host\": ", 9) < 0) goto error_bufs;
        if (!needs_escape) {
            if (buf_put(&frag_buf, "\"", 1) < 0) goto error_bufs;
            if (buf_put(&frag_buf, ns, nlen) < 0) goto error_bufs;
            if (buf_put(&frag_buf, "\"", 1) < 0) goto error_bufs;
        } else {
            if (!json_mod) {
                json_mod = PyImport_ImportModule("json");
                if (!json_mod) goto error_bufs;
            }
            PyObject *enc = PyObject_CallMethod(json_mod, "dumps", "O", name);
            if (!enc) goto error_bufs;
            Py_ssize_t elen;
            const char *es = PyUnicode_AsUTF8AndSize(enc, &elen);
            if (!es || buf_put(&frag_buf, es, elen) < 0) {
                Py_DECREF(enc);
                goto error_bufs;
            }
            Py_DECREF(enc);
        }
        if (buf_put(&frag_buf, ", \"Score\": ", 11) < 0) goto error_bufs;
    }
    t->name_off[n] = (Py_ssize_t)names_buf.len;
    t->frag_off[n] = (Py_ssize_t)frag_buf.len;
    Py_XDECREF(json_mod);
    json_mod = NULL;

    t->name_bytes = names_buf.data;  /* ownership moves */
    t->frag_bytes = frag_buf.data;

    /* populate hash slots (first writer wins; duplicate names share the
     * earlier row, which matches dict interning order semantics) */
    for (Py_ssize_t row = 0; row < n; row++) {
        Py_ssize_t off = t->name_off[row];
        Py_ssize_t len = t->name_off[row + 1] - off;
        uint64_t h = fnv1a(t->name_bytes + off, len);
        uint32_t idx = (uint32_t)h & t->mask;
        for (;;) {
            if (t->slots[idx] == 0) {
                t->slots[idx] = (uint32_t)(row + 1);
                break;
            }
            Py_ssize_t prow = (Py_ssize_t)t->slots[idx] - 1;
            Py_ssize_t poff = t->name_off[prow];
            Py_ssize_t plen = t->name_off[prow + 1] - poff;
            if (plen == len &&
                memcmp(t->name_bytes + poff, t->name_bytes + off, len) == 0)
                break;  /* duplicate name: keep first row */
            idx = (idx + 1) & t->mask;
        }
    }
    Py_DECREF(seq);
    return (PyObject *)t;

error_bufs:
    Py_XDECREF(json_mod);
    buf_free(&names_buf);
    buf_free(&frag_buf);
error:
    Py_DECREF(seq);
    Py_DECREF(t);
    return NULL;
}

/* ------------------------------------------------------------------ */
/* select_encode                                                       */

/* decimal render of score + '}' — snprintf is ~10x slower and sits on the
 * per-row hot path of a 10k-entry response */
static int put_score(Buf *b, long score) {
    char tmp[24];
    char *end = tmp + sizeof(tmp);
    char *p = end;
    *--p = '}';
    unsigned long v = score < 0 ? (unsigned long)(-score) : (unsigned long)score;
    do {
        *--p = (char)('0' + (v % 10));
        v /= 10;
    } while (v);
    if (score < 0) *--p = '-';
    return buf_put(b, p, (size_t)(end - p));
}

/* THE Prioritize emit loop — the one copy both select_encode and
 * select_encode_universe compile from, so warm-universe bytes can never
 * drift from the cold path's: candidate mask + global rank order ->
 * "[{fragment}<score>, ...]\n" with optional planned-row promotion to
 * rank 1.  0 on success, -1 on OOM. */
static int emit_ranked(Buf *out, NameTable *t, const uint8_t *mask,
                       const int64_t *order, Py_ssize_t n_ranked,
                       Py_ssize_t planned_row) {
    int promote = 0;
    if (planned_row >= 0 && planned_row < t->n_rows && mask[planned_row]) {
        /* planned node goes first iff it appears in the ranked order */
        for (Py_ssize_t k = 0; k < n_ranked; k++) {
            if (order[k] == planned_row) { promote = 1; break; }
        }
    }
    long rank = 0;
    int first = 1;
    if (buf_put(out, "[", 1) < 0) return -1;
    if (promote) {
        Py_ssize_t off = t->frag_off[planned_row];
        if (buf_put(out, t->frag_bytes + off,
                    (size_t)(t->frag_off[planned_row + 1] - off)) < 0 ||
            put_score(out, 10) < 0)
            return -1;
        rank = 1;
        first = 0;
    }
    for (Py_ssize_t k = 0; k < n_ranked; k++) {
        int64_t row = order[k];
        if (row < 0 || row >= t->n_rows || !mask[row]) continue;
        if (promote && row == planned_row) continue;
        if (!first && buf_put(out, ", ", 2) < 0) return -1;
        first = 0;
        Py_ssize_t off = t->frag_off[row];
        if (buf_put(out, t->frag_bytes + off,
                    (size_t)(t->frag_off[row + 1] - off)) < 0 ||
            put_score(out, 10 - rank) < 0)
            return -1;
        rank++;
    }
    return buf_put(out, "]\n", 2);
}

/* exact output sizing shared by both selects: masked fragments +
 * score/separator slack */
static size_t ranked_estimate(NameTable *t, const uint8_t *mask) {
    size_t est = 8;
    for (Py_ssize_t row = 0; row < t->n_rows; row++)
        if (mask[row])
            est += (size_t)(t->frag_off[row + 1] - t->frag_off[row]) + 16;
    return est;
}

static PyObject *wirec_select_encode(PyObject *mod, PyObject *args) {
    (void)mod;
    PyObject *parsed_obj, *table_obj, *ranked_obj;
    Py_ssize_t planned_row = -1;
    int use_node_names = 0;
    if (!PyArg_ParseTuple(args, "OOO|np", &parsed_obj, &table_obj, &ranked_obj,
                          &planned_row, &use_node_names))
        return NULL;
    if (!PyObject_TypeCheck(parsed_obj, &ParsedArgs_Type)) {
        PyErr_SetString(PyExc_TypeError, "expected ParsedArgs");
        return NULL;
    }
    if (!PyObject_TypeCheck(table_obj, &NameTable_Type)) {
        PyErr_SetString(PyExc_TypeError, "expected NameTable");
        return NULL;
    }
    ParsedArgs *pa = (ParsedArgs *)parsed_obj;
    NameTable *t = (NameTable *)table_obj;

    Py_buffer ranked;
    if (PyObject_GetBuffer(ranked_obj, &ranked, PyBUF_SIMPLE) < 0)
        return NULL;
    if (ranked.len % sizeof(int64_t) != 0) {
        PyBuffer_Release(&ranked);
        PyErr_SetString(PyExc_ValueError, "ranked must be int64 buffer");
        return NULL;
    }
    const int64_t *order = (const int64_t *)ranked.buf;
    Py_ssize_t n_ranked = ranked.len / sizeof(int64_t);

    /* candidate source: Nodes.items names, or the NodeNames array in
     * nodeCacheCapable mode */
    const StrSlice *cand = use_node_names ? pa->nn_names : pa->names;
    Py_ssize_t num_cand = use_node_names ? pa->num_nn_names : pa->num_names;

    /* candidate mask over rows; escaped names (rare) resolve under the
     * GIL first, everything else runs GIL-free below.  The mask comes
     * from the process-wide buffer pool (stale bytes cleared here) — a
     * fresh calloc per request at 10k rows churns pages into p99 */
    Buf mask_buf = pool_get((size_t)t->n_rows + 1);
    if (!mask_buf.data) {
        PyBuffer_Release(&ranked);
        return PyErr_NoMemory();
    }
    uint8_t *mask = (uint8_t *)mask_buf.data;
    memset(mask, 0, (size_t)t->n_rows + 1);
    for (Py_ssize_t k = 0; k < num_cand; k++) {
        const StrSlice *sl = &cand[k];
        if (sl->present && sl->escaped) {
            PyObject *u = slice_to_unicode(pa->body, sl);
            if (!u) goto error;
            Py_ssize_t ulen;
            const char *us = PyUnicode_AsUTF8AndSize(u, &ulen);
            if (!us) { Py_DECREF(u); goto error; }
            Py_ssize_t row = table_lookup(t, us, ulen);
            Py_DECREF(u);
            if (row >= 0) mask[row] = 1;
        }
    }

    const char *body = PyBytes_AS_STRING(pa->body);
    Buf out_buf = {NULL, 0, 0};
    Buf *out = &out_buf;
    int oom = 0;
    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t k = 0; k < num_cand; k++) {
        const StrSlice *sl = &cand[k];
        if (!sl->present || sl->escaped) continue;
        Py_ssize_t row = table_lookup(t, body + sl->off, sl->len);
        if (row >= 0) mask[row] = 1;
    }

    out_buf = pool_get(ranked_estimate(t, mask));
    if (!out_buf.data) oom = 1;
    if (!oom && emit_ranked(out, t, mask, order, n_ranked, planned_row) < 0)
        oom = 1;
    Py_END_ALLOW_THREADS

    pool_put(&mask_buf);
    PyBuffer_Release(&ranked);
    if (oom) {
        pool_put(&out_buf);
        return PyErr_NoMemory();
    }
    PyObject *res = PyBytes_FromStringAndSize(out->data, (Py_ssize_t)out->len);
    pool_put(&out_buf);
    return res;

error:
    pool_put(&mask_buf);
    PyBuffer_Release(&ranked);
    return NULL;
}

/* ------------------------------------------------------------------ */
/* filter_encode                                                       */

/* THE Filter emit loop — the one copy both filter_encode and
 * filter_respond compile from, so warm-universe bytes can never drift
 * from the cold path's:
 *
 *   {"Nodes": null, "NodeNames": [...passing...],
 *    "FailedNodes": {"<name>": "<reason>", ...}, "Error": ""}\n
 *
 * Candidates are described uniformly: slice bytes at ``base``+slices,
 * per-candidate resolved ``rows`` (-1 = absent from the table),
 * ``raw_ok`` (bytes emit verbatim) with ``enc_ptr``/``enc_len`` holding
 * the pre-JSON-encoded form for non-raw names (may be NULL when every
 * candidate is raw).  ``seen`` is a caller-zeroed per-row dedup
 * scratch; 0 on success with *n_failed_out set, -1 on OOM. */
static int emit_filter(Buf *out, const char *base, const StrSlice *cand,
                       Py_ssize_t num, const Py_ssize_t *rows,
                       const uint8_t *raw_ok, const char **enc_ptr,
                       const Py_ssize_t *enc_len, const uint8_t *vmask,
                       const char **reason_ptr, const Py_ssize_t *reason_len,
                       uint8_t *seen, Py_ssize_t *n_failed_out) {
    Py_ssize_t n_failed = 0;
    if (buf_put(out, "{\"Nodes\": null, \"NodeNames\": [", 30) < 0) return -1;
    int first = 1;
    for (Py_ssize_t k = 0; k < num; k++) {
        Py_ssize_t row = rows[k];
        if (row >= 0 && vmask[row]) continue;  /* violating -> FailedNodes */
        if (!first && buf_put(out, ", ", 2) < 0) return -1;
        first = 0;
        if (raw_ok[k]) {
            const StrSlice *sl = &cand[k];
            if (buf_put(out, "\"", 1) < 0 ||
                buf_put(out, base + sl->off, (size_t)sl->len) < 0 ||
                buf_put(out, "\"", 1) < 0)
                return -1;
        } else if (buf_put(out, enc_ptr[k], (size_t)enc_len[k]) < 0) {
            return -1;
        }
    }
    if (buf_put(out, "], \"FailedNodes\": {", 19) < 0) return -1;
    first = 1;
    for (Py_ssize_t k = 0; k < num; k++) {
        Py_ssize_t row = rows[k];
        if (row < 0 || !vmask[row] || seen[row]) continue;
        seen[row] = 1;
        n_failed++;
        if (!first && buf_put(out, ", ", 2) < 0) return -1;
        first = 0;
        if (raw_ok[k]) {
            const StrSlice *sl = &cand[k];
            if (buf_put(out, "\"", 1) < 0 ||
                buf_put(out, base + sl->off, (size_t)sl->len) < 0 ||
                buf_put(out, "\"", 1) < 0)
                return -1;
        } else if (buf_put(out, enc_ptr[k], (size_t)enc_len[k]) < 0) {
            return -1;
        }
        if (reason_ptr && reason_ptr[row]) {
            if (buf_put(out, ": ", 2) < 0 ||
                buf_put(out, reason_ptr[row], (size_t)reason_len[row]) < 0)
                return -1;
        } else if (buf_put(out, ": \"Node violates\"", 17) < 0) {
            return -1;
        }
    }
    if (buf_put(out, "}, \"Error\": \"\"}\n", 16) < 0) return -1;
    *n_failed_out = n_failed;
    return 0;
}

/* Build the NodeNames-mode FilterResult response straight from the
 * parsed body + name table + a per-row violation bitmask, optionally a
 * per-row reason table:
 *
 *   {"Nodes": null, "NodeNames": [...passing...],
 *    "FailedNodes": {"<name>": "<reason>", ...}, "Error": ""}\n
 *
 * Returns (bytes, n_failed): the failed-entry count rides along so the
 * decision log's per-request counters stay exact without re-parsing.
 *
 * ``reasons`` (optional 4th arg) is a sequence indexed by table row
 * whose entries are pre-JSON-encoded reason strings as bytes (quotes
 * and escapes included — built host-side with json.dumps once per
 * state, utils/decisions.py) or None; a violating row without one gets
 * the reference literal "Node violates".  Splicing pre-encoded bytes
 * keeps byte parity with the exact Python path's json.dumps by
 * construction.
 *
 * Byte-identical to FilterResult.to_json() over the exact Python path's
 * result for the same request (json.dumps separators/ensure_ascii):
 * candidates keep request order; a name can be emitted raw iff its slice
 * has no escapes and every byte is in [0x20,0x7e] (exactly the set
 * json.dumps re-emits unchanged); duplicate violating names collapse to
 * one FailedNodes entry at first-occurrence position (dict semantics);
 * names absent from the table never violate (they pass through). */
static PyObject *wirec_filter_encode(PyObject *mod, PyObject *args) {
    (void)mod;
    PyObject *parsed_obj, *table_obj, *mask_obj, *reasons_obj = Py_None;
    if (!PyArg_ParseTuple(args, "OOO|O", &parsed_obj, &table_obj, &mask_obj,
                          &reasons_obj))
        return NULL;
    if (!PyObject_TypeCheck(parsed_obj, &ParsedArgs_Type)) {
        PyErr_SetString(PyExc_TypeError, "expected ParsedArgs");
        return NULL;
    }
    if (!PyObject_TypeCheck(table_obj, &NameTable_Type)) {
        PyErr_SetString(PyExc_TypeError, "expected NameTable");
        return NULL;
    }
    ParsedArgs *pa = (ParsedArgs *)parsed_obj;
    NameTable *t = (NameTable *)table_obj;
    Py_buffer viol;
    if (PyObject_GetBuffer(mask_obj, &viol, PyBUF_SIMPLE) < 0) return NULL;
    if (viol.len < t->n_rows) {
        PyBuffer_Release(&viol);
        PyErr_SetString(PyExc_ValueError, "violation mask shorter than table");
        return NULL;
    }
    const uint8_t *vmask = (const uint8_t *)viol.buf;
    const StrSlice *cand = pa->nn_names;  /* NodeNames mode only */
    Py_ssize_t num = pa->num_nn_names;
    const char *body = PyBytes_AS_STRING(pa->body);

    /* per-candidate resolution: row (or -1) and, for slices json.dumps
     * would re-escape, a pre-encoded buffer built under the GIL */
    Py_ssize_t *rows = NULL;
    uint8_t *raw_ok = NULL;
    uint8_t *seen = NULL;          /* FailedNodes dedup by row */
    const char **enc_ptr = NULL;   /* encoded bytes for non-raw slices */
    Py_ssize_t *enc_len = NULL;
    PyObject **enc_obj = NULL;     /* owned refs backing enc_ptr */
    Py_ssize_t n_enc = 0;
    PyObject *json_mod = NULL, *res = NULL;
    PyObject *reasons_fast = NULL; /* borrowed-item view of reasons_obj */
    const char **reason_ptr = NULL; /* per-row reason bytes (borrowed) */
    Py_ssize_t *reason_len = NULL;
    Py_ssize_t n_failed = 0;
    size_t reason_bytes = 0;
    Buf out_buf = {NULL, 0, 0};
    Buf *out = &out_buf;
    int oom = 0;

    rows = PyMem_Malloc((size_t)(num ? num : 1) * sizeof(Py_ssize_t));
    raw_ok = PyMem_Malloc((size_t)(num ? num : 1));
    seen = PyMem_Calloc((size_t)t->n_rows + 1, 1);
    if (!rows || !raw_ok || !seen) { PyErr_NoMemory(); goto done; }

    size_t span_bytes = 0;
    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t k = 0; k < num; k++) {
        const StrSlice *sl = &cand[k];
        int ok = !sl->escaped;
        if (ok) {
            const unsigned char *p = (const unsigned char *)body + sl->off;
            for (Py_ssize_t j = 0; j < sl->len; j++) {
                if (p[j] < 0x20 || p[j] >= 0x7f) { ok = 0; break; }
            }
        }
        raw_ok[k] = (uint8_t)ok;
        if (ok) {
            rows[k] = table_lookup(t, body + sl->off, sl->len);
            span_bytes += (size_t)sl->len;
        } else {
            rows[k] = -1;  /* resolved under the GIL below */
            n_enc++;
        }
    }
    Py_END_ALLOW_THREADS

    if (n_enc) {
        enc_ptr = PyMem_Calloc((size_t)num, sizeof(char *));
        enc_len = PyMem_Calloc((size_t)num, sizeof(Py_ssize_t));
        enc_obj = PyMem_Calloc((size_t)num, sizeof(PyObject *));
        if (!enc_ptr || !enc_len || !enc_obj) { PyErr_NoMemory(); goto done; }
        json_mod = PyImport_ImportModule("json");
        if (!json_mod) goto done;
        for (Py_ssize_t k = 0; k < num; k++) {
            if (raw_ok[k]) continue;
            PyObject *u = slice_to_unicode(pa->body, &cand[k]);
            if (!u) goto done;
            Py_ssize_t ulen;
            const char *us = PyUnicode_AsUTF8AndSize(u, &ulen);
            if (!us) { Py_DECREF(u); goto done; }
            rows[k] = table_lookup(t, us, ulen);
            PyObject *e = PyObject_CallMethod(json_mod, "dumps", "O", u);
            Py_DECREF(u);
            if (!e) goto done;
            /* keep the utf-8 of the encoded form alive via a bytes ref */
            PyObject *eb = PyUnicode_AsUTF8String(e);
            Py_DECREF(e);
            if (!eb) goto done;
            enc_obj[k] = eb;
            enc_ptr[k] = PyBytes_AS_STRING(eb);
            enc_len[k] = PyBytes_GET_SIZE(eb);
            span_bytes += (size_t)enc_len[k];
        }
    }

    if (reasons_obj != Py_None) {
        /* resolve per-row reason bytes under the GIL; the fast-sequence
         * ref keeps every bytes item alive through the GIL-free encode */
        reasons_fast = PySequence_Fast(
            reasons_obj, "reasons must be a sequence");
        if (!reasons_fast) goto done;
        Py_ssize_t rsize = PySequence_Fast_GET_SIZE(reasons_fast);
        reason_ptr = PyMem_Calloc((size_t)t->n_rows + 1, sizeof(char *));
        reason_len = PyMem_Calloc((size_t)t->n_rows + 1, sizeof(Py_ssize_t));
        if (!reason_ptr || !reason_len) { PyErr_NoMemory(); goto done; }
        for (Py_ssize_t k = 0; k < num; k++) {
            Py_ssize_t row = rows[k];
            if (row < 0 || row >= rsize || !vmask[row] || reason_ptr[row])
                continue;
            PyObject *item = PySequence_Fast_GET_ITEM(reasons_fast, row);
            if (item == Py_None || !PyBytes_Check(item)) continue;
            reason_ptr[row] = PyBytes_AS_STRING(item);
            reason_len[row] = PyBytes_GET_SIZE(item);
            reason_bytes += (size_t)reason_len[row];
        }
    }

    Py_BEGIN_ALLOW_THREADS
    /* "name", -> len+4 each; failed entry adds ': "Node violates"' (18)
     * or ': ' + its pre-encoded reason bytes (accounted in reason_bytes) */
    out_buf = pool_get(96 + span_bytes + (size_t)num * 24 + reason_bytes);
    if (!out_buf.data) oom = 1;
    if (!oom && emit_filter(out, body, cand, num, rows, raw_ok, enc_ptr,
                            enc_len, vmask, reason_ptr, reason_len, seen,
                            &n_failed) < 0)
        oom = 1;
    Py_END_ALLOW_THREADS

    if (oom) PyErr_NoMemory();
    else {
        PyObject *bytes =
            PyBytes_FromStringAndSize(out->data, (Py_ssize_t)out->len);
        if (bytes) res = Py_BuildValue("(Nn)", bytes, n_failed);
    }

done:
    pool_put(&out_buf);
    if (enc_obj) {
        for (Py_ssize_t k = 0; k < num; k++) Py_XDECREF(enc_obj[k]);
    }
    PyMem_Free(enc_ptr);
    PyMem_Free(enc_len);
    PyMem_Free(enc_obj);
    PyMem_Free(reason_ptr);
    PyMem_Free(reason_len);
    Py_XDECREF(reasons_fast);
    Py_XDECREF(json_mod);
    PyMem_Free(rows);
    PyMem_Free(raw_ok);
    PyMem_Free(seen);
    PyBuffer_Release(&viol);
    return res;
}

/* ------------------------------------------------------------------ */
/* interned node-name universes                                        */

/* The kube-scheduler re-sends the same ~N-node candidate list for every
 * pending pod; the per-request O(nodes) work left on the wire path —
 * name-slice bookkeeping, per-candidate hash lookups, response-body
 * assembly — is identical across those repeats.  A Universe interns one
 * candidate list ONCE: the raw span bytes (exact-match key), the
 * rebased name slices, per-candidate encode metadata (raw_ok flags +
 * pre-JSON-encoded bytes for names json.dumps would escape), a
 * lazily-materialized Python str tuple for the host paths, and a cached
 * per-NameTable row map so partitioning a verdict over the universe is
 * one pass over an int32 array with ZERO hashing.  UniverseCache is a
 * bounded MRU of universes keyed by a 64-bit content digest and
 * VERIFIED by memcmp — the digest is a prefilter, never a trust source,
 * so a hit is byte-proven and can never serve a stale candidate set.
 *
 * Universes are plain refcounted Python objects: the cache list holds
 * one ref, response-skeleton caches (tas/fastpath.py) hold more, and an
 * evicted universe stays valid for in-flight users until the last ref
 * drops.
 *
 * Concurrency: every Universe/UniverseCache mutation runs WITH the GIL
 * held and without releasing it (the row-map rebuild swaps the pointer
 * only after the new array is fully built and makes no further Python
 * calls before its user re-reads it) — renders over universe state
 * therefore never race a rebuild.  The render loops here are bounded
 * (~100 us at 10k rows) so holding the GIL through them is cheaper
 * than the synchronization a release would require. */

static uint64_t span_digest(const char *s, Py_ssize_t n) {
    /* FNV-1a over 8-byte words (collisions are harmless — memcmp
     * verifies — so word-width beats byte-at-a-time ~8x) */
    uint64_t h = 1469598103934665603ULL;
    const uint64_t prime = 1099511628211ULL;
    Py_ssize_t i = 0;
    for (; i + 8 <= n; i += 8) {
        uint64_t w;
        memcpy(&w, s + i, 8);
        h = (h ^ w) * prime;
    }
    if (i < n) {
        uint64_t tail = 0;
        memcpy(&tail, s + i, (size_t)(n - i));
        h = (h ^ tail) * prime;
    }
    h = (h ^ (uint64_t)n) * prime;
    return h;
}

typedef struct {
    PyObject_HEAD
    uint64_t digest;
    long uid;               /* monotonic id, for /debug/wire */
    int use_node_names;     /* which candidate span this interns */
    PyObject *span;         /* bytes: the exact raw span (slices point in) */
    Py_ssize_t num;         /* candidate count */
    StrSlice *slices;       /* rebased into span */
    uint8_t *raw_ok;        /* per-candidate: bytes emit verbatim in JSON */
    PyObject **enc_obj;     /* per-candidate pre-encoded bytes, or NULL */
    PyObject *names;        /* lazily-built tuple of str */
    PyObject *table;        /* the NameTable the row map was built for */
    int32_t *rows;          /* per-candidate row in ->table, or -1 */
} Universe;

static _Atomic long universe_uid = 0;

static void Universe_dealloc(Universe *self) {
    Py_XDECREF(self->span);
    free(self->slices);
    free(self->raw_ok);
    if (self->enc_obj) {
        for (Py_ssize_t k = 0; k < self->num; k++)
            Py_XDECREF(self->enc_obj[k]);
        free(self->enc_obj);
    }
    Py_XDECREF(self->names);
    Py_XDECREF(self->table);
    free(self->rows);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyObject *Universe_get(Universe *self, void *closure) {
    const char *which = (const char *)closure;
    if (strcmp(which, "uid") == 0) return PyLong_FromLong(self->uid);
    if (strcmp(which, "num") == 0) return PyLong_FromSsize_t(self->num);
    if (strcmp(which, "nbytes") == 0)
        return PyLong_FromSsize_t(PyBytes_GET_SIZE(self->span));
    if (strcmp(which, "use_node_names") == 0)
        return PyBool_FromLong(self->use_node_names);
    Py_RETURN_NONE;
}

/* the interned Python str tuple — built once, shared by every host-path
 * consumer of this universe (exact host fallbacks would otherwise
 * materialize N fresh unicode objects per request) */
static PyObject *Universe_names(Universe *self, PyObject *noargs) {
    (void)noargs;
    if (self->names == NULL) {
        PyObject *tup = PyTuple_New(self->num);
        if (!tup) return NULL;
        for (Py_ssize_t k = 0; k < self->num; k++) {
            PyObject *u = slice_to_unicode(self->span, &self->slices[k]);
            if (!u) { Py_DECREF(tup); return NULL; }
            PyTuple_SET_ITEM(tup, k, u);
        }
        if (self->names == NULL) self->names = tup;
        else Py_DECREF(tup);  /* a concurrent builder won */
    }
    Py_INCREF(self->names);
    return self->names;
}

/* ensure self->rows maps this universe onto ``table``; returns the live
 * row array (borrowed).  Called with the GIL held; the swap happens
 * only after the new array is complete, and callers re-read ->rows
 * after this returns and then make no GIL-yielding calls while using
 * it, so a concurrent rebuild can never free an array in use. */
static int32_t *universe_rows_for(Universe *self, NameTable *t) {
    if (self->table == (PyObject *)t && self->rows != NULL)
        return self->rows;
    int32_t *rows = malloc((size_t)(self->num ? self->num : 1) *
                           sizeof(int32_t));
    if (!rows) { PyErr_NoMemory(); return NULL; }
    const char *base = PyBytes_AS_STRING(self->span);
    for (Py_ssize_t k = 0; k < self->num; k++) {
        const StrSlice *sl = &self->slices[k];
        Py_ssize_t row;
        if (!sl->escaped) {
            row = table_lookup(t, base + sl->off, sl->len);
        } else {
            /* rare: decode exactly like the per-request encoders do */
            PyObject *u = slice_to_unicode(self->span, sl);
            if (!u) { free(rows); return NULL; }
            Py_ssize_t ulen;
            const char *us = PyUnicode_AsUTF8AndSize(u, &ulen);
            if (!us) { Py_DECREF(u); free(rows); return NULL; }
            row = table_lookup(t, us, ulen);
            Py_DECREF(u);
        }
        rows[k] = row >= 0 && row <= INT32_MAX ? (int32_t)row : -1;
    }
    int32_t *old = self->rows;
    PyObject *old_table = self->table;
    Py_INCREF((PyObject *)t);
    self->rows = rows;
    self->table = (PyObject *)t;
    free(old);
    Py_XDECREF(old_table);
    return self->rows;
}

static PyGetSetDef Universe_getset[] = {
    {"uid", (getter)Universe_get, NULL, NULL, "uid"},
    {"num", (getter)Universe_get, NULL, NULL, "num"},
    {"nbytes", (getter)Universe_get, NULL, NULL, "nbytes"},
    {"use_node_names", (getter)Universe_get, NULL, NULL, "use_node_names"},
    {NULL},
};

static PyMethodDef Universe_methods[] = {
    {"names", (PyCFunction)Universe_names, METH_NOARGS,
     "The interned candidate-name tuple (built once, then shared)."},
    {NULL},
};

static PyTypeObject Universe_Type = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_wirec.Universe",
    .tp_basicsize = sizeof(Universe),
    .tp_dealloc = (destructor)Universe_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_getset = Universe_getset,
    .tp_methods = Universe_methods,
};

/* span extent of the candidate list a universe would intern; -1 start
 * when the request has no such span */
static void parsed_span(ParsedArgs *pa, int use_nn, Py_ssize_t *start,
                        Py_ssize_t *end, const StrSlice **slices,
                        Py_ssize_t *num) {
    if (use_nn) {
        *start = pa->nn_span_start;
        *end = pa->nn_span_end;
        *slices = pa->nn_names;
        *num = pa->num_nn_names;
    } else {
        *start = pa->nodes_span_start;
        *end = pa->nodes_span_end;
        *slices = pa->names;
        *num = pa->num_names;
    }
}

#define SEEN_RING 64

typedef struct {
    PyObject_HEAD
    Py_ssize_t capacity;
    PyObject *entries;        /* list of Universe, MRU first */
    /* once-seen digest ring: a universe is interned only on its SECOND
     * sighting, so a stream of one-shot candidate lists (the bench's
     * rotated miss tier, a churning cluster) never pays intern+evict
     * churn for spans that will never repeat */
    uint64_t seen_dig[SEEN_RING];
    Py_ssize_t seen_len[SEEN_RING];
    int seen_next;
} UniverseCache;

static void UniverseCache_dealloc(UniverseCache *self) {
    Py_XDECREF(self->entries);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyObject *UniverseCache_new(PyTypeObject *type, PyObject *args,
                                   PyObject *kwds) {
    Py_ssize_t capacity = 8;
    static char *kwlist[] = {"capacity", NULL};
    if (!PyArg_ParseTupleAndKeywords(args, kwds, "|n", kwlist, &capacity))
        return NULL;
    if (capacity < 1) {
        PyErr_SetString(PyExc_ValueError, "capacity must be >= 1");
        return NULL;
    }
    UniverseCache *self = (UniverseCache *)type->tp_alloc(type, 0);
    if (!self) return NULL;
    self->capacity = capacity;
    self->entries = PyList_New(0);
    if (!self->entries) { Py_DECREF(self); return NULL; }
    memset(self->seen_dig, 0, sizeof(self->seen_dig));
    memset(self->seen_len, 0, sizeof(self->seen_len));
    self->seen_next = 0;
    return (PyObject *)self;
}

/* the shared digest-taking internals: every public entry point computes
 * the span digest EXACTLY ONCE and hands it down (the round-1 review
 * caught lookup+note_seen+intern re-sweeping the same ~150 KB span up
 * to three times per cold request) */

static int cache_args(PyObject *args, ParsedArgs **pa_out, int *use_nn_out) {
    PyObject *parsed_obj;
    if (!PyArg_ParseTuple(args, "Op", &parsed_obj, use_nn_out)) return -1;
    if (!PyObject_TypeCheck(parsed_obj, &ParsedArgs_Type)) {
        PyErr_SetString(PyExc_TypeError, "expected ParsedArgs");
        return -1;
    }
    *pa_out = (ParsedArgs *)parsed_obj;
    return 0;
}

/* BORROWED matching universe after MRU promotion, or NULL (not found,
 * or promotion OOM with the error set — check PyErr_Occurred) */
static Universe *cache_find(UniverseCache *self, uint64_t digest, int use_nn,
                            const char *span_ptr, Py_ssize_t span_len) {
    Py_ssize_t count = PyList_GET_SIZE(self->entries);
    for (Py_ssize_t idx = 0; idx < count; idx++) {
        Universe *u = (Universe *)PyList_GET_ITEM(self->entries, idx);
        if (u->digest != digest || u->use_node_names != use_nn ||
            PyBytes_GET_SIZE(u->span) != span_len)
            continue;
        if (memcmp(PyBytes_AS_STRING(u->span), span_ptr,
                   (size_t)span_len) != 0)
            continue;
        if (idx) {  /* MRU */
            PyObject *obj = (PyObject *)u;
            Py_INCREF(obj);
            if (PyList_SetSlice(self->entries, idx, idx + 1, NULL) < 0 ||
                PyList_Insert(self->entries, 0, obj) < 0) {
                Py_DECREF(obj);
                return NULL;
            }
            Py_DECREF(obj);
        }
        return u;
    }
    return NULL;
}

/* 1 when (digest, len) is already in the once-seen ring; else note it
 * and return 0 */
static int cache_seen(UniverseCache *self, uint64_t digest,
                      Py_ssize_t span_len) {
    for (int k = 0; k < SEEN_RING; k++) {
        if (self->seen_len[k] == span_len && self->seen_dig[k] == digest)
            return 1;
    }
    self->seen_dig[self->seen_next] = digest;
    self->seen_len[self->seen_next] = span_len;
    self->seen_next = (self->seen_next + 1) % SEEN_RING;
    return 0;
}

static Universe *cache_intern(UniverseCache *self, ParsedArgs *pa,
                              int use_nn, uint64_t digest, Py_ssize_t start,
                              Py_ssize_t end, const StrSlice *slices,
                              Py_ssize_t num, Py_ssize_t *evicted_out);

/* lookup(parsed, use_node_names) -> Universe | None.  Digest prefilter
 * + full-span memcmp verify (zero false positives), MRU reorder on
 * hit.  Runs entirely under the GIL: one call is atomic w.r.t. other
 * serving threads. */
static PyObject *UniverseCache_lookup(UniverseCache *self, PyObject *args) {
    ParsedArgs *pa;
    int use_nn;
    if (cache_args(args, &pa, &use_nn) < 0) return NULL;
    Py_ssize_t start, end, num;
    const StrSlice *slices;
    parsed_span(pa, use_nn, &start, &end, &slices, &num);
    if (start < 0) Py_RETURN_NONE;
    const char *ptr = PyBytes_AS_STRING(pa->body) + start;
    Py_ssize_t span_len = end - start;
    Universe *u = cache_find(self, span_digest(ptr, span_len), use_nn, ptr,
                             span_len);
    if (!u) {
        if (PyErr_Occurred()) return NULL;
        Py_RETURN_NONE;
    }
    Py_INCREF((PyObject *)u);
    return (PyObject *)u;
}

/* note_seen(parsed, use_node_names) -> bool: record the span digest in
 * the once-seen ring; True when it was already there (the caller should
 * intern now — this is the span's second sighting). */
static PyObject *UniverseCache_note_seen(UniverseCache *self, PyObject *args) {
    ParsedArgs *pa;
    int use_nn;
    if (cache_args(args, &pa, &use_nn) < 0) return NULL;
    Py_ssize_t start, end, num;
    const StrSlice *slices;
    parsed_span(pa, use_nn, &start, &end, &slices, &num);
    if (start < 0) Py_RETURN_FALSE;
    const char *ptr = PyBytes_AS_STRING(pa->body) + start;
    Py_ssize_t span_len = end - start;
    return PyBool_FromLong(
        cache_seen(self, span_digest(ptr, span_len), span_len));
}

/* probe(parsed, use_node_names) -> (Universe | None, interned, evicted):
 * the serving entry point — ONE digest pass covers hit lookup, the
 * once-seen check, and (on a second sighting) the intern.  A hit is
 * (u, False, 0); a first sighting notes the digest and returns
 * (None, False, 0); a second sighting interns and returns
 * (u, True, evicted). */
static PyObject *UniverseCache_probe(UniverseCache *self, PyObject *args) {
    ParsedArgs *pa;
    int use_nn;
    if (cache_args(args, &pa, &use_nn) < 0) return NULL;
    Py_ssize_t start, end, num;
    const StrSlice *slices;
    parsed_span(pa, use_nn, &start, &end, &slices, &num);
    if (start < 0) return Py_BuildValue("(OOn)", Py_None, Py_False, 0);
    const char *ptr = PyBytes_AS_STRING(pa->body) + start;
    Py_ssize_t span_len = end - start;
    uint64_t digest = span_digest(ptr, span_len);
    Universe *found = cache_find(self, digest, use_nn, ptr, span_len);
    if (found) return Py_BuildValue("(OOn)", (PyObject *)found, Py_False, 0);
    if (PyErr_Occurred()) return NULL;
    if (!cache_seen(self, digest, span_len))
        return Py_BuildValue("(OOn)", Py_None, Py_False, 0);
    Py_ssize_t evicted = 0;
    Universe *u = cache_intern(self, pa, use_nn, digest, start, end, slices,
                               num, &evicted);
    if (!u) return NULL;
    return Py_BuildValue("(NOn)", (PyObject *)u, Py_True, evicted);
}

/* intern(parsed, use_node_names) -> (Universe, evicted_count) */
static PyObject *UniverseCache_intern(UniverseCache *self, PyObject *args) {
    ParsedArgs *pa;
    int use_nn;
    if (cache_args(args, &pa, &use_nn) < 0) return NULL;
    Py_ssize_t start, end, num;
    const StrSlice *slices;
    parsed_span(pa, use_nn, &start, &end, &slices, &num);
    if (start < 0) {
        PyErr_SetString(PyExc_ValueError, "request has no candidate span");
        return NULL;
    }
    const char *ptr = PyBytes_AS_STRING(pa->body) + start;
    Py_ssize_t evicted = 0;
    Universe *u = cache_intern(self, pa, use_nn,
                               span_digest(ptr, end - start), start, end,
                               slices, num, &evicted);
    if (!u) return NULL;
    return Py_BuildValue("(Nn)", (PyObject *)u, evicted);
}

/* NEW reference to the interned universe, inserted MRU-first with the
 * cache trimmed to capacity (*evicted_out = how many dropped) */
static Universe *cache_intern(UniverseCache *self, ParsedArgs *pa,
                              int use_nn, uint64_t digest, Py_ssize_t start,
                              Py_ssize_t end, const StrSlice *slices,
                              Py_ssize_t num, Py_ssize_t *evicted_out) {
    const char *body = PyBytes_AS_STRING(pa->body);
    Py_ssize_t span_len = end - start;

    Universe *u = PyObject_New(Universe, &Universe_Type);
    if (!u) return NULL;
    u->digest = digest;
    u->uid = atomic_fetch_add_explicit(&universe_uid, 1,
                                       memory_order_relaxed) + 1;
    u->use_node_names = use_nn;
    u->span = NULL;
    u->num = num;
    u->slices = NULL;
    u->raw_ok = NULL;
    u->enc_obj = NULL;
    u->names = NULL;
    u->table = NULL;
    u->rows = NULL;
    u->span = PyBytes_FromStringAndSize(body + start, span_len);
    u->slices = malloc((size_t)(num ? num : 1) * sizeof(StrSlice));
    u->raw_ok = malloc((size_t)(num ? num : 1));
    u->enc_obj = calloc((size_t)(num ? num : 1), sizeof(PyObject *));
    if (!u->span || !u->slices || !u->raw_ok || !u->enc_obj) {
        /* span failure set its own error; raw-malloc failures need ours */
        if (u->span) PyErr_NoMemory();
        Py_DECREF(u);
        return NULL;
    }
    const char *span_base = PyBytes_AS_STRING(u->span);
    PyObject *json_mod = NULL;
    for (Py_ssize_t k = 0; k < num; k++) {
        StrSlice sl = slices[k];
        sl.off -= start;  /* rebase into the span copy */
        u->slices[k] = sl;
        int ok = !sl.escaped;
        if (ok) {
            const unsigned char *p =
                (const unsigned char *)span_base + sl.off;
            for (Py_ssize_t j = 0; j < sl.len; j++) {
                if (p[j] < 0x20 || p[j] >= 0x7f) { ok = 0; break; }
            }
        }
        u->raw_ok[k] = (uint8_t)ok;
        if (!ok) {
            /* pre-encode ONCE what the per-request encoders would
             * json.dumps per request (exact parity by construction) */
            PyObject *uni = slice_to_unicode(u->span, &u->slices[k]);
            if (!uni) goto error;
            if (!json_mod) {
                json_mod = PyImport_ImportModule("json");
                if (!json_mod) { Py_DECREF(uni); goto error; }
            }
            PyObject *e =
                PyObject_CallMethod(json_mod, "dumps", "O", uni);
            Py_DECREF(uni);
            if (!e) goto error;
            PyObject *eb = PyUnicode_AsUTF8String(e);
            Py_DECREF(e);
            if (!eb) goto error;
            u->enc_obj[k] = eb;
        }
    }
    Py_XDECREF(json_mod);
    json_mod = NULL;

    if (PyList_Insert(self->entries, 0, (PyObject *)u) < 0) goto error;
    Py_ssize_t evicted = PyList_GET_SIZE(self->entries) - self->capacity;
    if (evicted > 0) {
        if (PyList_SetSlice(self->entries, self->capacity,
                            PyList_GET_SIZE(self->entries), NULL) < 0)
            goto error;
    } else {
        evicted = 0;
    }
    *evicted_out = evicted;
    return u;

error:
    Py_XDECREF(json_mod);
    Py_DECREF(u);
    return NULL;
}

/* snapshot() -> [Universe, ...] in MRU order — the state-change warmer
 * iterates these to pre-render response skeletons off the request path */
static PyObject *UniverseCache_snapshot(UniverseCache *self,
                                        PyObject *noargs) {
    (void)noargs;
    return PyList_GetSlice(self->entries, 0,
                           PyList_GET_SIZE(self->entries));
}

static PyObject *UniverseCache_universes(UniverseCache *self,
                                         PyObject *noargs) {
    (void)noargs;
    Py_ssize_t count = PyList_GET_SIZE(self->entries);
    PyObject *out = PyList_New(count);
    if (!out) return NULL;
    for (Py_ssize_t idx = 0; idx < count; idx++) {
        Universe *u = (Universe *)PyList_GET_ITEM(self->entries, idx);
        PyObject *d = Py_BuildValue(
            "{s:l, s:s, s:n, s:n}",
            "uid", u->uid,
            "kind", u->use_node_names ? "nodenames" : "nodes",
            "names", u->num,
            "bytes", PyBytes_GET_SIZE(u->span));
        if (!d) { Py_DECREF(out); return NULL; }
        PyList_SET_ITEM(out, idx, d);
    }
    return out;
}

static PyObject *UniverseCache_get(UniverseCache *self, void *closure) {
    const char *which = (const char *)closure;
    if (strcmp(which, "capacity") == 0)
        return PyLong_FromSsize_t(self->capacity);
    if (strcmp(which, "occupancy") == 0)
        return PyLong_FromSsize_t(PyList_GET_SIZE(self->entries));
    Py_RETURN_NONE;
}

static PyGetSetDef UniverseCache_getset[] = {
    {"capacity", (getter)UniverseCache_get, NULL, NULL, "capacity"},
    {"occupancy", (getter)UniverseCache_get, NULL, NULL, "occupancy"},
    {NULL},
};

static PyMethodDef UniverseCache_methods[] = {
    {"lookup", (PyCFunction)UniverseCache_lookup, METH_VARARGS,
     "Digest + memcmp-verified universe for this request's candidate "
     "span, MRU-promoted; None on miss."},
    {"probe", (PyCFunction)UniverseCache_probe, METH_VARARGS,
     "One-digest serving probe: (universe|None, interned, evicted)."},
    {"note_seen", (PyCFunction)UniverseCache_note_seen, METH_VARARGS,
     "Record the span digest; True when already seen (intern now)."},
    {"intern", (PyCFunction)UniverseCache_intern, METH_VARARGS,
     "Intern the request's candidate span; returns (Universe, evicted)."},
    {"universes", (PyCFunction)UniverseCache_universes, METH_NOARGS,
     "Debug snapshot: [{uid, kind, names, bytes}] in MRU order."},
    {"snapshot", (PyCFunction)UniverseCache_snapshot, METH_NOARGS,
     "The live Universe objects in MRU order (skeleton pre-warming)."},
    {NULL},
};

static PyTypeObject UniverseCache_Type = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_wirec.UniverseCache",
    .tp_basicsize = sizeof(UniverseCache),
    .tp_new = UniverseCache_new,
    .tp_dealloc = (destructor)UniverseCache_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_getset = UniverseCache_getset,
    .tp_methods = UniverseCache_methods,
};

/* ------------------------------------------------------------------ */
/* universe-backed encoders                                            */

/* filter_respond(universe, table, mask, reasons) -> (bytes, n_failed)
 *
 * The universe twin of filter_encode: candidates come from the interned
 * span, rows from the cached per-table map (ONE array read per
 * candidate, zero hashing), raw_ok/escape encodings pre-resolved at
 * intern time.  Output bytes are identical to filter_encode over the
 * same request by construction — both emit the same candidate order,
 * dedup, reasons, and framing from the same per-row data.  Runs under
 * the GIL throughout (see the universe concurrency note). */
static PyObject *wirec_filter_respond(PyObject *mod, PyObject *args) {
    (void)mod;
    PyObject *universe_obj, *table_obj, *mask_obj, *reasons_obj = Py_None;
    if (!PyArg_ParseTuple(args, "OOO|O", &universe_obj, &table_obj,
                          &mask_obj, &reasons_obj))
        return NULL;
    if (!PyObject_TypeCheck(universe_obj, &Universe_Type)) {
        PyErr_SetString(PyExc_TypeError, "expected Universe");
        return NULL;
    }
    if (!PyObject_TypeCheck(table_obj, &NameTable_Type)) {
        PyErr_SetString(PyExc_TypeError, "expected NameTable");
        return NULL;
    }
    Universe *u = (Universe *)universe_obj;
    NameTable *t = (NameTable *)table_obj;
    if (!u->use_node_names) {
        PyErr_SetString(PyExc_ValueError,
                        "filter_respond serves NodeNames universes only");
        return NULL;
    }
    Py_buffer viol;
    if (PyObject_GetBuffer(mask_obj, &viol, PyBUF_SIMPLE) < 0) return NULL;
    if (viol.len < t->n_rows) {
        PyBuffer_Release(&viol);
        PyErr_SetString(PyExc_ValueError, "violation mask shorter than table");
        return NULL;
    }
    const uint8_t *vmask = (const uint8_t *)viol.buf;

    /* resolve the row map first (may make Python calls), THEN take the
     * live pointer and stay GIL-atomic for the rest of the call */
    if (universe_rows_for(u, t) == NULL) {
        PyBuffer_Release(&viol);
        return NULL;
    }
    PyObject *reasons_fast = NULL;
    const char **reason_ptr = NULL;
    Py_ssize_t *reason_len = NULL;
    uint8_t *seen = NULL;
    Py_ssize_t *rows = NULL;
    const char **enc_ptr = NULL;
    Py_ssize_t *enc_len = NULL;
    PyObject *res = NULL;
    size_t reason_bytes = 0;
    Buf out_buf = {NULL, 0, 0};
    Buf *out = &out_buf;
    int oom = 0;
    const int32_t *rows32 = u->rows;
    Py_ssize_t num = u->num;
    const char *span = PyBytes_AS_STRING(u->span);

    /* adapt the universe's cached per-candidate state into the shared
     * emit shape: widened rows, plus enc pointer/length views over the
     * pre-encoded bytes objects (refs held by the universe) */
    seen = PyMem_Calloc((size_t)t->n_rows + 1, 1);
    rows = PyMem_Malloc((size_t)(num ? num : 1) * sizeof(Py_ssize_t));
    enc_ptr = PyMem_Calloc((size_t)(num ? num : 1), sizeof(char *));
    enc_len = PyMem_Calloc((size_t)(num ? num : 1), sizeof(Py_ssize_t));
    if (!seen || !rows || !enc_ptr || !enc_len) {
        PyErr_NoMemory();
        goto done;
    }
    for (Py_ssize_t k = 0; k < num; k++) {
        rows[k] = rows32[k];
        if (!u->raw_ok[k]) {
            enc_ptr[k] = PyBytes_AS_STRING(u->enc_obj[k]);
            enc_len[k] = PyBytes_GET_SIZE(u->enc_obj[k]);
        }
    }
    if (reasons_obj != Py_None) {
        reasons_fast =
            PySequence_Fast(reasons_obj, "reasons must be a sequence");
        if (!reasons_fast) goto done;
        Py_ssize_t rsize = PySequence_Fast_GET_SIZE(reasons_fast);
        reason_ptr = PyMem_Calloc((size_t)t->n_rows + 1, sizeof(char *));
        reason_len = PyMem_Calloc((size_t)t->n_rows + 1, sizeof(Py_ssize_t));
        if (!reason_ptr || !reason_len) { PyErr_NoMemory(); goto done; }
        for (Py_ssize_t k = 0; k < num; k++) {
            Py_ssize_t row = rows[k];
            if (row < 0 || row >= rsize || !vmask[row] || reason_ptr[row])
                continue;
            PyObject *item = PySequence_Fast_GET_ITEM(reasons_fast, row);
            if (item == Py_None || !PyBytes_Check(item)) continue;
            reason_ptr[row] = PyBytes_AS_STRING(item);
            reason_len[row] = PyBytes_GET_SIZE(item);
            reason_bytes += (size_t)reason_len[row];
        }
    }

    {
        size_t span_bytes = (size_t)PyBytes_GET_SIZE(u->span);
        Py_ssize_t n_failed = 0;
        out_buf = pool_get(96 + span_bytes + (size_t)num * 24 + reason_bytes);
        if (!out_buf.data) oom = 1;
        if (!oom && emit_filter(out, span, u->slices, num, rows, u->raw_ok,
                                enc_ptr, enc_len, vmask, reason_ptr,
                                reason_len, seen, &n_failed) < 0)
            oom = 1;
        if (oom) PyErr_NoMemory();
        else {
            PyObject *bytes =
                PyBytes_FromStringAndSize(out->data, (Py_ssize_t)out->len);
            if (bytes) res = Py_BuildValue("(Nn)", bytes, n_failed);
        }
    }

done:
    pool_put(&out_buf);
    PyMem_Free(reason_ptr);
    PyMem_Free(reason_len);
    Py_XDECREF(reasons_fast);
    PyMem_Free(seen);
    PyMem_Free(rows);
    PyMem_Free(enc_ptr);
    PyMem_Free(enc_len);
    PyBuffer_Release(&viol);
    return res;
}

/* select_encode_universe(universe, table, ranked, planned_row) -> bytes
 *
 * The universe twin of select_encode: the candidate mask fills from the
 * cached row map instead of per-name hash lookups; the emit loop is
 * identical, so bytes match select_encode over the same request by
 * construction. */
static PyObject *wirec_select_encode_universe(PyObject *mod, PyObject *args) {
    (void)mod;
    PyObject *universe_obj, *table_obj, *ranked_obj;
    Py_ssize_t planned_row = -1;
    if (!PyArg_ParseTuple(args, "OOO|n", &universe_obj, &table_obj,
                          &ranked_obj, &planned_row))
        return NULL;
    if (!PyObject_TypeCheck(universe_obj, &Universe_Type)) {
        PyErr_SetString(PyExc_TypeError, "expected Universe");
        return NULL;
    }
    if (!PyObject_TypeCheck(table_obj, &NameTable_Type)) {
        PyErr_SetString(PyExc_TypeError, "expected NameTable");
        return NULL;
    }
    Universe *u = (Universe *)universe_obj;
    NameTable *t = (NameTable *)table_obj;
    Py_buffer ranked;
    if (PyObject_GetBuffer(ranked_obj, &ranked, PyBUF_SIMPLE) < 0)
        return NULL;
    if (ranked.len % sizeof(int64_t) != 0) {
        PyBuffer_Release(&ranked);
        PyErr_SetString(PyExc_ValueError, "ranked must be int64 buffer");
        return NULL;
    }
    const int64_t *order = (const int64_t *)ranked.buf;
    Py_ssize_t n_ranked = ranked.len / sizeof(int64_t);

    if (universe_rows_for(u, t) == NULL) {
        PyBuffer_Release(&ranked);
        return NULL;
    }
    const int32_t *rows = u->rows;

    Buf mask_buf = pool_get((size_t)t->n_rows + 1);
    if (!mask_buf.data) {
        PyBuffer_Release(&ranked);
        return PyErr_NoMemory();
    }
    uint8_t *mask = (uint8_t *)mask_buf.data;
    memset(mask, 0, (size_t)t->n_rows + 1);
    for (Py_ssize_t k = 0; k < u->num; k++) {
        if (rows[k] >= 0) mask[rows[k]] = 1;
    }

    Buf out_buf = {NULL, 0, 0};
    Buf *out = &out_buf;
    int oom = 0;
    out_buf = pool_get(ranked_estimate(t, mask));
    if (!out_buf.data) oom = 1;
    if (!oom && emit_ranked(out, t, mask, order, n_ranked, planned_row) < 0)
        oom = 1;
    pool_put(&mask_buf);
    PyBuffer_Release(&ranked);
    if (oom) {
        pool_put(&out_buf);
        return PyErr_NoMemory();
    }
    PyObject *res = PyBytes_FromStringAndSize(out->data, (Py_ssize_t)out->len);
    pool_put(&out_buf);
    return res;
}

/* ------------------------------------------------------------------ */

static PyMethodDef wirec_methods[] = {
    {"parse_prioritize", wirec_parse_prioritize, METH_O,
     "Strict zero-copy scan of a scheduler-extender Args body."},
    {"build_table", wirec_build_table, METH_O,
     "Build a name->row table + response fragments for one state version."},
    {"select_encode", wirec_select_encode, METH_VARARGS,
     "Assemble the Prioritize response bytes from a parsed body, a name "
     "table, and the global rank order (optional planned row promotion)."},
    {"filter_encode", wirec_filter_encode, METH_VARARGS,
     "Assemble the NodeNames-mode FilterResult response from a parsed "
     "body, a name table, a per-row violation bitmask, and optional "
     "per-row pre-encoded reason bytes; returns (bytes, n_failed)."},
    {"filter_respond", wirec_filter_respond, METH_VARARGS,
     "filter_encode over an interned Universe: cached row map, zero "
     "hashing; returns (bytes, n_failed)."},
    {"select_encode_universe", wirec_select_encode_universe, METH_VARARGS,
     "select_encode over an interned Universe: candidate mask from the "
     "cached row map instead of per-name hash lookups."},
    {NULL},
};

static struct PyModuleDef wirec_module = {
    PyModuleDef_HEAD_INIT, "_wirec",
    "Native wire-protocol fast path for the TPU scheduler extender.",
    -1, wirec_methods,
    NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC PyInit__wirec(void) {
    if (PyType_Ready(&ParsedArgs_Type) < 0) return NULL;
    if (PyType_Ready(&NameTable_Type) < 0) return NULL;
    if (PyType_Ready(&Universe_Type) < 0) return NULL;
    if (PyType_Ready(&UniverseCache_Type) < 0) return NULL;
    PyObject *mod = PyModule_Create(&wirec_module);
    if (!mod) return NULL;
    Py_INCREF(&UniverseCache_Type);
    if (PyModule_AddObject(mod, "UniverseCache",
                           (PyObject *)&UniverseCache_Type) < 0) {
        Py_DECREF(&UniverseCache_Type);
        Py_DECREF(mod);
        return NULL;
    }
    return mod;
}

"""Native (C) components: build-on-demand loader.

The reference has no native code (SURVEY: 100% Go, zero C++/CUDA), but this
framework's runtime keeps its wire tails native: ``_wirec`` removes the
per-request JSON-object churn at 10k-node scale (see wirec.c).  The module
is compiled on first use with the toolchain baked into the image (g++/cc);
everything degrades gracefully to the pure-Python paths when no compiler
is available (``get_wirec() -> None``).
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sysconfig
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "wirec.c")
_SO = os.path.join(_DIR, "_wirec.so")

_lock = threading.Lock()
_loaded = False
_module = None


def _build() -> bool:
    cc = os.environ.get("CC", "cc")
    include = sysconfig.get_paths()["include"]
    cmd = [
        cc,
        "-O2",
        "-fPIC",
        "-shared",
        f"-I{include}",
        _SRC,
        "-o",
        _SO + ".tmp",
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired):
        return False
    if proc.returncode != 0:
        import sys

        print(f"_wirec build failed:\n{proc.stderr}", file=sys.stderr)
        return False
    os.replace(_SO + ".tmp", _SO)
    return True


def _stale() -> bool:
    try:
        return os.path.getmtime(_SO) < os.path.getmtime(_SRC)
    except OSError:
        return True


def get_wirec(allow_build: bool = True):
    """The ``_wirec`` extension module, or None when unavailable.

    Set ``PAS_TPU_NO_NATIVE=1`` to force the pure-Python paths (used by the
    test matrix to keep both variants covered)."""
    global _loaded, _module
    if os.environ.get("PAS_TPU_NO_NATIVE") == "1":
        return None
    if _loaded:
        return _module
    with _lock:
        if _loaded:
            return _module
        if _stale() and (not allow_build or not _build()):
            _loaded = True
            _module = None
            return None
        try:
            spec = importlib.util.spec_from_file_location("_wirec", _SO)
            module = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(module)
        except Exception:
            module = None
        _loaded = True
        _module = module
        return _module

"""Native (C) components: build-on-demand loader.

The reference has no native code (SURVEY: 100% Go, zero C++/CUDA), but this
framework's runtime keeps its wire tails native: ``_wirec`` removes the
per-request JSON-object churn at 10k-node scale (see wirec.c).  The module
is compiled on first use wherever a toolchain exists (dev machines, the
image BUILD stage); the shipped TAS image carries no compiler and a
read-only rootfs, so deploy/images/Dockerfile.tas precompiles the
artifact at build time and this loader just loads it
(``get_wirec(allow_build=False)`` is its gate).  Everything degrades
gracefully to the pure-Python paths when neither a prebuilt artifact nor
a compiler is available (``get_wirec() -> None``).

No binary is ever shipped or loaded blind: the build artifact is named by
the SHA-256 of the source, so the loader only loads a ``.so`` that was
compiled from the exact reviewed ``wirec.c`` on this machine (the round-2
advisor flagged the prior mtime check, which could load a foreign-ABI
binary after a fresh clone).
"""

from __future__ import annotations

import hashlib
import importlib.util
import os
import subprocess
import sysconfig
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "wirec.c")

_lock = threading.Lock()
_loaded = False
_module = None


def _so_path() -> str:
    """Build artifact path keyed by source content hash AND the
    interpreter ABI — a checkout shared between Python versions must not
    load an extension compiled against another interpreter's headers."""
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    soabi = sysconfig.get_config_var("SOABI") or "unknown-abi"
    return os.path.join(_DIR, f"_wirec-{digest}-{soabi}.so")


def _build(so_path: str) -> bool:
    cc = os.environ.get("CC", "cc")
    include = sysconfig.get_paths()["include"]
    # per-process tmp name: concurrent cold-starting processes must not
    # interleave compiler output into the same file (the winner's
    # os.replace is atomic; losers just replace it with identical bytes)
    tmp = f"{so_path}.{os.getpid()}.tmp"
    cmd = [
        cc,
        "-O2",
        "-fPIC",
        "-shared",
        f"-I{include}",
        _SRC,
        "-o",
        tmp,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired):
        return False
    if proc.returncode != 0:
        import sys

        print(f"_wirec build failed:\n{proc.stderr}", file=sys.stderr)
        return False
    try:
        os.replace(tmp, so_path)
    except OSError:
        # a concurrent builder's cleanup may have removed our tmp; we
        # only lost the race — the winner's artifact serves everyone
        return os.path.exists(so_path)
    # best-effort cleanup: artifacts from older source revisions, and tmp
    # files orphaned by crashed builds (older than the 120 s build
    # timeout — never a concurrent builder's in-progress tmp)
    import time

    now = time.time()  # pascheck: allow[clock] -- compared against os.path.getmtime, which is wall time by definition
    try:
        for entry in os.listdir(_DIR):
            path = os.path.join(_DIR, entry)
            if not entry.startswith("_wirec"):
                continue
            stale_so = entry.endswith(".so") and path != so_path
            orphan_tmp = False
            if entry.endswith(".tmp"):
                try:
                    orphan_tmp = now - os.path.getmtime(path) > 120
                except OSError:
                    continue
            if stale_so or orphan_tmp:
                try:
                    os.unlink(path)
                except OSError:
                    pass
    except OSError:
        pass
    return True


def get_wirec(allow_build: bool = True):
    """The ``_wirec`` extension module, or None when unavailable.

    Set ``PAS_TPU_NO_NATIVE=1`` to force the pure-Python paths (used by the
    test matrix to keep both variants covered)."""
    global _loaded, _module
    if os.environ.get("PAS_TPU_NO_NATIVE") == "1":
        return None
    if _loaded:
        return _module
    with _lock:
        if _loaded:
            return _module
        override = os.environ.get("PAS_TPU_WIREC_SO")
        if override:
            # dev/CI hook (make test-wirec): load EXACTLY this artifact,
            # bypassing the content-hash gate — how the sanitizer build
            # (-fsanitize=address,undefined) runs the wire-path tests
            # against instrumented code.  Never set in production.  An
            # EXPLICIT override that fails to import must raise, not
            # degrade: swallowing it would turn the whole sanitizer CI
            # gate green while the tests skip on get_wirec() is None,
            # having exercised zero native code.
            spec = importlib.util.spec_from_file_location("_wirec", override)
            module = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(module)
            _loaded = True
            _module = module
            return _module
        try:
            so = _so_path()
        except OSError:
            _loaded = True
            _module = None
            return None
        if not os.path.exists(so) and (not allow_build or not _build(so)):
            _loaded = True
            _module = None
            return None
        try:
            spec = importlib.util.spec_from_file_location("_wirec", so)
            module = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(module)
        except Exception:
            module = None
        _loaded = True
        _module = module
        return _module

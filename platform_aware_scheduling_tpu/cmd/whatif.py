"""What-if CLI: replay a flight-recorder capture offline.

``python -m platform_aware_scheduling_tpu.cmd.whatif --capture
capture.jsonl --loadMultiplier 2.0`` is the air-gapped sibling of
``POST /debug/whatif``: fetch a capture once (``curl
.../debug/record > capture.jsonl``), then ask what-if questions against
it from anywhere — no scheduler process needed
(docs/observability.md "Flight recorder & what-if").
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from platform_aware_scheduling_tpu.testing import replay
from platform_aware_scheduling_tpu.utils import klog


def build_arg_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="pas-whatif",
        description=(
            "replay a flight-recorder capture through the digital twin "
            "under transform knobs; prints projected per-SLO verdicts, "
            "burn rates and budget ledgers as JSON"
        ),
    )
    parser.add_argument(
        "--capture",
        required=True,
        help="path to a /debug/record JSONL capture, or - for stdin",
    )
    parser.add_argument(
        "--loadMultiplier",
        type=float,
        default=1.0,
        help="scale the recorded load surface and verb arrivals",
    )
    parser.add_argument(
        "--removeNodes",
        type=int,
        default=0,
        help="replay with this many fewer nodes than recorded",
    )
    parser.add_argument(
        "--numNodes",
        type=int,
        default=None,
        help="override the recorded node scale entirely",
    )
    parser.add_argument(
        "--maxTicks",
        type=int,
        default=None,
        help="cap the replayed tick count",
    )
    parser.add_argument(
        "--servingCapacity",
        type=int,
        default=None,
        help="per-tick verb admission budget (default: the recorded "
        "per-tick peak, so 1x sheds nothing)",
    )
    parser.add_argument(
        "--latencyThresholdMs",
        type=float,
        default=25.0,
        help="Prioritize/Filter p99 SLO threshold for the projection",
    )
    parser.add_argument(
        "--wireSloUs",
        type=float,
        default=0.0,
        help="wire-floor SLO threshold in us (0 = off; a replay "
        "cannot reproduce wall-clock jitter)",
    )
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--v", type=int, default=1, help="klog verbosity")
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    args = build_arg_parser().parse_args(argv)
    klog.set_verbosity(args.v)
    if args.capture == "-":
        text = sys.stdin.read()
    else:
        try:
            with open(args.capture, "r", encoding="utf-8") as handle:
                text = handle.read()
        except OSError as exc:
            print(f"error: cannot read capture: {exc}", file=sys.stderr)
            return 2
    spec = {
        "capture": text,
        "load_multiplier": args.loadMultiplier,
        "remove_nodes": args.removeNodes,
        "num_nodes": args.numNodes,
        "max_ticks": args.maxTicks,
        "serving_capacity": args.servingCapacity,
        "latency_threshold_ms": args.latencyThresholdMs,
        "wire_slo_us": args.wireSloUs,
        "seed": args.seed,
    }
    try:
        result = replay.whatif_from_spec(spec)
    except replay.CaptureError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    print(json.dumps(result, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""TAS service main: flags, assembly, signal handling.

Reference: telemetry-aware-scheduling/cmd/main.go:31-117.  Identical flag
surface (``--kubeConfig --port --cert --key --cacert --unsafe --syncPeriod``
plus klog ``--v``); assembly adds the TPU twist: a TensorStateMirror is
attached to the cache so the extender's hot path runs the jitted scoring
kernels, with the exact host path as automatic fallback.
"""

from __future__ import annotations

import argparse
import os
import signal
import threading
from typing import List, Optional

from platform_aware_scheduling_tpu.cmd import common
from platform_aware_scheduling_tpu.extender.server import Server
from platform_aware_scheduling_tpu.kube.client import KubeClient, get_kube_client
from platform_aware_scheduling_tpu.ops.state import TensorStateMirror
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas.controller import TelemetryPolicyController
from platform_aware_scheduling_tpu.tas.metrics import CustomMetricsClient
from platform_aware_scheduling_tpu.tas.strategies import (
    core,
    deschedule,
    dontschedule,
    scheduleonmetric,
)
from platform_aware_scheduling_tpu.tas.telemetryscheduler import MetricsExtender
from platform_aware_scheduling_tpu.utils import klog
from platform_aware_scheduling_tpu.utils.duration import parse_duration


def build_arg_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tas-extender",
        description="Telemetry-aware scheduling extender (TPU-native)",
    )
    default_kubeconfig = os.path.join(
        os.environ.get("HOME", "/root"), ".kube", "config"
    )
    parser.add_argument("--kubeConfig", default=default_kubeconfig,
                        help="location of kubernetes config file")
    parser.add_argument("--port", default="9001",
                        help="port on which the scheduler extender will listen")
    parser.add_argument("--cert", default="/etc/kubernetes/pki/ca.crt",
                        help="cert file extender will use")
    parser.add_argument("--key", default="/etc/kubernetes/pki/ca.key",
                        help="key file extender will use")
    parser.add_argument("--cacert", default="/etc/kubernetes/pki/ca.crt",
                        help="ca file extender will use")
    parser.add_argument("--unsafe", action="store_true",
                        help="unsafe instances of extender will be served over http")
    parser.add_argument("--syncPeriod", default="5s",
                        help="interval between cache syncs, e.g. 1m or 2s")
    parser.add_argument("--v", type=int, default=2, help="klog verbosity")
    parser.add_argument("--batchPlanner", action="store_true",
                        help="solve the whole pending set each sync period "
                        "and steer pods onto their batch-assigned nodes")
    parser.add_argument("--batchSolver", default="greedy",
                        choices=["greedy", "sinkhorn"],
                        help="batch planner solver: greedy (sequential-"
                        "equivalent) or sinkhorn (globally coordinated)")
    parser.add_argument("--nodeCacheCapable", action="store_true",
                        help="serve Prioritize/Filter from Args.NodeNames "
                        "(register the extender nodeCacheCapable: true); "
                        "large clusters avoid shipping full node objects")
    parser.add_argument("--serving", default="threaded",
                        choices=["threaded", "async"],
                        help="HTTP front-end: threaded (reference-parity "
                        "default) or async (event loop + micro-batched "
                        "device dispatch, docs/serving.md)")
    parser.add_argument("--batchWindow", default="1ms",
                        help="async serving: micro-batch coalescing window "
                        "(Go duration, e.g. 500us, 1ms)")
    parser.add_argument("--batchMax", type=int, default=64,
                        help="async serving: max requests fused per batch")
    parser.add_argument("--queueDepth", type=int, default=256,
                        help="async serving: admission queue bound; past it "
                        "requests get 503 + Retry-After")
    parser.add_argument("--rebalance", default="off",
                        choices=["off", "dry-run", "active"],
                        help="closed-loop rebalancer (docs/rebalance.md): "
                        "dry-run computes and publishes plans on "
                        "/debug/rebalance without touching the cluster; "
                        "active evicts through pods/eviction behind "
                        "rate-limit, cooldown and min-available guards")
    parser.add_argument("--rebalanceHysteresis", type=int, default=3,
                        help="consecutive violating enforcement cycles "
                        "before a node becomes an eviction candidate")
    parser.add_argument("--rebalanceMaxMoves", type=int, default=5,
                        help="churn budget: max evictions planned per cycle")
    parser.add_argument("--rebalanceSolver", default="greedy",
                        choices=["greedy", "sinkhorn"],
                        help="replan solver (mirrors --batchSolver)")
    parser.add_argument("--rebalanceCooldown", default="5m",
                        help="per-pod eviction cooldown (Go duration)")
    parser.add_argument("--rebalanceRate", type=float, default=0.5,
                        help="token-bucket eviction rate (evictions/s)")
    parser.add_argument("--rebalanceBurst", type=int, default=3,
                        help="token-bucket eviction burst")
    parser.add_argument("--rebalanceMinAvailable", type=int, default=1,
                        help="per-workload-group running-pod floor the "
                        "actuator must not evict below")
    common.add_profile_flag(parser)
    common.add_robustness_flags(parser)
    common.add_decision_flags(parser)
    common.add_event_flags(parser)
    common.add_gang_flags(parser)
    common.add_admission_flags(parser)
    common.add_shard_flags(parser)
    common.add_forecast_flags(parser)
    common.add_ha_flags(parser)
    common.add_slo_flags(parser)
    common.add_control_flags(parser)
    common.add_record_flags(parser)
    common.add_solveobs_flags(parser)
    return parser


def assemble(
    kube_client: KubeClient,
    metrics_client,
    sync_period_s: float,
    enable_device_path: bool = True,
    enable_batch_planner: bool = False,
    batch_solver: str = "greedy",
    node_cache_capable: bool = False,
    rebalance_mode: str = "off",
    rebalance_options: Optional[dict] = None,
    breakers=None,
    degraded_mode: Optional[str] = None,
    gang_tracker=None,
    forecast_options: Optional[dict] = None,
    leadership=None,
    gang_journal=None,
):
    """Wire cache + mirror + extender + controller + enforcer (the body of
    ``tasController``, reference cmd/main.go:53-95).  Returns the pieces and
    a stop Event controlling every background loop.

    ``breakers``/``degraded_mode``: when either is given, a
    DegradedModeController (tas/degraded.py) is built over the cache's
    freshness signal and the circuit states and attached to the
    extender, the enforcer, and the rebalancer — degraded Filter/
    Prioritize policy plus the unconditional eviction suspension.

    ``gang_tracker``: the --gang=on GangTracker
    (common.build_gang_tracker); attached to the extender so Filter/
    Prioritize/Bind consult gang reservations and the front-ends serve
    GET /debug/gangs (docs/gang.md).

    ``forecast_options``: the --forecast=on options dict
    (common.forecast_options); a Forecaster (forecast/engine.py) is
    built over the cache's history rings + the mirror and attached to
    the extender (predicted-value ranking, /debug/forecast), the
    degraded controller (bounded extrapolation), and the rebalancer
    (trend-aware hysteresis) — docs/forecast.md.

    ``leadership``: the --leaderElect LeaseElector
    (common.build_lease_elector); attached to the enforcer (deschedule
    label pass), the rebalancer + its actuator (cycle gate + per-
    eviction fencing), the gang tracker (dead-sweep), and the extender
    (/readyz condition, /debug/leader).  None — the default single-
    replica assembly — leaves every behavior byte-identical.

    ``gang_journal``: the --gangJournal=on GangJournal
    (common.build_gang_journal); the tracker journals reservation/bind
    mutations write-behind and recovers them here, reconciled against
    live pods, before any verb is served (docs/gang.md)."""
    cache = AutoUpdatingCache()
    mirror: Optional[TensorStateMirror] = None
    if enable_device_path:
        mirror = TensorStateMirror()
        mirror.attach(cache)
    planner = None
    if enable_batch_planner and mirror is not None:
        from platform_aware_scheduling_tpu.tas.planner import BatchPlanner

        planner = BatchPlanner(cache, mirror, solver=batch_solver)
    # the forecaster must exist BEFORE the extender: MetricsExtender's
    # constructor runs the first warm pass, and the history rings must
    # already be recording when the initial metric seeds land
    forecaster = common.build_forecaster(cache, mirror, forecast_options)
    extender = MetricsExtender(
        cache,
        mirror=mirror,
        planner=planner,
        node_cache_capable=node_cache_capable,
    )
    if forecaster is not None:
        extender.forecaster = forecaster
        # after the forecaster's own refit subscription (appended at its
        # construction above), so each refresh pass re-warms rankings
        # against the fit it JUST published — warm_fastpath alone fires
        # mid-pass, before the refit, and would leave every fresh
        # forecast view cold to its first request
        cache.on_refresh_pass.append(extender.warm_forecast_rankings)
    if gang_tracker is not None:
        extender.gangs = gang_tracker
        if gang_journal is not None:
            # crash-safe reservations: recover the journaled slices —
            # reconciled against live pods — BEFORE any verb can reserve
            # over them, then journal every durable mutation from here on
            gang_tracker.journal = gang_journal
            gang_tracker.recover()
    if leadership is not None:
        extender.leadership = leadership
        if gang_tracker is not None:
            gang_tracker.leadership = leadership

    enforcer = core.MetricEnforcer(kube_client, mirror=mirror)
    enforcer.leadership = leadership
    enforcer.register_strategy_type(deschedule.Strategy())
    enforcer.register_strategy_type(scheduleonmetric.Strategy())
    enforcer.register_strategy_type(dontschedule.Strategy())

    degraded = None
    if breakers is not None or degraded_mode is not None:
        from platform_aware_scheduling_tpu.tas.degraded import (
            MODE_LAST_KNOWN_GOOD,
            DegradedModeController,
        )

        degraded = DegradedModeController(
            cache,
            breakers=breakers,
            mode=degraded_mode or MODE_LAST_KNOWN_GOOD,
        )
        degraded.forecaster = forecaster  # bounded LKG extrapolation
        extender.degraded = degraded
        enforcer.degraded = degraded

    # closed-loop rebalancer (docs/rebalance.md): each deschedule
    # enforcement cycle feeds the drift detector; past the hysteresis
    # threshold the evictable pods are replanned on-device and (active
    # mode) evicted behind the actuator's guards.  Needs the mirror —
    # host-only assemblies stay label-only like the reference.
    if rebalance_mode != "off" and mirror is not None:
        from platform_aware_scheduling_tpu.rebalance import Rebalancer

        rebalancer = Rebalancer(
            kube_client, mirror, mode=rebalance_mode,
            **(rebalance_options or {}),
        )
        rebalancer.degraded = degraded
        rebalancer.forecaster = forecaster  # trend-aware hysteresis
        # singleton gating + per-eviction fencing (kube/lease.py): the
        # cycle idles as "follower" off-leader, and even the leader's
        # actuator re-verifies its fencing token before each eviction
        rebalancer.leadership = leadership
        rebalancer.actuator.leadership = leadership
        rebalancer.attach(enforcer)
        extender.rebalancer = rebalancer
        # gang-atomic eviction completes the loop: a whole-gang eviction
        # releases the gang's slice reservation (docs/gang.md)
        if gang_tracker is not None:
            rebalancer.actuator.gang_tracker = gang_tracker

    controller = TelemetryPolicyController(kube_client, cache, enforcer)

    stop = threading.Event()
    cache.start_periodic_update(sync_period_s, metrics_client, stop=stop)
    controller.run(stop)
    enforcer.start_enforcing(cache, sync_period_s, stop=stop)
    if planner is not None:
        planner_informer = planner.watch(kube_client)
        planner.start(sync_period_s)
        threading.Thread(
            target=lambda: (stop.wait(), planner_informer.stop()), daemon=True
        ).start()
    return cache, mirror, extender, controller, enforcer, stop


def build_server(
    extender,
    serving: str = "threaded",
    window_s: float = 0.001,
    max_batch: int = 64,
    max_queue_depth: int = 256,
):
    """The selected HTTP front-end over an extender: the reference-parity
    threaded server (default) or the event-loop micro-batching one
    (serving/, opt-in via --serving=async).  Shared by the TAS and GAS
    mains — both serve the same verbs through the same wire stack.

    /metrics serves the full exposition (verb histograms + serving
    counters + path-attribution and JAX compile counters — utils/trace.py);
    the async server composes the same page itself from the extender's
    shared recorder."""
    if serving == "async":
        from platform_aware_scheduling_tpu.serving import AsyncServer

        return AsyncServer(
            extender,
            window_s=window_s,
            max_batch=max_batch,
            max_queue_depth=max_queue_depth,
        )
    provider = getattr(
        extender, "metrics_text", extender.recorder.prometheus_text
    )
    return Server(extender, metrics_provider=provider)


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_arg_parser()
    args = parser.parse_args(argv)
    common.validate_control_flags(parser, args)
    common.validate_admission_flags(parser, args)
    common.validate_shard_flags(parser, args)
    klog.set_verbosity(args.v)
    sync_period_s = parse_duration(args.syncPeriod)
    # decision provenance + causal event journal on/off + ring sizes,
    # before any verb can record or publish
    common.configure_decisions(args)
    common.configure_events(args)

    # every remote call goes through the fault-tolerant proxy: retried
    # reads, breaker-gated writes, per-endpoint-group circuits
    # (kube/retry.py; docs/robustness.md).  The metrics client rides the
    # same proxy — its get_node_custom_metric verb lands in the
    # "metrics" circuit group
    retry_policy, breakers = common.build_fault_tolerance(args)
    kube_client = common.wrap_kube_client(
        get_kube_client(args.kubeConfig), retry_policy, breakers
    )
    metrics_client = CustomMetricsClient(kube_client)
    # HA control plane (docs/robustness.md "HA & leader election"):
    # leader election + crash-safe gang journal, both optional and both
    # riding the fault-tolerant client built above
    leadership = common.build_lease_elector(args, kube_client)
    gang_journal = common.build_gang_journal(args, kube_client, breakers)
    # cost-analysis capture hangs off each kernel's FIRST compile, which
    # assemble's warm pass triggers — install before assembly
    common.install_cost_visibility()
    gang_tracker = common.build_gang_tracker(args, kube_client)
    cache, mirror, extender, controller, _, stop = assemble(
        kube_client,
        metrics_client,
        sync_period_s,
        enable_batch_planner=args.batchPlanner,
        batch_solver=args.batchSolver,
        node_cache_capable=args.nodeCacheCapable,
        breakers=breakers,
        degraded_mode=args.degradedMode,
        gang_tracker=gang_tracker,
        forecast_options=common.forecast_options(args, sync_period_s),
        leadership=leadership,
        gang_journal=gang_journal,
        rebalance_mode=args.rebalance,
        rebalance_options={
            "hysteresis_cycles": args.rebalanceHysteresis,
            "max_moves": args.rebalanceMaxMoves,
            "solver": args.rebalanceSolver,
            "cooldown_s": parse_duration(args.rebalanceCooldown),
            "rate_per_s": args.rebalanceRate,
            "burst": args.rebalanceBurst,
            "min_available": args.rebalanceMinAvailable,
        },
    )

    # admission plane (--admission=on; docs/admission.md): the priority
    # queue both verbs consult, plus — with --preemption=on — the gang
    # preemption planner over its own dedicated active-mode actuator.
    # Built BEFORE the budget controller so the preemption-
    # aggressiveness knob can attach.  Off (the default) builds nothing
    common.build_admission_plane(
        args,
        extender,
        kube_client=kube_client,
        gang_tracker=gang_tracker,
        leadership=leadership,
    )

    # partition plane (--shard=on; docs/sharding.md): consistent-hash
    # partition ownership journaled in a ConfigMap, the telemetry
    # refresh cut to owned partitions, scatter/gather serving over
    # gossiped digests.  Built BEFORE the budget controller so the
    # per-partition shed knobs can attach.  Off (the default) builds
    # nothing — the wire stays byte-identical
    shard_plane = common.build_shard_plane(
        args,
        extender,
        kube_client=kube_client,
        cache=cache,
        mirror=mirror,
        leadership=leadership,
    )

    # SLO engine (--slo=on; docs/observability.md "SLOs & error
    # budgets"): judged over the extender's recorder + the cache's
    # freshness signal, ticked on its own daemon loop; attaching it to
    # the extender lights up /debug/slo, the pas_slo_* gauges, and the
    # informational slo_burn readiness condition.  Off (the default)
    # builds nothing — the wire stays byte-identical
    slo_engine = common.build_slo_engine(args, extender, cache=cache)
    if slo_engine is not None:
        slo_engine.start(common.slo_period(args, sync_period_s), stop=stop)

    # budget feedback controller (--sloControl=on; docs/observability.md
    # "Budget feedback control"): subscribed to the engine's post-tick
    # hook, stepping the rebalancer/forecaster/degraded knobs — the
    # admission knob joins below once the server (and so the dispatcher)
    # exists.  Off (the default) builds nothing
    budget_controller = common.build_budget_controller(
        args, extender, slo_engine
    )
    if budget_controller is not None and shard_plane is not None:
        # per-partition digest top-k shed knobs
        # (pas_control_knob_setting{knob=shard_topk_p<N>, partition=})
        budget_controller.attach_shard(shard_plane)

    # flight recorder (--flightRecorder=on; docs/observability.md
    # "Flight recorder & what-if"): anonymized verb/telemetry/control
    # events into a bounded ring behind GET /debug/record and
    # POST /debug/whatif.  Off (the default) builds nothing — the verbs
    # skip one attribute check and the wire stays byte-identical
    flight_recorder = common.build_flight_recorder(args, extender, cache=cache)
    if flight_recorder is not None and shard_plane is not None:
        # ownership changes land in the capture as anonymized shard
        # events (partition ids + fencing epochs only — record_shard)
        shard_plane.coordinator.flight = flight_recorder

    # solve observatory (--solveObs=on; docs/observability.md "Solve
    # observatory"): per-stage solve attribution + refresh churn behind
    # GET /debug/solve.  Built AFTER the flight recorder so churn passes
    # ride an enabled capture.  Off (the default) builds nothing — the
    # solve pays one module-global read and the wire stays byte-identical
    common.build_solve_observatory(args, extender, cache=cache)

    common.maybe_start_profiler(args.profilePort)
    common.start_device_watch(stop=stop)
    if leadership is not None:
        # the election loop starts AFTER assembly so a recovered gang
        # journal and warmed caches are in place before this replica can
        # win the lease and begin actuating
        leadership.start(stop)

    from platform_aware_scheduling_tpu.utils.gctuning import tune_for_serving

    tune_for_serving()
    server = build_server(
        extender,
        serving=args.serving,
        window_s=parse_duration(args.batchWindow),
        max_batch=args.batchMax,
        max_queue_depth=args.queueDepth,
    )
    if budget_controller is not None and hasattr(server, "dispatcher"):
        # the shed knob actuates the async front-end's live-read
        # admission bound; the threaded server has no admission queue,
        # so there the availability path simply has no knob
        budget_controller.attach_admission(server.dispatcher)
    # /readyz also waits on the TASPolicy CRD informer's initial list —
    # the extender's own conditions (warm + telemetry freshness) come
    # from its readiness_conditions() via the server's probe
    if controller.informer is not None:
        from platform_aware_scheduling_tpu.utils import health

        server.probe.register(
            "policy_informer_synced",
            health.informer_synced(controller.informer, "taspolicy"),
        )
    done = threading.Event()
    failed = []

    def serve():
        try:
            server.start_server(
                port=args.port,
                cert_file=args.cert,
                key_file=args.key,
                ca_file=args.cacert,
                unsafe=args.unsafe,
                block=True,
            )
        except Exception as exc:
            # a dead server must take the process down so the kubelet
            # restarts it, not leave a Running pod that serves nothing
            klog.error("extender server failed: %s", exc)
            failed.append(exc)
            done.set()

    threading.Thread(target=serve, daemon=True).start()

    # catchInterrupt (reference cmd/main.go:113-117)
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    stop.set()
    server.shutdown()
    klog.v(1).info_s("Exiting", component="extender")
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())

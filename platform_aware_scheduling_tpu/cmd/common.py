"""Flags and startup shared by the TAS and GAS service mains.

One helper owns the ``--profilePort`` flag AND the
``jax.profiler.start_server`` startup so the two mains cannot drift
(the GAS main historically lacked the flag entirely); same for the
device/observability wiring (cost-analysis hooks + the memory-watermark
sampler, utils/devicewatch.py).
"""

from __future__ import annotations

import argparse
import threading
from typing import Optional

from platform_aware_scheduling_tpu.utils import devicewatch, klog


def add_profile_flag(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--profilePort", type=int, default=0,
                        help="start the JAX profiler server on this port "
                        "(0 = off): connect TensorBoard/xprof on demand to "
                        "trace the device kernels with zero steady-state "
                        "overhead (SURVEY §5.1 — the reference has no "
                        "tracing at all)")


def add_robustness_flags(
    parser: argparse.ArgumentParser, degraded: bool = True
) -> None:
    """Fault-tolerance flag surface shared by both mains
    (docs/robustness.md): retry/backoff and circuit-breaker tuning.
    ``--degradedMode`` only exists where a DegradedModeController is
    actually built (TAS); offering a flag GAS would silently ignore is
    worse than not offering it."""
    parser.add_argument("--retryMaxAttempts", type=int, default=4,
                        help="max attempts per idempotent API read "
                        "(writes never blind-retry)")
    parser.add_argument("--retryBaseDelay", default="100ms",
                        help="first retry backoff (Go duration); doubles "
                        "per attempt with deterministic jitter")
    parser.add_argument("--retryMaxDelay", default="5s",
                        help="backoff cap (Go duration)")
    parser.add_argument("--retryDeadline", default="30s",
                        help="per-call deadline across all retry attempts "
                        "(Go duration)")
    parser.add_argument("--circuitFailureThreshold", type=int, default=5,
                        help="consecutive transport failures that open an "
                        "endpoint group's circuit")
    parser.add_argument("--circuitResetTimeout", default="30s",
                        help="how long an open circuit waits before the "
                        "half-open probe (Go duration)")
    if degraded:
        parser.add_argument("--degradedMode", default="last-known-good",
                            choices=["fail-open", "fail-closed",
                                     "last-known-good"],
                            help="dontschedule Filter policy while telemetry "
                            "is degraded: fail-open passes every candidate, "
                            "fail-closed passes none, last-known-good keeps "
                            "serving retained values within a bounded age "
                            "then fails open.  Evictions are ALWAYS "
                            "suspended while degraded (not configurable)")


def add_decision_flags(parser: argparse.ArgumentParser) -> None:
    """Decision-provenance flag surface shared by both mains
    (docs/observability.md "Decision provenance")."""
    parser.add_argument("--decisionLog", default="on",
                        choices=["off", "on"],
                        help="per-decision explain records behind "
                        "GET /debug/decisions: every Filter/Prioritize/"
                        "rebalance decision keeps its per-node reasons "
                        "and score breakdown, closed by pod-bind "
                        "feedback into pas_decision_* placement-quality "
                        "metrics.  Costs <=5%% serving p99 (pinned by "
                        "the http_load decision A/B); off disables "
                        "recording and 404s the endpoint")
    parser.add_argument("--decisionLogSize", type=int, default=512,
                        help="decision-log ring capacity; an open record "
                        "overwritten before its bind feedback counts in "
                        "pas_decision_evicted_open_total (size the ring "
                        "above pending-pods x verbs)")


def add_event_flags(parser: argparse.ArgumentParser) -> None:
    """Causal-event-spine flag surface shared by both mains
    (docs/observability.md "Explain plane")."""
    parser.add_argument("--events", default="on",
                        choices=["off", "on"],
                        help="causal event journal behind GET "
                        "/debug/explain: every subsystem publishes typed "
                        "events (wire spans, verdicts, admission holds, "
                        "preemptions, rebalance moves, controller "
                        "actuations, SLO flips) carrying correlation "
                        "keys, so one query returns the ordered causal "
                        "chain for a pod/gang/request/node.  Publication "
                        "costs <=5 us per warm verb (pinned by "
                        "obs_smoke); off publishes nothing and 404s the "
                        "endpoint")
    parser.add_argument("--eventsSize", type=int, default=4096,
                        help="event-journal ring capacity; overflow "
                        "drops the OLDEST event and counts it in "
                        "pas_events_dropped_total")


def configure_events(args) -> None:
    """Apply the shared event flags to the process-wide EventJournal."""
    from platform_aware_scheduling_tpu.utils import events

    events.JOURNAL.configure(
        enabled=getattr(args, "events", "on") == "on",
        capacity=getattr(args, "eventsSize", 4096),
    )


def add_gang_flags(parser: argparse.ArgumentParser) -> None:
    """Gang & topology-aware scheduling flag surface (docs/gang.md).
    One helper so a future GAS adoption cannot drift from TAS."""
    parser.add_argument("--gang", default="off", choices=["off", "on"],
                        help="all-or-nothing co-scheduling of multi-host "
                        "TPU slices: pods labeled pas-workload-group + "
                        "pas-gang-size (+ pas-gang-topology, e.g. 4x4) "
                        "atomically reserve a contiguous mesh slice at "
                        "Filter time, or fail every candidate.  Bypasses "
                        "the Filter response cache and the native "
                        "Prioritize scanner while on (the gang verdict is "
                        "pod-label-dependent state those caches cannot "
                        "key)")
    parser.add_argument("--gangReservationTTL", default="30s",
                        help="how long a gang's slice reservation holds "
                        "without bind progress before it is reclaimed "
                        "(Go duration); each member Filter refreshes it")
    parser.add_argument("--gangMeshRefresh", default="30s",
                        help="max age of the cached node mesh-coordinate "
                        "map (pas-tpu-coord labels) before the gang "
                        "tracker relists nodes (Go duration)")


def add_admission_flags(
    parser: argparse.ArgumentParser, preemption: bool = True
) -> None:
    """Priority-aware admission plane flag surface (docs/admission.md).
    One helper for both mains; GAS passes ``preemption=False`` — with
    no gang tracker there are no whole-gang victims to evict."""
    parser.add_argument("--admission", default="off", choices=["off", "on"],
                        help="priority-aware admission plane: pods carry "
                        "a pas-priority class label, capacity-class "
                        "Filter failures enqueue into a bounded per-class "
                        "queue, lower-priority pods are held behind "
                        "queued higher-priority work (with backfill and "
                        "per-class fairness), and the front-ends serve "
                        "GET /debug/admission.  Bypasses the Filter "
                        "response cache while on (the verdict is per-pod "
                        "queue state).  Off (the default) constructs "
                        "nothing and leaves the wire byte-identical")
    parser.add_argument("--admissionClasses", default="high,normal,batch",
                        help="comma-separated priority class ladder, most "
                        "important first (the pas-priority label values)")
    parser.add_argument("--admissionDefaultClass", default="normal",
                        help="class assigned to unlabeled (or unknown-"
                        "label) pods; must appear in --admissionClasses")
    parser.add_argument("--admissionDepth", type=int, default=64,
                        help="bounded queue depth; overflow sheds the "
                        "worst-ranked entry (or rejects the arrival when "
                        "it ranks worst)")
    parser.add_argument("--admissionFairnessStreak", type=int, default=8,
                        help="consecutive same-class admissions before a "
                        "waiting other class must be let through")
    parser.add_argument("--admissionStarveConsults", type=int, default=16,
                        help="queue consults after which each further "
                        "consult counts one pas_admission_starved_total "
                        "event (the class availability SLO's bad signal)")
    if preemption:
        parser.add_argument("--preemption", default="off",
                            choices=["off", "on"],
                            help="gang-aware preemption: a starving "
                            "higher-priority gang may displace strictly "
                            "lower-class gangs — whole gangs only, "
                            "all-or-nothing through the SafeActuator's "
                            "fenced atomic gang path, the freed slice "
                            "reserved before the victims finish "
                            "draining.  Requires --admission=on and "
                            "--gang=on")
        parser.add_argument("--preemptionMaxVictims", type=int, default=8,
                            help="max victim PODS one preemption plan "
                            "may evict (the budget controller's "
                            "aggressiveness knob steps this down under "
                            "availability burn)")
        parser.add_argument("--preemptionRetry", default="5s",
                            help="min interval between plans for the "
                            "same target gang (Go duration)")
        parser.add_argument("--preemptionRate", type=float, default=0.5,
                            help="preemption evictions per second "
                            "(token bucket, separate from the "
                            "rebalancer's)")
        parser.add_argument("--preemptionBurst", type=int, default=8,
                            help="preemption eviction burst; a victim "
                            "gang larger than this can never be evicted "
                            "atomically")
        parser.add_argument("--preemptionCooldown", default="5m",
                            help="per-pod eviction cooldown for the "
                            "preemption actuator (Go duration)")


def admission_classes(args) -> tuple:
    """The parsed --admissionClasses ladder."""
    return tuple(
        s.strip() for s in args.admissionClasses.split(",") if s.strip()
    )


def validate_admission_flags(parser: argparse.ArgumentParser, args) -> None:
    """Fail fast (exit 2 with usage) on contradictory admission wiring
    instead of silently no-opping at runtime."""
    if getattr(args, "admission", "off") == "on":
        classes = admission_classes(args)
        if not classes or len(set(classes)) != len(classes):
            parser.error(
                f"--admissionClasses {args.admissionClasses!r} is not a "
                f"valid ladder: need at least one class, no duplicates"
            )
        if args.admissionDefaultClass not in classes:
            parser.error(
                f"--admissionDefaultClass {args.admissionDefaultClass!r} "
                f"is not in --admissionClasses {args.admissionClasses!r}"
            )
    if getattr(args, "preemption", "off") == "on":
        if getattr(args, "admission", "off") != "on":
            parser.error(
                "--preemption=on requires --admission=on: the planner "
                "triggers from the admission queue's starving gangs; "
                "without the plane there is no trigger"
            )
        if getattr(args, "gang", "off") != "on":
            parser.error(
                "--preemption=on requires --gang=on: victims are whole "
                "gangs from the tracker's census and the freed slice is "
                "reserved through it; without the tracker there is "
                "nothing to preempt or reserve"
            )


def build_admission_plane(
    args, extender, kube_client=None, gang_tracker=None, leadership=None
):
    """The AdmissionPlane for --admission=on (None when off), attached
    as ``extender.admission`` (the verbs, /metrics, and
    /debug/admission all key off that attr).  With --preemption=on a
    PreemptionPlanner rides along over its own dedicated SafeActuator —
    active mode by definition (preemption that cannot evict is just
    queueing), its own token bucket so a preemption burst cannot starve
    the rebalancer's budget (or vice versa)."""
    if getattr(args, "admission", "off") != "on":
        return None
    from platform_aware_scheduling_tpu.admission import (
        AdmissionPlane,
        PreemptionPlanner,
    )

    plane = AdmissionPlane(
        classes=admission_classes(args),
        default_class=args.admissionDefaultClass,
        max_depth=args.admissionDepth,
        fairness_streak=args.admissionFairnessStreak,
        starve_consults=args.admissionStarveConsults,
    )
    plane.gangs = gang_tracker
    if (
        getattr(args, "preemption", "off") == "on"
        and gang_tracker is not None
        and kube_client is not None
    ):
        from platform_aware_scheduling_tpu.rebalance.actuator import (
            MODE_ACTIVE,
            SafeActuator,
        )
        from platform_aware_scheduling_tpu.utils.duration import (
            parse_duration,
        )

        actuator = SafeActuator(
            kube_client,
            mode=MODE_ACTIVE,
            rate_per_s=args.preemptionRate,
            burst=args.preemptionBurst,
            cooldown_s=parse_duration(args.preemptionCooldown),
        )
        # NOT actuator.gang_tracker: the rebalancer path's full-gang
        # auto-release would fight reservation-while-draining — the
        # planner marks victims DRAINING itself and the tracker's sweep
        # releases them when the pods are gone
        actuator.leadership = leadership
        plane.preemption = PreemptionPlanner(
            plane,
            gang_tracker,
            actuator,
            max_victims=args.preemptionMaxVictims,
            retry_s=parse_duration(args.preemptionRetry),
            leadership=leadership,
        )
    extender.admission = plane
    return plane


def add_shard_flags(parser: argparse.ArgumentParser) -> None:
    """Partition-plane flag surface (docs/sharding.md)."""
    parser.add_argument("--shard", default="off", choices=["off", "on"],
                        help="consistent-hash partition plane: the node "
                        "universe hashes into --shardPartitions "
                        "partitions, ownership is journaled+fenced in a "
                        "ConfigMap, each replica refreshes and mirrors "
                        "ONLY its owned partitions, and Filter/"
                        "Prioritize answer scatter/gather from the "
                        "local solve plus gossiped remote digests "
                        "(peer /debug/shard pulls).  Bypasses the "
                        "Filter response cache while on (the merged "
                        "verdict depends on digest freshness).  Off "
                        "(the default) constructs nothing and leaves "
                        "the wire byte-identical")
    parser.add_argument("--shardPartitions", type=int, default=4,
                        help="partition count P; every replica must "
                        "agree on it (it is the modulus of the "
                        "consistent hash)")
    parser.add_argument("--shardPeers", default="",
                        help="comma-separated peer base URLs "
                        "(http://host:port) whose /debug/shard this "
                        "replica pulls remote-partition digests from; "
                        "empty serves local partitions only")
    parser.add_argument("--shardTopK", type=int, default=16,
                        help="per-metric candidate summaries carried in "
                        "each partition digest (k lowest + k highest); "
                        "the budget controller's per-partition shed "
                        "knob steps this down under freshness burn")
    parser.add_argument("--shardStaleBound", default="30s",
                        help="digest staleness bound (Go duration): a "
                        "remote digest older than this stops serving "
                        "and the gather fails open to local-only "
                        "answers (edge-triggered digest_stale event)")
    parser.add_argument("--shardMemberTTL", default="15s",
                        help="membership heartbeat TTL (Go duration): a "
                        "replica silent for longer drops from the "
                        "rendezvous and its partitions hand off")
    parser.add_argument("--shardConfigMap", default="pas-shard-partitions",
                        help="ConfigMap name holding the journaled "
                        "partition-ownership state")


def shard_peers(args) -> tuple:
    """The parsed --shardPeers URL list."""
    return tuple(
        s.strip() for s in getattr(args, "shardPeers", "").split(",")
        if s.strip()
    )


def validate_shard_flags(parser: argparse.ArgumentParser, args) -> None:
    """Fail fast (exit 2 with usage) on contradictory shard wiring."""
    if getattr(args, "shard", "off") != "on":
        return
    if args.shardPartitions < 1:
        parser.error(
            f"--shardPartitions {args.shardPartitions} must be >= 1"
        )
    if args.shardTopK < 1:
        parser.error(f"--shardTopK {args.shardTopK} must be >= 1")
    for peer in shard_peers(args):
        if not (peer.startswith("http://") or peer.startswith("https://")):
            parser.error(
                f"--shardPeers entry {peer!r} is not a base URL "
                f"(expected http://host:port)"
            )


def build_shard_plane(
    args, extender, kube_client, cache, mirror, leadership=None
):
    """The ShardPlane for --shard=on (None when off), attached as
    ``extender.shard`` (the verbs, /metrics, and /debug/shard all key
    off that attr) and wired into the cache/mirror: the refresh filter
    drops non-owned nodes at ingest and the refresh pass drives
    coordination + digest publish + gossip — no new threads."""
    if getattr(args, "shard", "off") != "on":
        return None
    from platform_aware_scheduling_tpu.shard import ShardPlane
    from platform_aware_scheduling_tpu.utils.duration import parse_duration

    plane = ShardPlane(
        identity=replica_identity(args),
        partitions=args.shardPartitions,
        kube_client=kube_client,
        namespace=getattr(args, "leaseNamespace", "default") or "default",
        configmap=args.shardConfigMap,
        leadership=leadership,
        peers=shard_peers(args),
        topk=args.shardTopK,
        stale_after_s=parse_duration(args.shardStaleBound),
        member_ttl_s=parse_duration(args.shardMemberTTL),
    )
    if cache is not None and mirror is not None:
        plane.attach(cache, mirror)
    extender.shard = plane
    return plane


def add_forecast_flags(
    parser: argparse.ArgumentParser, forecast: bool = True
) -> None:
    """Predictive-telemetry flag surface (docs/forecast.md).  Like
    ``--degradedMode``, the flags only exist where a Forecaster is
    actually built (TAS): GAS has no telemetry cache to forecast over,
    and offering flags it would silently ignore is worse than not
    offering them (``add_forecast_flags(parser, forecast=False)`` is the
    explicit no-op adoption both mains share)."""
    if not forecast:
        return
    parser.add_argument("--forecast", default="off", choices=["off", "on"],
                        help="schedule on forecasts, not snapshots: a "
                        "batched on-device EWMA/Holt fit over the "
                        "telemetry refresh history ranks scheduleonmetric "
                        "on predicted-at-bind values, holds eviction "
                        "streaks on transient spikes trending back down, "
                        "and lets degraded last-known-good mode serve "
                        "bounded extrapolations (docs/forecast.md)")
    parser.add_argument("--forecastWindow", type=int, default=32,
                        help="refresh-history samples kept per metric "
                        "(the fit's lookback window)")
    parser.add_argument("--forecastHorizon", default="",
                        help="how far ahead predictions target (Go "
                        "duration); empty = one refresh period ahead "
                        "(the value at the next refresh); capped at "
                        "--forecastWindow refresh steps — no fit "
                        "predicts further ahead than it looked back")
    parser.add_argument("--forecastBandBound", type=float, default=0.25,
                        help="max mean relative uncertainty band under "
                        "which degraded LKG mode keeps serving forecast "
                        "extrapolations; past it the pre-forecast "
                        "frozen-LKG/neutral behavior returns")


def add_ha_flags(parser: argparse.ArgumentParser, ha: bool = True) -> None:
    """HA control-plane flag surface (docs/robustness.md "HA & leader
    election"): leader election over a coordination.k8s.io Lease plus
    the crash-safe gang reservation journal.  Like ``--degradedMode``
    and ``--forecast``, the flags only exist where the machinery does
    (TAS): GAS runs no singleton actuation loops and keeps no gang
    state, and offering flags it would silently ignore is worse than
    not offering them (``add_ha_flags(parser, ha=False)`` is the
    explicit no-op adoption both mains share)."""
    if not ha:
        return
    parser.add_argument("--leaderElect", action="store_true",
                        help="run N replicas behind one Service with "
                        "exactly one executing the actuation loops "
                        "(rebalancer, deschedule labels, gang sweep): "
                        "leadership rides a coordination.k8s.io Lease "
                        "with a monotonic fencing token; followers keep "
                        "serving Filter/Prioritize at full quality.  Off "
                        "(the default) changes nothing on the wire")
    parser.add_argument("--leaseName", default="pas-tas-extender",
                        help="name of the leadership Lease object")
    parser.add_argument("--leaseNamespace", default="default",
                        help="namespace of the leadership Lease")
    parser.add_argument("--leaseDuration", default="15s",
                        help="how long a leadership grant survives "
                        "without renew before standbys may take over "
                        "(Go duration); also the deposed leader's "
                        "self-demotion deadline")
    parser.add_argument("--leaseRenewPeriod", default="",
                        help="interval between renew/acquire attempts "
                        "(Go duration); empty = a third of "
                        "--leaseDuration, jittered deterministically "
                        "per replica")
    parser.add_argument("--replicaId", default="",
                        help="this replica's lease holder identity; "
                        "empty derives hostname-pid")
    parser.add_argument("--gangJournal", default="off",
                        choices=["off", "on"],
                        help="journal gang slice reservations and binds "
                        "to a ConfigMap (write-behind, breaker-gated) "
                        "and recover them at startup, reconciled "
                        "against live pods — a restart no longer "
                        "orphans in-flight gangs (docs/gang.md)")
    parser.add_argument("--gangJournalName", default="pas-gang-journal",
                        help="name of the journal ConfigMap")
    parser.add_argument("--gangJournalNamespace", default="default",
                        help="namespace of the journal ConfigMap")


def add_slo_flags(parser: argparse.ArgumentParser) -> None:
    """SLO engine flag surface shared by both mains
    (docs/observability.md "SLOs & error budgets")."""
    parser.add_argument("--slo", default="off", choices=["off", "on"],
                        help="evaluate first-class SLOs over the process's "
                        "own metrics: verb availability, Filter/Prioritize "
                        "latency, telemetry freshness and eviction safety "
                        "(TAS), with Google-SRE multi-window burn-rate "
                        "alerting (page 5m/1h, warn 6h/3d) on "
                        "pas_slo_burn_rate and GET /debug/slo.  Off (the "
                        "default) registers no gauges and changes nothing "
                        "on the wire — the engine never touches the "
                        "request path")
    parser.add_argument("--sloConfig", default="",
                        help="JSON SLO overrides merged by name over the "
                        "default set: a list (or {\"slos\": [...]}) of "
                        "{name, sli, objective, verbs, threshold_ms, "
                        "good, bad, page_burn, warn_burn} entries; "
                        "{\"name\": ..., \"disabled\": true} removes a "
                        "default.  Malformed input fails startup")
    parser.add_argument("--sloPeriod", default="",
                        help="SLO evaluation tick period (Go duration); "
                        "empty = the sync period (TAS) or 5s (GAS)")


def build_slo_engine(args, extender, cache=None, period_s: float = 5.0):
    """The SLOEngine for --slo=on (None when off): the default SLO set
    for this main (TAS when a telemetry cache is given, GAS otherwise)
    merged with --sloConfig, reading the extender's recorder and — on
    TAS — the cache's freshness signal.  Attached as ``extender.slo``
    (the /debug/slo + /metrics + readiness wiring keys off that attr);
    the caller starts the tick loop."""
    if getattr(args, "slo", "off") != "on":
        return None
    from platform_aware_scheduling_tpu.utils.slo import (
        SLOEngine,
        default_slos,
        merge_config,
    )

    slos = merge_config(
        default_slos(tas=cache is not None),
        getattr(args, "sloConfig", ""),
    )
    engine = SLOEngine(
        slos,
        recorders=[extender.recorder],
        freshness=cache.telemetry_freshness if cache is not None else None,
    )
    extender.slo = engine
    return engine


def add_control_flags(parser: argparse.ArgumentParser) -> None:
    """Budget-controller flag surface shared by both mains
    (docs/observability.md "Budget feedback control")."""
    parser.add_argument("--sloControl", default="off", choices=["off", "on"],
                        help="close the SLO loop: a budget controller "
                        "subscribes to the engine's burn-rate evaluations "
                        "and steps bounded knobs — admission queue depth "
                        "(availability), rebalancer max-moves/hysteresis "
                        "(eviction safety), extrapolation band/horizon/LKG "
                        "bounds (freshness) — one ladder step per tick, "
                        "hysteretic loosening, every actuation on "
                        "pas_control_* and GET /debug/control.  Requires "
                        "--slo=on; off (the default) constructs nothing "
                        "and leaves the wire byte-identical")


def validate_control_flags(parser: argparse.ArgumentParser, args) -> None:
    """Fail fast at flag parse on contradictory wiring: the controller
    actuates on the SLO engine's evaluations, so --sloControl=on with
    --slo=off could only ever no-op silently — reject it loudly
    instead (parser.error exits 2 with usage, like any bad flag)."""
    if (
        getattr(args, "sloControl", "off") == "on"
        and getattr(args, "slo", "off") != "on"
    ):
        parser.error(
            "--sloControl=on requires --slo=on: the budget controller "
            "actuates on the SLO engine's burn-rate evaluations; "
            "without the judge there is nothing to control"
        )


def build_budget_controller(args, extender, engine):
    """The BudgetController for --sloControl=on (None when off),
    subscribed to ``engine`` and attached as ``extender.control`` (the
    /debug/control + /metrics wiring keys off that attr).  Every
    actuator the extender actually has gets a knob: the rebalancer's
    aggressiveness pair, the forecaster's extrapolation bounds (plus
    its surge signal as the trend pre-arm source), and the degraded
    controller's last-known-good trust.  The admission knob is the
    async front-end's dispatcher — the caller attaches it after
    build_server (assembly order: the server does not exist yet
    here)."""
    if getattr(args, "sloControl", "off") != "on":
        return None
    from platform_aware_scheduling_tpu.utils.control import BudgetController

    forecaster = getattr(extender, "forecaster", None)
    controller = BudgetController(
        engine,
        trend_source=(
            forecaster.predicts_surge if forecaster is not None else None
        ),
    )
    rebalancer = getattr(extender, "rebalancer", None)
    if rebalancer is not None:
        controller.attach_rebalancer(rebalancer)
    if forecaster is not None:
        controller.attach_forecaster(forecaster)
    degraded = getattr(extender, "degraded", None)
    if degraded is not None:
        controller.attach_degraded(degraded)
    admission = getattr(extender, "admission", None)
    if admission is not None and admission.preemption is not None:
        # preemption aggressiveness: sustained availability burn steps
        # the per-plan victim budget down (utils/control.py)
        controller.attach_preemption(admission.preemption)
    extender.control = controller
    return controller


def add_record_flags(parser: argparse.ArgumentParser) -> None:
    """Flight-recorder flag surface shared by both mains
    (docs/observability.md "Flight recorder & what-if")."""
    parser.add_argument("--flightRecorder", default="off",
                        choices=["off", "on"],
                        help="bounded ring of ANONYMIZED control-plane "
                        "events (verb arrivals keyed by the interned-"
                        "universe digest + candidate count, per-refresh "
                        "telemetry decile curves, eviction/leader flips "
                        "— never node, pod, or namespace names), "
                        "exported as versioned JSONL on GET /debug/record "
                        "and replayable through the digital twin "
                        "(POST /debug/whatif, python -m ...cmd.whatif). "
                        "Costs <=5%% serving p99 (pinned by the http_load "
                        "recorder A/B); off records nothing and 404s "
                        "both endpoints")
    parser.add_argument("--recordSize", type=int, default=4096,
                        help="flight-recorder ring capacity; overflow "
                        "drops the OLDEST event (the recorder keeps the "
                        "latest window) and counts it in "
                        "pas_record_dropped_total")


def build_flight_recorder(args, extender, cache=None):
    """The FlightRecorder for --flightRecorder=on (None when off),
    attached as ``extender.flight`` (the /debug/record + /debug/whatif +
    /metrics wiring keys off that attr).  With a telemetry ``cache``
    (TAS), one ``on_refresh_pass`` subscription summarizes each pass's
    metric values into decile events and polls the eviction/leadership
    families — the same hook the forecaster refits on, so control
    events cost nothing on the request path."""
    if getattr(args, "flightRecorder", "off") != "on":
        return None
    from platform_aware_scheduling_tpu.utils.record import FlightRecorder

    recorder = FlightRecorder(
        capacity=getattr(args, "recordSize", 4096)
    )
    extender.flight = recorder
    if cache is not None:
        cache.on_refresh_pass.append(
            lambda: recorder.observe_cache(cache)
        )
    # the causal spine exports through the same capture (anonymized to
    # kind/event/tick + an irreversible correlation hash — record_spine)
    from platform_aware_scheduling_tpu.utils import events

    events.JOURNAL.flight = recorder
    return recorder


def add_solveobs_flags(parser: argparse.ArgumentParser) -> None:
    """Solve-observatory flag surface shared by both mains
    (docs/observability.md "Solve observatory")."""
    parser.add_argument("--solveObs", default="off",
                        choices=["off", "on"],
                        help="per-stage device-solve attribution "
                        "(snapshot/transfer/compile/execute/readback/"
                        "encode rings + pas_solve_stage_us histograms), "
                        "refresh churn telemetry (changed rows per "
                        "metric per pass, pas_state_churn_*), and the "
                        "per-kernel recompile watch, served on GET "
                        "/debug/solve.  Off instruments nothing — the "
                        "solve pays one module-global read and the wire "
                        "stays byte-identical")
    parser.add_argument("--solveObsSize", type=int, default=256,
                        help="solve-observatory sample ring capacity; "
                        "overflow drops the OLDEST sample (stage "
                        "histograms keep the full history)")


def build_solve_observatory(args, extender, cache=None):
    """The SolveObservatory for --solveObs=on (None when off), installed
    in the process-wide ``ops.solveobs.ACTIVE`` slot (the instrumented
    sites span layers that never see the extender) and attached as
    ``extender.solveobs`` for the /debug/solve route.  With a telemetry
    ``cache`` (TAS), one ``on_refresh_pass`` subscription drains the
    mirror's per-metric churn counts into histograms, the causal spine,
    and — when a flight recorder is also wired — the capture, so churn
    accounting costs nothing on the request path."""
    if getattr(args, "solveObs", "off") != "on":
        return None
    from platform_aware_scheduling_tpu.ops import solveobs

    observatory = solveobs.enable(
        capacity=getattr(args, "solveObsSize", 256)
    )
    observatory.mirror = getattr(extender, "mirror", None)
    observatory.flight = getattr(extender, "flight", None)
    extender.solveobs = observatory
    if cache is not None:
        cache.on_refresh_pass.append(observatory.flush_refresh_pass)
    return observatory


def slo_period(args, default_s: float) -> float:
    """The --sloPeriod in seconds (default: the caller's sync period)."""
    raw = getattr(args, "sloPeriod", "")
    if not raw:
        return default_s
    from platform_aware_scheduling_tpu.utils.duration import parse_duration

    return parse_duration(raw)


def replica_identity(args) -> str:
    """The lease holder identity: --replicaId or hostname-pid."""
    explicit = getattr(args, "replicaId", "")
    if explicit:
        return explicit
    import os
    import socket

    return f"{socket.gethostname()}-{os.getpid()}"


def build_lease_elector(args, kube_client):
    """The LeaseElector for --leaderElect (None when off).  The client
    should already be the fault-tolerant proxy: lease verbs are
    classified idempotent-by-fencing there, so acquire/renew retry
    within the lease duration (kube/retry.py)."""
    if not getattr(args, "leaderElect", False):
        return None
    from platform_aware_scheduling_tpu.kube.lease import LeaseElector
    from platform_aware_scheduling_tpu.utils.duration import parse_duration

    duration_s = parse_duration(args.leaseDuration)
    renew_s = (
        parse_duration(args.leaseRenewPeriod)
        if getattr(args, "leaseRenewPeriod", "")
        else None
    )
    return LeaseElector(
        kube_client,
        identity=replica_identity(args),
        lease_name=args.leaseName,
        namespace=args.leaseNamespace,
        lease_duration_s=duration_s,
        renew_period_s=renew_s,
    )


def build_gang_journal(args, kube_client, breakers=None):
    """The GangJournal for --gangJournal=on (None when off, or when
    --gang is off — there is no state to journal).

    The reservation ledger is REPLICA-LOCAL (each tracker journals its
    own full-state snapshots), so under --leaderElect the journal name
    is suffixed with the replica identity — N replicas sharing one
    ConfigMap would last-writer-wins erase each other's reservations.
    For recovery to find the journal across restarts, give replicas a
    STABLE --replicaId (e.g. the StatefulSet pod name); the hostname-pid
    default changes on every restart and orphans the previous journal
    (docs/gang.md "Crash-safe reservations")."""
    if getattr(args, "gangJournal", "off") != "on":
        return None
    if getattr(args, "gang", "off") != "on":
        return None
    from platform_aware_scheduling_tpu.gang import GangJournal

    name = args.gangJournalName
    if getattr(args, "leaderElect", False):
        name = f"{name}-{replica_identity(args)}"
    return GangJournal(
        kube_client,
        name=name,
        namespace=args.gangJournalNamespace,
        breakers=breakers,
    )


def forecast_options(args, sync_period_s: float) -> Optional[dict]:
    """The --forecast* flags as the options dict ``assemble`` builds a
    Forecaster from (None = off)."""
    if getattr(args, "forecast", "off") != "on":
        return None
    from platform_aware_scheduling_tpu.utils.duration import parse_duration

    horizon_s = None
    if getattr(args, "forecastHorizon", ""):
        horizon_s = parse_duration(args.forecastHorizon)
    return {
        "window": args.forecastWindow,
        "horizon_s": horizon_s,
        "period_s": sync_period_s,
        "band_bound": args.forecastBandBound,
    }


def build_forecaster(cache, mirror, options: Optional[dict]):
    """The Forecaster for --forecast=on (None when off or when the
    assembly is host-only — the forecast views ride the device mirror)."""
    if options is None or mirror is None:
        return None
    from platform_aware_scheduling_tpu.forecast import Forecaster

    return Forecaster(cache, mirror, **options)


def build_gang_tracker(args, kube_client):
    """The GangTracker for --gang=on (None when off), over the kube
    client's node list as the mesh-coordinate source."""
    if getattr(args, "gang", "off") != "on":
        return None
    from platform_aware_scheduling_tpu.gang import GangTracker
    from platform_aware_scheduling_tpu.utils.duration import parse_duration

    return GangTracker(
        nodes_provider=kube_client.list_nodes,
        pods_provider=kube_client.list_pods,
        ttl_s=parse_duration(args.gangReservationTTL),
        mesh_max_age_s=parse_duration(args.gangMeshRefresh),
    )


def configure_decisions(args) -> None:
    """Apply the shared decision flags to the process-wide DecisionLog."""
    from platform_aware_scheduling_tpu.utils import decisions

    decisions.DECISIONS.configure(
        enabled=args.decisionLog == "on", capacity=args.decisionLogSize
    )


def build_fault_tolerance(args):
    """(RetryPolicy, CircuitBreakerRegistry) from the shared flags."""
    from platform_aware_scheduling_tpu.kube.retry import (
        CircuitBreakerRegistry,
        RetryPolicy,
    )
    from platform_aware_scheduling_tpu.utils.duration import parse_duration

    policy = RetryPolicy(
        max_attempts=args.retryMaxAttempts,
        base_delay_s=parse_duration(args.retryBaseDelay),
        max_delay_s=parse_duration(args.retryMaxDelay),
        deadline_s=parse_duration(args.retryDeadline),
    )
    breakers = CircuitBreakerRegistry(
        failure_threshold=args.circuitFailureThreshold,
        reset_timeout_s=parse_duration(args.circuitResetTimeout),
    )
    return policy, breakers


def wrap_kube_client(kube_client, policy, breakers):
    """The fault-tolerant proxy both mains put in front of every API
    consumer (kube/retry.py)."""
    from platform_aware_scheduling_tpu.kube.retry import FaultTolerantClient

    return FaultTolerantClient(kube_client, policy=policy, breakers=breakers)


def maybe_start_profiler(port: int) -> bool:
    """Start the JAX profiler server when ``port`` is nonzero; returns
    whether it is serving.  Profiling must never block serving — any
    failure logs and the main continues."""
    if not port:
        return False
    try:
        import jax.profiler

        jax.profiler.start_server(port)
        klog.v(1).info_s(
            f"JAX profiler serving on :{port}", component="extender"
        )
        return True
    except Exception as exc:
        klog.error("profiler server failed: %s", exc)
        return False


def install_cost_visibility() -> None:
    """Install the one-shot per-kernel cost-analysis capture
    (utils/devicewatch.py).  Call BEFORE assembly — the capture hangs
    off each watched kernel's FIRST compile, which assembly's warm pass
    triggers."""
    devicewatch.install_cost_hooks()


def start_device_watch(
    stop: Optional[threading.Event] = None, sample_period_s: float = 10.0
) -> devicewatch.DeviceWatcher:
    """Start the device memory-watermark sampler on a daemon thread
    (graceful no-op on CPU); returns the watcher."""
    watcher = devicewatch.DeviceWatcher(period_s=sample_period_s)
    watcher.start(stop=stop)
    return watcher

"""Flags and startup shared by the TAS and GAS service mains.

One helper owns the ``--profilePort`` flag AND the
``jax.profiler.start_server`` startup so the two mains cannot drift
(the GAS main historically lacked the flag entirely); same for the
device/observability wiring (cost-analysis hooks + the memory-watermark
sampler, utils/devicewatch.py).
"""

from __future__ import annotations

import argparse
import threading
from typing import Optional

from platform_aware_scheduling_tpu.utils import devicewatch, klog


def add_profile_flag(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--profilePort", type=int, default=0,
                        help="start the JAX profiler server on this port "
                        "(0 = off): connect TensorBoard/xprof on demand to "
                        "trace the device kernels with zero steady-state "
                        "overhead (SURVEY §5.1 — the reference has no "
                        "tracing at all)")


def maybe_start_profiler(port: int) -> bool:
    """Start the JAX profiler server when ``port`` is nonzero; returns
    whether it is serving.  Profiling must never block serving — any
    failure logs and the main continues."""
    if not port:
        return False
    try:
        import jax.profiler

        jax.profiler.start_server(port)
        klog.v(1).info_s(
            f"JAX profiler serving on :{port}", component="extender"
        )
        return True
    except Exception as exc:
        klog.error("profiler server failed: %s", exc)
        return False


def install_cost_visibility() -> None:
    """Install the one-shot per-kernel cost-analysis capture
    (utils/devicewatch.py).  Call BEFORE assembly — the capture hangs
    off each watched kernel's FIRST compile, which assembly's warm pass
    triggers."""
    devicewatch.install_cost_hooks()


def start_device_watch(
    stop: Optional[threading.Event] = None, sample_period_s: float = 10.0
) -> devicewatch.DeviceWatcher:
    """Start the device memory-watermark sampler on a daemon thread
    (graceful no-op on CPU); returns the watcher."""
    watcher = devicewatch.DeviceWatcher(period_s=sample_period_s)
    watcher.start(stop=stop)
    return watcher

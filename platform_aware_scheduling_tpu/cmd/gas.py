"""GAS service main.

Reference: gpu-aware-scheduling/cmd/gas-scheduler-extender/main.go:11-35 —
flags, extender assembly, HTTP(S) serving.
"""

from __future__ import annotations

import argparse
import os
import signal
import threading
from typing import List, Optional

from platform_aware_scheduling_tpu.cmd import common
from platform_aware_scheduling_tpu.gas.scheduler import GASExtender
from platform_aware_scheduling_tpu.kube.client import get_kube_client
from platform_aware_scheduling_tpu.utils import klog


def build_arg_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="gas-extender",
        description="GPU-aware scheduling extender (TPU-native)",
    )
    default_kubeconfig = os.path.join(
        os.environ.get("HOME", "/root"), ".kube", "config"
    )
    parser.add_argument("--kubeConfig", default=default_kubeconfig)
    parser.add_argument("--port", default="9001")
    parser.add_argument("--cert", default="/etc/kubernetes/pki/ca.crt")
    parser.add_argument("--key", default="/etc/kubernetes/pki/ca.key")
    parser.add_argument("--cacert", default="/etc/kubernetes/pki/ca.crt")
    parser.add_argument("--unsafe", action="store_true")
    parser.add_argument("--v", type=int, default=4, help="klog verbosity")
    parser.add_argument("--serving", default="threaded",
                        choices=["threaded", "async"],
                        help="HTTP front-end: threaded (reference-parity "
                        "default) or async (event loop + micro-batched "
                        "dispatch, docs/serving.md)")
    parser.add_argument("--batchWindow", default="1ms",
                        help="async serving: micro-batch coalescing window")
    parser.add_argument("--batchMax", type=int, default=64,
                        help="async serving: max requests fused per batch")
    parser.add_argument("--queueDepth", type=int, default=256,
                        help="async serving: admission queue bound; past it "
                        "requests get 503 + Retry-After")
    # parity with cmd/tas.py via the one shared helper (cmd/common.py);
    # forecast=False: GAS has no telemetry cache to forecast over, so the
    # --forecast* flags are explicitly NOT offered (no dead flags — the
    # same stance --degradedMode takes above)
    common.add_profile_flag(parser)
    common.add_robustness_flags(parser, degraded=False)
    common.add_decision_flags(parser)
    common.add_event_flags(parser)
    # queue-only admission: GAS has no gang tracker, so the --preemption
    # surface is explicitly NOT offered (no dead flags)
    common.add_admission_flags(parser, preemption=False)
    common.add_forecast_flags(parser, forecast=False)
    common.add_ha_flags(parser, ha=False)
    common.add_slo_flags(parser)
    common.add_control_flags(parser)
    common.add_record_flags(parser)
    common.add_solveobs_flags(parser)
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_arg_parser()
    args = parser.parse_args(argv)
    common.validate_control_flags(parser, args)
    common.validate_admission_flags(parser, args)
    klog.set_verbosity(args.v)
    common.configure_decisions(args)
    common.configure_events(args)

    # fault-tolerant proxy in front of every API consumer — GAS has no
    # telemetry cache so no degraded-mode controller, but its informers
    # and bind/annotate traffic get the same retry/backoff/circuit
    # treatment as TAS (docs/robustness.md)
    retry_policy, breakers = common.build_fault_tolerance(args)
    kube_client = common.wrap_kube_client(
        get_kube_client(args.kubeConfig), retry_policy, breakers
    )
    # before the extender warms its device binpack kernels (cost capture
    # rides each kernel's first compile)
    common.install_cost_visibility()
    extender = GASExtender(kube_client, retry_policy=retry_policy)
    # admission plane (--admission=on): queue-only here — no gang
    # tracker, so backfill runs size-only and preemption never attaches
    common.build_admission_plane(args, extender, kube_client=kube_client)

    common.maybe_start_profiler(args.profilePort)
    watch_stop = threading.Event()
    common.start_device_watch(stop=watch_stop)
    # SLO engine (--slo=on): GAS gets the verb-availability +
    # gas_filter-latency defaults (no telemetry cache to judge freshness
    # over); off builds nothing (docs/observability.md)
    slo_engine = common.build_slo_engine(args, extender)
    if slo_engine is not None:
        slo_engine.start(common.slo_period(args, 5.0), stop=watch_stop)
    # budget controller (--sloControl=on): GAS has no rebalancer/
    # forecaster/degraded actuators, so only the admission knob (async
    # serving) can attach below; the controller still observes
    budget_controller = common.build_budget_controller(
        args, extender, slo_engine
    )
    # flight recorder (--flightRecorder=on): verb arrivals only — GAS
    # has no telemetry cache, so no decile/control events here
    common.build_flight_recorder(args, extender)
    # solve observatory (--solveObs=on): GAS has no telemetry mirror, so
    # no churn passes — the device binpack solves still attribute stages
    common.build_solve_observatory(args, extender)

    from platform_aware_scheduling_tpu.cmd.tas import build_server
    from platform_aware_scheduling_tpu.utils.duration import parse_duration
    from platform_aware_scheduling_tpu.utils.gctuning import tune_for_serving

    tune_for_serving()
    server = build_server(
        extender,
        serving=args.serving,
        window_s=parse_duration(args.batchWindow),
        max_batch=args.batchMax,
        max_queue_depth=args.queueDepth,
    )
    if budget_controller is not None and hasattr(server, "dispatcher"):
        budget_controller.attach_admission(server.dispatcher)
    done = threading.Event()
    failed = []

    def serve():
        try:
            server.start_server(
                port=args.port,
                cert_file=args.cert,
                key_file=args.key,
                ca_file=args.cacert,
                unsafe=args.unsafe,
                block=True,
            )
        except Exception as exc:
            klog.error("extender server failed: %s", exc)
            failed.append(exc)
            done.set()

    threading.Thread(target=serve, daemon=True).start()

    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    watch_stop.set()
    extender.cache.stop()
    server.shutdown()
    klog.v(1).info_s("Exiting", component="extender")
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())

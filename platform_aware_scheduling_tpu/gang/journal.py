"""Crash-safe gang reservation journal over a ConfigMap-style kube
object (docs/gang.md "Crash-safe reservations").

A gang reservation lives in GangTracker memory — one extender restart
used to orphan every in-flight slice: half-bound gangs lost their hold,
already-bound members sat on nodes the re-formed gang might not
re-reserve, and a re-reservation elsewhere could admit a gang straddling
two slices.  The journal closes that hole:

  * **Write-behind.**  In-memory state stays the source of truth; after
    any durable mutation commits (reserve, expiry, release, bind) the
    tracker flushes a full snapshot here (group.py's dirty-generation
    counter coalesces bursts).  TTL refreshes are NOT durable — recovery
    re-arms a fresh TTL — so the cache-hit steady state writes nothing.
  * **Breaker-gated.**  A journal write is a kube write; while the kube
    circuit is not closed the write is skipped outright
    (``pas_gang_journal_skipped_total{reason="circuit_open"}``) and the
    tracker degrades to in-memory-only — scheduling availability is
    never hostage to journal durability.  Failed writes are likewise
    counted and dropped; the next durable mutation retries naturally.
  * **Reconciled recovery.**  ``GangTracker.recover()`` loads the
    snapshot at assembly and replays it AGAINST LIVE PODS: binds whose
    pod is gone, not running, or sitting on a node outside the journaled
    slice invalidate their entry, and a contradicted entry is DISCARDED
    (``pas_gang_journal_discarded_total``) rather than replayed — a
    stale journal can never admit a gang straddling two slices.

The backend speaks the ``get/create/update_configmap`` verb trio
(kube/client.py, the fake in testing/fake_kube.py), with optimistic
concurrency handled here: a conflicting update re-reads once and
re-applies — last snapshot wins, which is correct because snapshots are
full-state (no read-modify-write merge to lose).
"""

from __future__ import annotations

import json
from typing import Dict, Optional

from platform_aware_scheduling_tpu.kube.client import (
    ConflictError,
    NotFoundError,
)
from platform_aware_scheduling_tpu.utils import klog, trace
from platform_aware_scheduling_tpu.utils.tracing import CounterSet

DEFAULT_JOURNAL_NAME = "pas-gang-journal"
DEFAULT_JOURNAL_NAMESPACE = "default"

#: snapshot schema version; a journal written by a different schema is
#: ignored at load (recovery fails safe to an empty ledger)
SCHEMA_VERSION = 1


class GangJournal:
    """One ConfigMap holding the tracker's full reservation snapshot."""

    def __init__(
        self,
        kube_client,
        name: str = DEFAULT_JOURNAL_NAME,
        namespace: str = DEFAULT_JOURNAL_NAMESPACE,
        breakers=None,
        counters: Optional[CounterSet] = None,
    ):
        self.kube_client = kube_client
        self.name = name
        self.namespace = namespace
        # CircuitBreakerRegistry (kube/retry.py) or None: the gate that
        # turns journal writes off while the kube API is failing fast
        self.breakers = breakers
        self.counters = counters if counters is not None else trace.COUNTERS
        # last committed resourceVersion: the steady-state save is ONE
        # single-attempt PUT (no read) — a retrying GET on the verb path
        # would block Filter for the whole read-retry deadline while the
        # API struggles, before the breaker even opens
        self._last_rv: Optional[str] = None

    # -- gating ----------------------------------------------------------------

    def _kube_circuit_closed(self) -> bool:
        if self.breakers is None:
            return True
        from platform_aware_scheduling_tpu.kube.retry import (
            GROUP_KUBE,
            STATE_CLOSED as CLOSED,
        )

        return self.breakers.states().get(GROUP_KUBE, CLOSED) == CLOSED

    def _skip(self, reason: str) -> None:
        self.counters.inc(
            "pas_gang_journal_skipped_total", labels={"reason": reason}
        )

    # -- persistence -----------------------------------------------------------

    def _body(self, snapshot: Dict) -> Dict:
        return {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": self.name, "namespace": self.namespace},
            "data": {
                "state": json.dumps(
                    {"version": SCHEMA_VERSION, **snapshot}
                )
            },
        }

    def save(self, snapshot: Dict) -> bool:
        """Persist one full-state snapshot; True on commit.  Skipped
        (False) while the kube circuit is open; any write error is
        counted and swallowed — the journal must never wedge a verb."""
        if not self._kube_circuit_closed():
            self._skip("circuit_open")
            klog.v(2).info_s(
                "gang journal write skipped: kube circuit open "
                "(in-memory-only until it closes)",
                component="gang",
            )
            return False
        body = self._body(snapshot)
        try:
            committed = self._write(body)
        except Exception as exc:
            self._skip("error")
            klog.error("gang journal write failed: %s", exc)
            return False
        self._last_rv = committed["metadata"]["resourceVersion"]
        self.counters.inc("pas_gang_journal_writes_total")
        return True

    def _write(self, body: Dict) -> Dict:
        """Commit one snapshot: a single PUT under the cached RV in the
        steady state; 404/409/no-RV fall back to a read-then-write
        round (first write, journal deleted, or a concurrent writer —
        snapshots are full-state, so last wins)."""
        if self._last_rv is not None:
            attempt = dict(body, metadata=dict(body["metadata"]))
            attempt["metadata"]["resourceVersion"] = self._last_rv
            try:
                return self.kube_client.update_configmap(attempt)
            except (ConflictError, NotFoundError):
                pass  # RV stale or object gone: learn the truth below
        try:
            current = self.kube_client.get_configmap(self.namespace, self.name)
        except NotFoundError:
            return self.kube_client.create_configmap(body)
        body = dict(body, metadata=dict(body["metadata"]))
        body["metadata"]["resourceVersion"] = current["metadata"][
            "resourceVersion"
        ]
        return self.kube_client.update_configmap(body)

    def load(self) -> Optional[Dict]:
        """The last committed snapshot, or None (missing journal, parse
        trouble, schema mismatch, API failure — recovery fails safe to
        an empty ledger either way)."""
        try:
            obj = self.kube_client.get_configmap(self.namespace, self.name)
        except NotFoundError:
            return None
        except Exception as exc:
            klog.error("gang journal load failed: %s", exc)
            return None
        try:
            state = json.loads((obj.get("data") or {}).get("state") or "")
        except (ValueError, TypeError):
            klog.error("gang journal unparseable; ignoring")
            return None
        if state.get("version") != SCHEMA_VERSION:
            klog.error(
                "gang journal schema %r != %r; ignoring",
                state.get("version"),
                SCHEMA_VERSION,
            )
            return None
        return state

"""Gang & topology-aware scheduling: atomic multi-host TPU slice
placement with TTL reservations (docs/gang.md)."""

from platform_aware_scheduling_tpu.gang.group import (  # noqa: F401
    GangSpec,
    GangTracker,
    STATE_BOUND,
    STATE_FORMING,
    STATE_RELEASED,
    STATE_RESERVED,
)
from platform_aware_scheduling_tpu.gang.journal import (  # noqa: F401
    GangJournal,
)

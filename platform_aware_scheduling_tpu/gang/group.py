"""All-or-nothing gang co-scheduling with topology-constrained
reservations (docs/gang.md).

The stock Filter/Prioritize path admits pods one at a time — the
node-level version of the "sum fits but no single unit does" problem
PAPER.md's GAS solves per card.  Two multi-host jobs that each need a
contiguous ICI sub-slice of a shared mesh then deadlock half-placed:
each holds scattered nodes the other needs, and neither ever completes
a valid topology.

The :class:`GangTracker` makes co-scheduling atomic:

  * a pod carrying ``pas-workload-group`` + ``pas-gang-size`` (and
    optionally ``pas-gang-topology: "HxW"``) labels is a **gang
    member** (utils/labels.py);
  * the FIRST member's Filter runs the topology-feasibility kernel
    (ops/topology.py) over the mesh's free cells and — all-or-nothing —
    either **reserves a whole feasible slice** (best anchor = fewest
    stranded free neighbors) or fails every candidate with a concrete
    ``gang ...: no feasible HxW slice`` reason;
  * while the reservation holds, members pass Filter ONLY on reserved
    nodes, other gangs' pods fail reserved nodes with
    ``gang: node reserved by gang ...``, and each member Filter
    refreshes the reservation TTL;
  * Bind observations promote members to bound; when every member has
    bound the gang is **admitted** (``pas_gang_admitted_total``, time
    to full gang recorded);
  * a reservation whose TTL lapses before the gang fully binds is
    **reclaimed** (``pas_gang_reservation_expirations_total``) and the
    gang re-forms — so an abandoned half-gang can never pin mesh nodes
    forever, and no member of an incomplete gang binds after expiry.

Lifecycle: ``forming -> reserved -> bound -> released``, with a
``draining`` detour for preemption victims (admission/preempt.py): an
evicted-whole gang keeps holding its slice while its pods terminate so
the preemptor's overlapping reservation (``reserve_slice``) is never
observably free to third parties.  All state
transitions happen under one short lock; the feasibility solve runs on
device (host mirror as fallback/control — byte-identical wire behavior,
pinned by tests/test_gang.py).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from platform_aware_scheduling_tpu.extender.types import HostPriority
from platform_aware_scheduling_tpu.kube.objects import Pod
from platform_aware_scheduling_tpu.ops import topology
from platform_aware_scheduling_tpu.utils import decisions, klog, trace
from platform_aware_scheduling_tpu.utils import labels as shared_labels
from platform_aware_scheduling_tpu.utils.tracing import (
    LatencyRecorder,
    histograms_text,
)

STATE_FORMING = "forming"
STATE_RESERVED = "reserved"
STATE_BOUND = "bound"
#: a preempted victim: its whole-gang eviction has been issued and its
#: pods are terminating.  The gang KEEPS holding its slice (no third pod
#: may slip into the hole) while the preemptor's overlapping reservation
#: is already in place (reserve_slice) — reservation-while-draining.
#: The dead-gang sweep releases it once every member is gone; a wedged
#: drain is idle-dropped like an abandoned forming gang.
STATE_DRAINING = "draining"
STATE_RELEASED = "released"

DEFAULT_TTL_S = 30.0
DEFAULT_MESH_MAX_AGE_S = 30.0

#: process-wide time-to-full-gang histogram (its own family —
#: pas_gang_time_to_full_seconds, label: topology), registered once into
#: the shared /metrics page via trace.EXTRA_PROVIDERS
FULL_GANG_LATENCY = LatencyRecorder()


def _gang_histogram_text() -> str:
    return histograms_text(
        [FULL_GANG_LATENCY],
        metric="pas_gang_time_to_full_seconds",
        help_texts=trace.help_texts(),
        label_name="topology",
    )


trace.EXTRA_PROVIDERS.append(_gang_histogram_text)


class GangSpec:
    """A pod's parsed gang demand."""

    __slots__ = ("gang_id", "size", "topology")

    def __init__(self, gang_id: str, size: int, topo: Optional[tuple]):
        self.gang_id = gang_id
        self.size = size
        self.topology = topo  # (rows, cols) or None (any k nodes)

    @property
    def topology_label(self) -> str:
        if self.topology is None:
            return "any"
        return f"{self.topology[0]}x{self.topology[1]}"

    @classmethod
    def from_pod(cls, pod: Pod) -> Optional["GangSpec"]:
        """None unless the pod carries a well-formed gang demand.  The
        validation lives in ONE place — utils/labels.gang_id_for (group
        + size labels, size >= 1, topology cell count == size) — so the
        scheduler and the gang-aware rebalance actuator can never
        disagree about membership.  A malformed demand fails open to
        non-gang semantics (logged) — a typo must not wedge scheduling."""
        pod_labels = pod.get_labels()
        gang_id = shared_labels.gang_id_for(pod.namespace, pod_labels)
        if gang_id is None:
            if (
                pod_labels.get(shared_labels.GROUP_LABEL)
                and shared_labels.GANG_SIZE_LABEL in pod_labels
            ):
                klog.v(2).info_s(
                    f"malformed gang labels on pod {pod.namespace}/"
                    f"{pod.name}; treating pod as non-gang",
                    component="gang",
                )
            return None
        size = int(pod_labels[shared_labels.GANG_SIZE_LABEL])
        topo = None
        raw_topo = pod_labels.get(shared_labels.GANG_TOPOLOGY_LABEL)
        if raw_topo:
            topo = shared_labels.parse_topology(raw_topo)
        return cls(gang_id, size, topo)


class _Gang:
    """One tracked gang's mutable state (all access under the tracker's
    lock)."""

    __slots__ = (
        "gang_id",
        "spec",
        "state",
        "members",
        "bound",
        "reserved_nodes",
        "anchor",
        "created_at",
        "last_seen",
        "expires_at",
    )

    def __init__(self, spec: GangSpec, now: float):
        self.gang_id = spec.gang_id
        self.spec = spec
        self.state = STATE_FORMING
        self.members: Set[str] = set()  # pod keys seen at Filter time
        self.bound: Dict[str, str] = {}  # pod key -> node
        self.reserved_nodes: List[str] = []  # row-major slice order
        self.anchor: Optional[Tuple[int, int, int, int]] = None  # i, j, h, w
        self.created_at = now
        self.last_seen = now
        self.expires_at: Optional[float] = None

    def to_dict(self, now: float) -> Dict:
        out = {
            "gang": self.gang_id,
            "state": self.state,
            "size": self.spec.size,
            "topology": self.spec.topology_label,
            "members_seen": len(self.members),
            "bound": len(self.bound),
            "reserved_nodes": list(self.reserved_nodes),
        }
        if self.anchor is not None:
            i, j, h, w = self.anchor
            out["anchor"] = {"row": i, "col": j, "rows": h, "cols": w}
        if self.state == STATE_RESERVED and self.expires_at is not None:
            out["ttl_remaining_s"] = round(max(0.0, self.expires_at - now), 3)
        return out


class GangTracker:
    """The gang ledger the TAS verbs consult: reservations, member
    lifecycle, and the Filter/Prioritize overlays.

    ``nodes_provider`` supplies the cluster node list (kube
    ``list_nodes`` in production, the fake in tests) from which the mesh
    coordinate map is built and refreshed (``mesh_max_age_s``);
    ``clock`` is injectable so TTL behavior tests advance time instead
    of sleeping."""

    def __init__(
        self,
        nodes_provider: Callable[[], list],
        ttl_s: float = DEFAULT_TTL_S,
        mesh_max_age_s: float = DEFAULT_MESH_MAX_AGE_S,
        use_device: bool = True,
        clock: Callable[[], float] = time.monotonic,
        pods_provider: Optional[Callable[[], list]] = None,
    ):
        self.nodes_provider = nodes_provider
        # optional live-pod source (kube list_pods): bound gangs whose
        # members have ALL disappeared (job finished, pods deleted) are
        # released by the periodic dead-gang sweep, so a completed job's
        # slice cannot stay reserved until process restart
        self.pods_provider = pods_provider
        self.ttl_s = float(ttl_s)
        self.mesh_max_age_s = float(mesh_max_age_s)
        self.use_device = use_device
        self._clock = clock
        self._lock = threading.Lock()
        self._gangs: Dict[str, _Gang] = {}
        self._member_gang: Dict[str, str] = {}  # pod key -> gang id
        # bumped whenever the set of gang-held nodes can have changed
        # (reserve, TTL expiry, release/drop of a holding gang): the
        # Filter response cache keys non-gang entries on this, so a
        # cached verdict can never outlive the reservation state it
        # encoded (docs/gang.md)
        self._reservation_version = 0
        self._mesh: Optional[topology.MeshView] = None
        self._mesh_at: float = -float("inf")
        self._swept_at: float = -float("inf")
        self._sweeping = False
        # optional kube.lease.LeaseElector: the dead-gang sweep is a
        # singleton loop — cluster-wide pod LISTs from every replica
        # would multiply API load for an action only one replica's
        # release should perform (docs/robustness.md "HA & leader
        # election").  Verb overlays are NOT gated: every replica serves
        # Filter/Prioritize against its own reservation ledger.
        self.leadership = None
        # optional gang.journal.GangJournal: reservation/bind state is
        # journaled write-behind after every mutation (and recovered by
        # recover() at assembly) so a restart cannot lose live slices —
        # docs/gang.md "Crash-safe reservations"
        self.journal = None
        self._journal_gen = 0  # bumped under the lock on durable changes
        self._journal_saved_gen = 0
        # serializes flushes: two verbs flushing concurrently could
        # otherwise land an OLDER snapshot after a newer one while the
        # generation math marks the state clean
        self._journal_write_lock = threading.Lock()

    # -- mesh ------------------------------------------------------------------

    def _mesh_view(self, now: float) -> Optional[topology.MeshView]:
        """The (cached) coordinate map; a provider failure keeps serving
        the stale mesh rather than wedging the verb (same last-known-good
        stance as the telemetry cache)."""
        with self._lock:
            mesh = self._mesh
            fresh = (now - self._mesh_at) <= self.mesh_max_age_s
        if mesh is not None and fresh:
            return mesh
        try:
            nodes = self.nodes_provider()
        except Exception as exc:
            klog.error("gang mesh refresh failed: %s", exc)
            return mesh
        new = topology.MeshView(nodes)
        with self._lock:
            self._mesh = new
            self._mesh_at = now
        return new

    def _sweep_dead_gangs(self, now: float, wait: bool = False) -> None:
        """Release bound gangs whose members have ALL stopped running
        (job finished / pods deleted) — at most one pod list per
        ``mesh_max_age_s``.  Without this, a completed job's slice would
        stay reserved forever (the actuator's whole-gang release covers
        evictions, not completions).

        The cluster pod LIST never runs on a verb's thread: a Filter
        that trips the interval hands the scan to a one-shot daemon
        thread (``wait=False``); :meth:`prune` runs it inline
        (``wait=True``) so tests and maintenance calls are
        deterministic."""
        if self.pods_provider is None:
            return
        if self.leadership is not None and not self.leadership.is_leader():
            # singleton loop: only the leader scans the cluster and
            # releases dead gangs (module attr doc); _swept_at is left
            # alone so a freshly-promoted leader sweeps immediately
            return
        with self._lock:
            if self._sweeping or (now - self._swept_at) <= (
                self.mesh_max_age_s
            ):
                return
            self._swept_at = now
            bound_gangs = {
                gang.gang_id: set(gang.bound)
                for gang in self._gangs.values()
                # draining victims release here too: once every evicted
                # member is gone the slice belongs to the preemptor alone
                if gang.state in (STATE_BOUND, STATE_DRAINING)
            }
            if not bound_gangs:
                return
            self._sweeping = True

        def scan() -> None:
            try:
                pods = self.pods_provider()
                # a pod that Succeeded/Failed or is terminating no longer
                # RUNS on its slice — counting it as live would hold a
                # completed Job's reservation until its pods are GCed
                # (same liveness rule as the actuator's group floor)
                live = {
                    f"{pod.namespace}/{pod.name}"
                    for pod in pods
                    if pod.phase not in ("Succeeded", "Failed")
                    and pod.deletion_timestamp is None
                }
                for gang_id, members in bound_gangs.items():
                    if members and not (members & live):
                        klog.v(1).info_s(
                            f"gang {gang_id}: every bound member gone; "
                            f"releasing its slice",
                            component="gang",
                        )
                        self.release(gang_id)
            except Exception as exc:
                klog.error("gang dead-sweep pod list failed: %s", exc)
            finally:
                with self._lock:
                    self._sweeping = False

        if wait:
            scan()
        else:
            threading.Thread(target=scan, daemon=True).start()

    # -- reservation bookkeeping (all under the lock) --------------------------

    def _reserved_map_locked(
        self, exclude: Optional[str] = None
    ) -> Dict[str, str]:
        """{node: holding gang id} across every live reservation
        (bound gangs keep holding their slice until released)."""
        held: Dict[str, str] = {}
        for gang in self._gangs.values():
            if gang.gang_id == exclude:
                continue
            # draining victims still hold: their pods are terminating on
            # the slice and the overlapping preemptor reservation relies
            # on nobody else slipping in (reservation-while-draining)
            if gang.state in (STATE_RESERVED, STATE_BOUND, STATE_DRAINING):
                for node in gang.reserved_nodes:
                    held[node] = gang.gang_id
        return held

    def _prune_locked(self, now: float) -> int:
        """Reclaim expired reservations (gang re-forms) and drop gangs
        abandoned in forming for 10x the TTL.  Returns the number of
        expirations (counted by the caller outside the lock)."""
        expired = 0
        for gang in self._gangs.values():
            if (
                gang.state == STATE_RESERVED
                and gang.expires_at is not None
                and gang.expires_at <= now
            ):
                gang.state = STATE_FORMING
                gang.reserved_nodes = []
                gang.anchor = None
                gang.expires_at = None
                # binds on the abandoned slice do not carry over: the
                # re-formed gang may reserve a DIFFERENT slice, and
                # admission must mean k binds on the CURRENT one — never
                # a gang straddling two slices
                gang.bound = {}
                expired += 1
        if expired:
            self._reservation_version += 1
            self._journal_gen += 1
        idle_bound = 10.0 * self.ttl_s
        for gang_id in [
            gid
            for gid, gang in self._gangs.items()
            # a DRAINING victim whose pods never finish terminating must
            # not pin its slice forever either — same idle bound as an
            # abandoned forming gang (the sweep handles the normal case)
            if gang.state in (STATE_FORMING, STATE_DRAINING)
            and (now - gang.last_seen) > idle_bound
        ]:
            self._drop_locked(gang_id)
        return expired

    def _drop_locked(self, gang_id: str) -> None:
        dropped = self._gangs.pop(gang_id, None)
        if dropped is not None:
            if dropped.reserved_nodes:
                self._reservation_version += 1  # its slice is free again
                self._journal_gen += 1
            # released = removed from tracking; the terminal state is
            # stamped on the object so any held reference reads true
            dropped.state = STATE_RELEASED
            dropped.reserved_nodes = []
        for key in [
            k for k, gid in self._member_gang.items() if gid == gang_id
        ]:
            del self._member_gang[key]

    def _publish_gauges_locked(self) -> Tuple[float, float]:
        active = sum(
            1
            for gang in self._gangs.values()
            if gang.state in (STATE_FORMING, STATE_RESERVED)
        )
        held = sum(
            len(gang.reserved_nodes)
            for gang in self._gangs.values()
            if gang.state in (STATE_RESERVED, STATE_BOUND, STATE_DRAINING)
        )
        return float(active), float(held)

    def _set_gauges(self, gauges: Tuple[float, float]) -> None:
        trace.COUNTERS.set_gauge("pas_gang_active", gauges[0])
        trace.COUNTERS.set_gauge("pas_gang_reserved_nodes", gauges[1])

    # -- reservation solve -----------------------------------------------------

    def _try_reserve_locked(
        self,
        gang: _Gang,
        candidates: List[str],
        mesh: Optional[topology.MeshView],
        now: float,
    ) -> Optional[str]:
        """Attempt the all-or-nothing reservation for a forming gang over
        this request's candidates.  Returns None on success (the gang
        holds a slice) or the bounded rejection-reason label."""
        # gang.bound is always empty here: both paths into FORMING (new
        # gang, TTL expiry) clear it — abandoned-slice binds never leak
        # into a new solve (the straddling fix)
        held = self._reserved_map_locked(exclude=gang.gang_id)
        free = [name for name in candidates if name not in held]
        spec = gang.spec
        if spec.topology is None:
            # size-only gang: any k nodes, chosen in sorted-name order
            # for determinism (no adjacency constraint, no mesh needed)
            chosen = sorted(set(free))[: spec.size]
            if len(chosen) < spec.size:
                return "infeasible"
            gang.reserved_nodes = chosen
            gang.anchor = None
        else:
            if mesh is None or len(mesh) == 0:
                return "no_mesh"
            free_mask = mesh.free_mask(free)
            h, w = spec.topology
            best = None  # (score, orientation index, i, j, h, w)
            for idx, (hh, ww) in enumerate(
                [(h, w)] if h == w else [(h, w), (w, h)]
            ):
                feas = topology.topology_feasibility(
                    free_mask, hh, ww, use_device=self.use_device
                )
                anchor = topology.best_anchor(feas)
                if anchor is None:
                    continue
                i, j, score = anchor
                key = (score, idx, i, j)
                if best is None or key < best[0]:
                    best = (key, i, j, hh, ww)
            if best is None:
                return "infeasible"
            _, i, j, hh, ww = best
            names = mesh.names_for(topology.slice_cells(i, j, hh, ww))
            if names is None:  # a hole raced into the window
                return "infeasible"
            gang.reserved_nodes = names
            gang.anchor = (i, j, hh, ww)
        gang.state = STATE_RESERVED
        gang.expires_at = now + self.ttl_s
        self._reservation_version += 1
        self._journal_gen += 1
        return None

    # -- verb overlays ---------------------------------------------------------

    def filter_overlay(
        self, pod: Pod, candidates: List[str]
    ) -> Tuple[Dict[str, str], Dict[str, int]]:
        """The gang verdict for one Filter request: ``(failed, codes)``
        merged over the telemetry violation map by the caller
        (tas/telemetryscheduler._filter_nodes).

        Non-gang pod: candidates held by gang reservations fail with a
        concrete ``gang: node reserved by gang <id>`` reason
        (CODE_GANG_RESERVED).  Gang member: only the gang's reserved
        slice passes; with no reservable slice EVERY candidate fails
        (CODE_GANG_INFEASIBLE) — the all-or-nothing invariant."""
        now = self._clock()
        spec = GangSpec.from_pod(pod)
        self._sweep_dead_gangs(now)
        mesh = None
        if spec is not None and spec.topology is not None:
            mesh = self._mesh_view(now)
        expired = 0
        reservations_created = 0
        rejected_reason = None
        failed: Dict[str, str] = {}
        codes: Dict[str, int] = {}
        with self._lock:
            expired = self._prune_locked(now)
            if spec is None:
                held = self._reserved_map_locked()
                for name in candidates:
                    holder = held.get(name)
                    if holder is not None:
                        failed[name] = shared_labels.gang_reserved_reason(holder)
                        codes[name] = decisions.CODE_GANG_RESERVED
                gauges = self._publish_gauges_locked()
            else:
                gang = self._gangs.get(spec.gang_id)
                if gang is None:
                    gang = _Gang(spec, now)
                    self._gangs[spec.gang_id] = gang
                gang.last_seen = now
                gang.members.add(f"{pod.namespace}/{pod.name}")
                self._member_gang[f"{pod.namespace}/{pod.name}"] = (
                    spec.gang_id
                )
                if gang.state == STATE_FORMING:
                    rejected_reason = self._try_reserve_locked(
                        gang, candidates, mesh, now
                    )
                    if rejected_reason is None:
                        reservations_created = 1
                if gang.state in (STATE_RESERVED, STATE_BOUND):
                    if gang.state == STATE_RESERVED:
                        # an actively scheduling gang keeps its hold
                        gang.expires_at = now + self.ttl_s
                    allowed = set(gang.reserved_nodes)
                    held = self._reserved_map_locked(exclude=spec.gang_id)
                    topo = spec.topology_label
                    for name in candidates:
                        if name in allowed:
                            continue
                        holder = held.get(name)
                        if holder is not None:
                            failed[name] = (
                                shared_labels.gang_reserved_reason(holder)
                            )
                            codes[name] = decisions.CODE_GANG_RESERVED
                        else:
                            failed[name] = (
                                f"gang {spec.gang_id}: node outside "
                                f"reserved {topo} slice"
                            )
                            codes[name] = decisions.CODE_GANG_INFEASIBLE
                else:
                    reason = (
                        "no mesh coordinates available"
                        if rejected_reason == "no_mesh"
                        else f"no feasible {spec.topology_label} slice"
                    )
                    for name in candidates:
                        failed[name] = f"gang {spec.gang_id}: {reason}"
                        codes[name] = decisions.CODE_GANG_INFEASIBLE
                gauges = self._publish_gauges_locked()
        if expired:
            trace.COUNTERS.inc(
                "pas_gang_reservation_expirations_total", expired
            )
        if reservations_created:
            trace.COUNTERS.inc("pas_gang_reservations_total")
        if rejected_reason is not None:
            trace.COUNTERS.inc(
                "pas_gang_rejected_total", labels={"reason": rejected_reason}
            )
        self._set_gauges(gauges)
        self._journal_flush()  # no-op unless durable state moved
        return failed, codes

    def prioritize_overlay(
        self, pod: Pod, candidates: List[str]
    ) -> Optional[List[HostPriority]]:
        """Gang-member Prioritize: the reserved slice's nodes in
        row-major slice order (the topology kernel already chose the
        anchor stranding the fewest free neighbors), ordinal scores like
        the host path.  None for non-gang pods (the normal ranking
        serves); an unreservable gang gets an empty list — no node is a
        good home for a gang that cannot fully place."""
        spec = GangSpec.from_pod(pod)
        if spec is None:
            return None
        # Filter normally runs first and holds the reservation; this
        # degenerates to a lookup.  A Prioritize-first arrival drives the
        # same reservation path so the verbs cannot disagree.
        self.filter_overlay(pod, candidates)
        with self._lock:
            gang = self._gangs.get(spec.gang_id)
            reserved = (
                list(gang.reserved_nodes)
                if gang is not None
                and gang.state in (STATE_RESERVED, STATE_BOUND)
                else []
            )
        in_request = set(candidates)
        ordered = [name for name in reserved if name in in_request]
        return [
            HostPriority(host=name, score=10 - i)
            for i, name in enumerate(ordered)
        ]

    # -- outcome feedback ------------------------------------------------------

    def observe_bind(self, namespace: str, name: str, node: str) -> None:
        """A member landed: promote it within its gang; the gang is
        admitted when every member has bound onto the reserved slice."""
        key = f"{namespace}/{name}"
        admitted: Optional[_Gang] = None
        now = self._clock()
        with self._lock:
            gang_id = self._member_gang.get(key)
            if gang_id is None:
                return
            gang = self._gangs.get(gang_id)
            if gang is None or gang.state not in (
                STATE_RESERVED,
                STATE_BOUND,
            ):
                return
            if node not in gang.reserved_nodes:
                klog.v(2).info_s(
                    f"gang {gang_id}: member {key} bound OFF-slice to "
                    f"{node}",
                    component="gang",
                )
                return
            gang.bound[key] = node
            self._journal_gen += 1  # binds are durable: recovery replays them
            if (
                gang.state == STATE_RESERVED
                and len(gang.bound) >= gang.spec.size
            ):
                gang.state = STATE_BOUND
                gang.expires_at = None
                admitted = gang
            gauges = self._publish_gauges_locked()
        if admitted is not None:
            trace.COUNTERS.inc("pas_gang_admitted_total")
            FULL_GANG_LATENCY.observe(
                admitted.spec.topology_label, max(0.0, now - admitted.created_at)
            )
            klog.v(1).info_s(
                f"gang {admitted.gang_id} fully bound "
                f"({admitted.spec.size} pods, "
                f"{admitted.spec.topology_label})",
                component="gang",
            )
        self._set_gauges(gauges)
        self._journal_flush()

    def release(self, gang_id: str) -> bool:
        """Drop a gang and free its slice (job finished or evicted whole
        by the gang-aware actuator)."""
        with self._lock:
            existed = gang_id in self._gangs
            self._drop_locked(gang_id)
            gauges = self._publish_gauges_locked()
        self._set_gauges(gauges)
        self._journal_flush()
        return existed

    # -- preemption support (admission/preempt.py; docs/admission.md) ----------

    def mark_draining(self, gang_id: str) -> bool:
        """Flip a preemption victim to DRAINING after its whole-gang
        eviction was issued: the gang keeps holding its slice while its
        pods terminate (nobody else may slip into the hole), but the
        planner's census no longer offers it and its members re-enter
        scheduling as a fresh gang once the sweep releases it."""
        with self._lock:
            gang = self._gangs.get(gang_id)
            if gang is None or gang.state not in (
                STATE_RESERVED,
                STATE_BOUND,
            ):
                return False
            gang.state = STATE_DRAINING
            gang.expires_at = None
            gang.last_seen = self._clock()
            # held nodes did not change, but cached Filter verdicts may
            # encode this gang as schedulable-on — not true anymore
            self._reservation_version += 1
            self._journal_gen += 1
            gauges = self._publish_gauges_locked()
        self._set_gauges(gauges)
        self._journal_flush()
        return True

    def reserve_slice(
        self,
        pod: Pod,
        nodes: List[str],
        anchor: Optional[Tuple[int, int, int, int]] = None,
    ) -> bool:
        """Reservation-while-draining, the preemptor's half: hold the
        planned slice for ``pod``'s gang BEFORE the victims finish
        draining.  The preemptor's reservation may overlap DRAINING
        victims' holds — its own members pass Filter on the slice (the
        allowed-set check precedes the held map), every other pod keeps
        failing those nodes, and when the sweep releases the last victim
        the slice transfers without ever being observably free.  The
        normal TTL applies from now, so an abandoned preemption still
        expires instead of pinning the mesh."""
        spec = GangSpec.from_pod(pod)
        if spec is None or not nodes:
            return False
        now = self._clock()
        with self._lock:
            gang = self._gangs.get(spec.gang_id)
            if gang is None:
                gang = _Gang(spec, now)
                self._gangs[spec.gang_id] = gang
            if gang.state in (STATE_BOUND, STATE_DRAINING):
                return False  # already placed, or itself a victim
            key = f"{pod.namespace}/{pod.name}"
            gang.members.add(key)
            self._member_gang[key] = spec.gang_id
            gang.last_seen = now
            gang.state = STATE_RESERVED
            gang.reserved_nodes = list(nodes)
            gang.anchor = tuple(anchor) if anchor is not None else None
            gang.bound = {}
            gang.expires_at = now + self.ttl_s
            self._reservation_version += 1
            self._journal_gen += 1
            gauges = self._publish_gauges_locked()
        trace.COUNTERS.inc("pas_gang_reservations_total")
        self._set_gauges(gauges)
        self._journal_flush()
        return True

    def preemption_census(self) -> List[Dict]:
        """The victim-candidate view the preemption planner scores:
        every gang currently holding nodes and not already committed to
        a prior preemption (RESERVED or BOUND; DRAINING gangs are spoken
        for, FORMING gangs hold nothing worth taking)."""
        with self._lock:
            out = []
            for gang in self._gangs.values():
                if gang.state not in (STATE_RESERVED, STATE_BOUND):
                    continue
                out.append(
                    {
                        "gang": gang.gang_id,
                        "state": gang.state,
                        "size": gang.spec.size,
                        "nodes": list(gang.reserved_nodes),
                        "members": sorted(gang.members | set(gang.bound)),
                        "bound": dict(gang.bound),
                    }
                )
            return out

    def mesh(self) -> Optional[topology.MeshView]:
        """The (cached) mesh coordinate map, for the preemption
        planner's feasibility what-ifs."""
        return self._mesh_view(self._clock())

    # -- crash-safe journal (gang/journal.py; docs/gang.md) --------------------

    def _journal_snapshot_locked(self) -> Dict:
        """The full durable state: every RESERVED/BOUND gang's slice and
        binds.  Forming gangs hold nothing and are not journaled; TTL
        deadlines are not journaled either — recovery re-arms a fresh
        TTL so an abandoned reservation still expires on schedule."""
        gangs = []
        for gang in sorted(
            self._gangs.values(), key=lambda g: (g.created_at, g.gang_id)
        ):
            # DRAINING journals too (its slice is still held); recovery's
            # non-bound branch restores any non-BOUND state as RESERVED
            # with a fresh TTL, which is exactly the containment we want
            # after a crash mid-preemption
            if gang.state not in (
                STATE_RESERVED,
                STATE_BOUND,
                STATE_DRAINING,
            ):
                continue
            gangs.append(
                {
                    "gang": gang.gang_id,
                    "state": gang.state,
                    "size": gang.spec.size,
                    "topology": (
                        list(gang.spec.topology)
                        if gang.spec.topology is not None
                        else None
                    ),
                    "reserved_nodes": list(gang.reserved_nodes),
                    "anchor": (
                        list(gang.anchor) if gang.anchor is not None else None
                    ),
                    "bound": dict(gang.bound),
                    "members": sorted(gang.members),
                }
            )
        return {"gangs": gangs}

    def _journal_flush(self) -> None:
        """Write-behind: persist the snapshot iff durable state moved
        since the last committed write.  A failed/skipped write leaves
        the saved generation behind, so the NEXT durable mutation (or
        maintenance call) retries — in-memory-only degradation heals
        itself once the kube circuit closes."""
        journal = self.journal
        if journal is None:
            return
        # one flush at a time, and the snapshot is taken AFTER the write
        # lock is held — so whichever flush runs last always persists
        # the newest state (a concurrent mutation's own flush either
        # waits here or finds the generation already saved)
        with self._journal_write_lock:
            with self._lock:
                if self._journal_gen == self._journal_saved_gen:
                    return
                gen = self._journal_gen
                snapshot = self._journal_snapshot_locked()
            if journal.save(snapshot):
                with self._lock:
                    self._journal_saved_gen = max(
                        self._journal_saved_gen, gen
                    )

    def recover(self) -> int:
        """Restore journaled reservations at startup, reconciled against
        live pods; returns the number of gangs restored.

        Reconciliation is the safety half: a bind whose pod is gone is
        simply dropped (the slice stays reserved for the re-forming
        gang), but a bind CONTRADICTED by the live cluster — the pod
        runs on a different node, or on a node outside the journaled
        slice — discards the whole entry.  Replaying a contradicted
        reservation is exactly how a recovered extender would admit a
        gang straddling two slices; the journal is evidence, the
        cluster is truth."""
        journal = self.journal
        if journal is None:
            return 0
        data = journal.load()
        entries = (data or {}).get("gangs") or []
        if not entries:
            return 0
        if self.pods_provider is None:
            # no live view, no validation, no replay — same stance as a
            # failing pod list below: restoring unreconciled state is
            # the straddling hazard (docs/robustness.md recovery matrix)
            klog.error(
                "gang journal recovery: no pods_provider to reconcile "
                "against; discarding %d journaled gangs",
                len(entries),
            )
            trace.COUNTERS.inc(
                "pas_gang_journal_discarded_total", len(entries)
            )
            return 0
        live: Dict[str, str] = {}
        try:
            for pod in self.pods_provider():
                if (
                    pod.phase in ("Succeeded", "Failed")
                    or pod.deletion_timestamp is not None
                ):
                    continue
                live[f"{pod.namespace}/{pod.name}"] = (
                    pod.spec_node_name or ""
                )
        except Exception as exc:
            # no live view, no validation, no replay: restoring
            # unreconciled state is the straddling hazard
            klog.error(
                "gang journal recovery: cannot list pods (%s); "
                "discarding %d journaled gangs",
                exc,
                len(entries),
            )
            trace.COUNTERS.inc(
                "pas_gang_journal_discarded_total", len(entries)
            )
            return 0
        now = self._clock()
        restored = 0
        discarded = 0
        with self._lock:
            for entry in entries:
                gang_id = entry.get("gang")
                try:
                    size = int(entry.get("size"))
                    raw_topo = entry.get("topology")
                    topo = tuple(raw_topo) if raw_topo else None
                    reserved = [str(n) for n in entry.get("reserved_nodes")]
                except (TypeError, ValueError):
                    discarded += 1
                    continue
                if not gang_id or size < 1 or not reserved:
                    discarded += 1
                    continue
                if gang_id in self._gangs:
                    continue  # live state outranks the journal
                slice_set = set(reserved)
                members = set(entry.get("members") or []) | set(
                    entry.get("bound") or {}
                )
                # the cluster is truth: a recovered bind is a live member
                # RUNNING ON the journaled slice (even one whose bind
                # observation the crash swallowed); a gone-or-unbound
                # member just drops its bind; a live member bound OFF the
                # slice contradicts the whole entry
                contradicted = False
                bound: Dict[str, str] = {}
                for key in sorted(members):
                    node_now = live.get(key)
                    if not node_now:
                        continue  # pod gone, or never actually bound
                    if node_now not in slice_set:
                        contradicted = True
                        break
                    bound[key] = node_now
                if contradicted:
                    discarded += 1
                    klog.v(1).info_s(
                        f"gang {gang_id}: journal contradicted by live "
                        f"pods; discarding its reservation",
                        component="gang",
                    )
                    continue
                gang = _Gang(GangSpec(gang_id, size, topo), now)
                gang.reserved_nodes = reserved
                anchor = entry.get("anchor")
                gang.anchor = tuple(anchor) if anchor else None
                gang.bound = bound
                gang.members = members | set(bound)
                if entry.get("state") == STATE_BOUND and len(bound) >= size:
                    gang.state = STATE_BOUND
                    gang.expires_at = None
                else:
                    # fresh TTL: the recovered reservation holds exactly
                    # one grace window for the gang to resume binding
                    gang.state = STATE_RESERVED
                    gang.expires_at = now + self.ttl_s
                self._gangs[gang_id] = gang
                for key in gang.members:
                    self._member_gang[key] = gang_id
                restored += 1
            if restored:
                self._reservation_version += 1
            gauges = self._publish_gauges_locked()
        if restored:
            trace.COUNTERS.inc("pas_gang_journal_recovered_total", restored)
            klog.v(1).info_s(
                f"gang journal recovery: {restored} reservation(s) "
                f"restored, {discarded} discarded",
                component="gang",
            )
        if discarded:
            trace.COUNTERS.inc("pas_gang_journal_discarded_total", discarded)
        self._set_gauges(gauges)
        return restored

    # -- introspection ---------------------------------------------------------

    def cache_token(self) -> Tuple[int, Dict[str, str]]:
        """(reservation version, {node: holding gang id}) for the Filter
        response cache (tas/telemetryscheduler._gang_cache_token): every
        reservation change bumps the version, so a cached response keyed
        on it can never outlive the state it encoded.  Prunes expired
        reservations first — a cache-hit steady state must still observe
        TTL expiry (the expiry itself bumps the version and misses the
        stale entries)."""
        now = self._clock()
        self._sweep_dead_gangs(now)
        with self._lock:
            expired = self._prune_locked(now)
            version = self._reservation_version
            held = self._reserved_map_locked()  # built fresh already
            # gauges only when something actually expired — this runs on
            # every non-gang Filter request, and the common no-expiry
            # case must not pay two all-gang walks under the lock
            gauges = self._publish_gauges_locked() if expired else None
        if expired:
            trace.COUNTERS.inc(
                "pas_gang_reservation_expirations_total", expired
            )
            self._set_gauges(gauges)
            self._journal_flush()
        return version, held

    def reserved_nodes(self) -> Dict[str, str]:
        with self._lock:
            return self._reserved_map_locked()

    def gang_state(self, gang_id: str) -> Optional[str]:
        with self._lock:
            gang = self._gangs.get(gang_id)
            return gang.state if gang is not None else None

    def prune(self) -> int:
        now = self._clock()
        self._sweep_dead_gangs(now, wait=True)
        with self._lock:
            expired = self._prune_locked(now)
            gauges = self._publish_gauges_locked()
        if expired:
            trace.COUNTERS.inc(
                "pas_gang_reservation_expirations_total", expired
            )
        self._set_gauges(gauges)
        self._journal_flush()
        return expired

    def snapshot(self) -> Dict:
        now = self._clock()
        with self._lock:
            gangs = sorted(
                self._gangs.values(), key=lambda g: (g.created_at, g.gang_id)
            )
            out = {
                "enabled": True,
                "ttl_s": self.ttl_s,
                "mesh": {
                    "rows": self._mesh.rows if self._mesh else 0,
                    "cols": self._mesh.cols if self._mesh else 0,
                    "nodes": len(self._mesh) if self._mesh else 0,
                },
                "gangs": [gang.to_dict(now) for gang in gangs],
                "reserved_nodes": len(self._reserved_map_locked()),
            }
        return out

    def to_json(self) -> bytes:
        import json

        return json.dumps(self.snapshot()).encode() + b"\n"

"""Event-loop HTTP(S) front-end: asyncio transport over the existing wire
parity stack (docs/serving.md).

Drop-in alternative to the threaded ``extender.server.Server`` —
identical constructor-and-serve surface (``start_server`` / ``port`` /
``wait_ready`` / ``shutdown``), identical wire behavior:

  * framing comes from the SAME sans-IO head parser the threaded handler
    uses (``extender.server.parse_request_head``: strict Content-Length,
    Transfer-Encoding and duplicate-CL rejection, 64 KiB head cap, 1 GB
    body refusal, 100-continue, keep-alive + pipelining, 5 s read /
    10 s write timeouts);
  * routing/middleware IS ``extender.server.Server.route`` (exact
    content-type check, 405, 404 catch-all, /metrics, V(5) wire capture)
    — this class wraps an unstarted ``Server`` purely for routing;
  * mTLS uses the same pinned ``configure_secure_context``.

What changes is the concurrency model: connections are served by ONE
event loop (no thread per connection), and verb execution goes through
the micro-batching dispatcher — concurrent requests coalesce into one
fused device solve with responses demultiplexed per request
(serving/dispatcher.py, serving/batch.py).  The threaded server remains
the reference-parity default; this front-end is opt-in via
``--serving=async`` on the service mains.
"""

from __future__ import annotations

import asyncio
import socket
import threading
import time
from typing import Optional

from platform_aware_scheduling_tpu.extender.server import (
    HTTPRequest,
    HTTPResponse,
    EXECUTOR_DEBUG_PATHS,
    HeadParseError,
    MAX_HEAD_LENGTH,
    QUEUE_BYPASS_PATHS,
    READ_HEADER_TIMEOUT_S,
    Server,
    WRITE_TIMEOUT_S,
    configure_secure_context,
    parse_request_head,
    render_response,
    render_simple,
)
from platform_aware_scheduling_tpu.serving.batch import BatchExecutor
from platform_aware_scheduling_tpu.serving.dispatcher import (
    MicroBatchDispatcher,
)
from platform_aware_scheduling_tpu.utils import klog, trace
from platform_aware_scheduling_tpu.utils.tracing import (
    CounterSet,
    LatencyRecorder,
)

_RBUF = 1 << 16


class AsyncServer:
    """Asyncio front-end + micro-batched dispatch around a Scheduler."""

    def __init__(
        self,
        scheduler,
        metrics_provider=None,
        window_s: float = 0.001,
        max_batch: int = 64,
        max_queue_depth: int = 256,
        retry_after_s: float = 1.0,
    ):
        self.scheduler = scheduler
        # serving-stage observability, merged into the same /metrics
        # endpoint the extender's verb histograms use.  The scheduler's
        # own LatencyRecorder is shared when it has one so the whole
        # process emits ONE pas_request_duration_seconds family (a second
        # recorder would need a second # TYPE header — invalid exposition)
        scheduler_recorder = getattr(scheduler, "recorder", None)
        self.recorder = scheduler_recorder or LatencyRecorder()
        self.counters = CounterSet()
        # the admission-shed counter (pas_serving_rejected_total) lives
        # in THIS layer-local set — an SLO engine judging the scheduler's
        # verb availability must read it, or a saturated queue shedding
        # half the traffic would score compliance 1.0 (utils/slo.py; the
        # mains attach the engine before building the server)
        slo_engine = getattr(scheduler, "slo", None)
        if (
            slo_engine is not None
            and hasattr(slo_engine, "counter_sets")
            and self.counters not in slo_engine.counter_sets
        ):
            slo_engine.counter_sets.append(self.counters)
        trace.install_jax_hooks()

        if metrics_provider is not None:
            # legacy explicit provider: its text is prepended verbatim
            # (the caller owns exposition validity for that fragment).
            # When the recorder is privately owned (scheduler has none),
            # the serving-stage histograms must still be exposed here —
            # the provider's text cannot contain them
            _extra = metrics_provider
            own_recorders = [] if scheduler_recorder is not None else [
                self.recorder
            ]

            def provider() -> str:
                return _extra() + trace.exposition(
                    recorders=own_recorders, counter_sets=[self.counters]
                )

        else:

            def provider() -> str:
                # dynamic: the SLO engine may be wired after construction
                # (assembly order, tests) and its families must appear on
                # /metrics only while it is (utils/slo.py off-path rule)
                sets = [self.counters]
                slo_engine = getattr(self.scheduler, "slo", None)
                if slo_engine is not None:
                    sets.append(slo_engine.counters)
                controller = getattr(self.scheduler, "control", None)
                if controller is not None:
                    sets.append(controller.counters)
                flight = getattr(self.scheduler, "flight", None)
                if flight is not None:
                    sets.append(flight.counters)
                admission = getattr(self.scheduler, "admission", None)
                if admission is not None:
                    sets.append(admission.counters)
                shard = getattr(self.scheduler, "shard", None)
                if shard is not None:
                    sets.append(shard.counters)
                return trace.exposition(
                    recorders=[self.recorder], counter_sets=sets
                )

        # unstarted Server: routing + middleware + /metrics/health only
        self._router = Server(scheduler, metrics_provider=provider)
        # readiness gains the async-only condition: admission-queue
        # headroom.  A saturated queue answers /readyz 503 (with the
        # queue named in the reasons) while the endpoint itself stays
        # readable — it bypasses the very queue it reports on
        self._router.probe.register("admission_queue", self._queue_condition)
        self.batch = BatchExecutor(self._router)
        self.dispatcher = MicroBatchDispatcher(
            route=self._router.route,
            batch_route=self.batch,
            window_s=window_s,
            max_batch=max_batch,
            max_queue_depth=max_queue_depth,
            retry_after_s=retry_after_s,
            recorder=self.recorder,
            counters=self.counters,
        )
        self._ready = threading.Event()
        self._stop: Optional[asyncio.Event] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._port: Optional[int] = None
        self._startup_error: Optional[BaseException] = None

    @property
    def probe(self):
        """The /readyz ReadinessProbe (scheduler conditions + the
        admission-queue condition registered above)."""
        return self._router.probe

    def _queue_condition(self):
        depth = len(self.dispatcher._queue)
        limit = self.dispatcher.max_queue_depth
        if depth >= limit:
            return False, f"admission queue saturated ({depth}/{limit})"
        return True, f"depth {depth}/{limit}"

    # -- serving ---------------------------------------------------------------

    def start_server(
        self,
        port: str,
        cert_file: str = "",
        key_file: str = "",
        ca_file: str = "",
        unsafe: bool = False,
        host: str = "",
        block: bool = True,
    ) -> None:
        """Same contract as ``Server.start_server``: plain HTTP when
        ``unsafe``, pinned mTLS otherwise; ``block=False`` serves on a
        daemon thread (startup failures re-raise in the caller)."""
        ssl_context = None
        if not unsafe:
            ssl_context = configure_secure_context(cert_file, key_file, ca_file)
        if block:
            self._serve_loop(host, port, ssl_context, unsafe, reraise=True)
            return
        self._thread = threading.Thread(
            target=self._serve_loop,
            args=(host, port, ssl_context, unsafe, False),
            daemon=True,
        )
        self._thread.start()
        while not self._ready.wait(0.05):
            if not self._thread.is_alive():
                raise self._startup_error or RuntimeError(
                    "async server died during startup"
                )

    def _serve_loop(self, host, port, ssl_context, unsafe, reraise) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        try:
            loop.run_until_complete(
                self._main(host, port, ssl_context, unsafe)
            )
        except BaseException as exc:  # surfaced by start_server(block=False)
            self._startup_error = exc
            if reraise:
                raise
            klog.error("async extender server failed: %s", exc)
        finally:
            self._loop = None
            try:
                loop.close()
            except Exception:
                pass

    async def _main(self, host, port, ssl_context, unsafe) -> None:
        self._stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        self.dispatcher.start(loop)
        server = await asyncio.start_server(
            self._handle_conn,
            host or None,
            int(port),
            ssl=ssl_context,
        )
        self._port = server.sockets[0].getsockname()[1]
        scheme = "HTTP" if unsafe else "HTTPS"
        klog.v(2).info_s(
            f"Extender Listening on {scheme} {self._port} (async)",
            component="extender",
        )
        self._ready.set()
        try:
            async with server:
                await self._stop.wait()
        finally:
            await self.dispatcher.stop()
            # cancel lingering connection handlers so loop.close() is
            # quiet (keep-alive connections outlive the stop signal)
            tasks = [
                t
                for t in asyncio.all_tasks()
                if t is not asyncio.current_task()
            ]
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)

    # -- connection handling ---------------------------------------------------

    async def _handle_conn(self, reader, writer) -> None:
        sock = writer.get_extra_info("socket")
        if sock is not None:
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
        buf = bytearray()
        try:
            while True:
                # -- read the request head (same framing as the threaded
                #    handler; shared parse_request_head).  Span timing
                #    starts at the request's FIRST byte, not loop entry —
                #    keep-alive idle time belongs to no request ----------
                t_accept = time.perf_counter() if buf else None
                head_end = buf.find(b"\r\n\r\n")
                while head_end < 0:
                    if len(buf) > MAX_HEAD_LENGTH:
                        await self._send_simple(writer, 431)
                        return
                    chunk = await self._read(reader)
                    if not chunk:
                        return
                    if t_accept is None:
                        t_accept = time.perf_counter()
                    buf += chunk
                    head_end = buf.find(b"\r\n\r\n")
                if head_end > MAX_HEAD_LENGTH:
                    await self._send_simple(writer, 431)
                    return
                head = bytes(buf[:head_end])
                del buf[: head_end + 4]
                try:
                    method, path, version, headers, lowered, length = (
                        parse_request_head(head)
                    )
                except HeadParseError as exc:
                    await self._send_simple(writer, exc.status)
                    return
                if lowered.get("expect", "").lower() == "100-continue":
                    writer.write(b"HTTP/1.1 100 Continue\r\n\r\n")
                    try:
                        await writer.drain()
                    except (ConnectionError, OSError):
                        return
                # -- read the body ----------------------------------------
                while len(buf) < length:
                    chunk = await self._read(reader)
                    if not chunk:
                        return
                    buf += chunk
                body = bytes(buf[:length])
                del buf[:length]
                # -- dispatch through the micro-batcher + respond ---------
                request_id = (
                    lowered.get("x-request-id") or trace.new_request_id()
                )
                span = trace.Span(f"{method} {path}", request_id, t0=t_accept)
                span.add_stage("read", time.perf_counter() - t_accept)
                request = HTTPRequest(
                    method=method, path=path, headers=headers, body=body,
                    span=span,
                )
                bare_path = path.partition("?")[0]
                if bare_path in QUEUE_BYPASS_PATHS:
                    # observability endpoints bypass the admission queue:
                    # they must stay readable precisely when the queue is
                    # saturated (the condition they exist to diagnose),
                    # and they never touch the device.  The set derives
                    # from the DEBUG_ENDPOINTS index (extender/server.py)
                    # so a new debug route cannot silently queue here
                    try:
                        response = self._router.route(request)
                    except Exception as exc:
                        klog.error("handler raised: %r", exc)
                        response = HTTPResponse(status=500)
                elif bare_path in EXECUTOR_DEBUG_PATHS:
                    # also bypass the queue, but these BLOCK: the
                    # bounded profile capture sleeps for its window and
                    # a what-if runs a whole twin replay — run them
                    # off-loop so the event loop keeps serving meanwhile
                    try:
                        response = await asyncio.get_running_loop().run_in_executor(
                            None, self._router.route, request
                        )
                    except Exception as exc:
                        klog.error("handler raised: %r", exc)
                        response = HTTPResponse(status=500)
                else:
                    response = await self.dispatcher.submit(request)
                # every response carries the id — INCLUDING the 503
                # backpressure rejection the dispatcher answers directly
                response.headers.setdefault("X-Request-ID", request_id)
                close = (
                    version == "HTTP/1.0"
                    or lowered.get("connection", "").lower() == "close"
                )
                t_write = time.perf_counter()
                writer.write(render_response(response, close))
                try:
                    await asyncio.wait_for(writer.drain(), WRITE_TIMEOUT_S)
                except (asyncio.TimeoutError, ConnectionError, OSError):
                    span.set("error", "write failed")
                    return
                finally:
                    span.add_stage("write", time.perf_counter() - t_write)
                    trace.TRACES.add(span.finish(response.status))
                if close:
                    return
        finally:
            try:
                writer.close()
            except Exception:
                pass

    @staticmethod
    async def _read(reader) -> bytes:
        """One socket read under the head/body timeout; b'' = give up on
        the connection (EOF, timeout, reset) — as the threaded handler."""
        try:
            return await asyncio.wait_for(
                reader.read(_RBUF), READ_HEADER_TIMEOUT_S
            )
        except (asyncio.TimeoutError, ConnectionError, OSError):
            return b""

    @staticmethod
    async def _send_simple(writer, status: int) -> None:
        try:
            writer.write(
                render_simple(
                    status, close=True, request_id=trace.new_request_id()
                )
            )
            await writer.drain()
        except (ConnectionError, OSError):
            pass

    # -- control surface (Server parity) ---------------------------------------

    @property
    def port(self) -> int:
        assert self._port is not None
        return self._port

    def wait_ready(self, timeout: float = 10.0) -> bool:
        return self._ready.wait(timeout)

    def shutdown(self) -> None:
        loop = self._loop
        if loop is not None and self._stop is not None:
            try:
                loop.call_soon_threadsafe(self._stop.set)
            except RuntimeError:
                pass  # loop already closed
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._ready.clear()
        self._port = None

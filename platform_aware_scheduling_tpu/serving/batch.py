"""Batched route execution: fuse a coalesced batch's device work, then
demux per-request responses through the unchanged routing stack.

The dispatcher (serving/dispatcher.py) hands a batch of raw HTTP requests
to :class:`BatchExecutor`.  Requests that cannot batch — non-verb paths,
middleware rejections (wrong content type, oversize body, non-POST),
/metrics — are answered inline through ``Server.route`` exactly as the
threaded front-end would.  The Prioritize/Filter members are grouped per
path and each group is offered to the scheduler's optional ``warm_batch``
hook (MetricsExtender.warm_batch) which performs ONE fused device solve
covering every ranking/violation set the group needs; the members are
then served one by one through the same ``Server.route`` — now pure
cache hits — so responses are byte-identical to the per-request path by
construction (the encode path never changes, only cache warmth).

Schedulers without the hook (GAS) just get the serialized demux, which
already beats thread-per-connection at concurrency: one worker thread
instead of N racing the interpreter lock.
"""

from __future__ import annotations

from typing import List

from platform_aware_scheduling_tpu.extender.server import (
    HTTPRequest,
    HTTPResponse,
    MAX_CONTENT_LENGTH,
    Server,
)
from platform_aware_scheduling_tpu.utils import klog, trace

_BATCH_PATHS = ("/scheduler/prioritize", "/scheduler/filter")


class BatchExecutor:
    """``batch_route`` callable for MicroBatchDispatcher over a routing
    ``Server`` (used for its route table + middleware, never started)."""

    def __init__(self, router: Server):
        self.router = router
        # instrumentation (pinned by tests/test_serving.py): batches
        # executed and fused device solves performed across them
        self.batches = 0
        self.fused_solves = 0

    def _batchable(self, request: HTTPRequest) -> bool:
        """Only requests that will pass the middleware chain reach a verb
        handler; everything else is answered inline (its response never
        depends on cache warmth)."""
        return (
            request.path in _BATCH_PATHS
            and request.method == "POST"
            and request.header("Content-Type") == "application/json"
            and len(request.body) <= MAX_CONTENT_LENGTH
        )

    def __call__(
        self, requests: List[HTTPRequest]
    ) -> List[HTTPResponse]:
        self.batches += 1
        responses: List[HTTPResponse] = [None] * len(requests)  # type: ignore
        groups: dict = {}
        for i, request in enumerate(requests):
            if self._batchable(request):
                groups.setdefault(request.path, []).append(i)
            else:
                responses[i] = self._route_one(requests[i])
        warm = getattr(self.router.scheduler, "warm_batch", None)
        for path, idxs in groups.items():
            if warm is not None:
                try:
                    solves = int(warm(path, [requests[i] for i in idxs]))
                    self.fused_solves += solves
                    if solves:
                        trace.COUNTERS.inc(
                            "pas_serving_fused_solves_total", solves
                        )
                except Exception as exc:  # warmth is an optimization only
                    klog.error(
                        "batch warm failed, per-request path serves: %s", exc
                    )
            for i in idxs:
                responses[i] = self._route_one(requests[i])
        return responses

    def _route_one(self, request: HTTPRequest) -> HTTPResponse:
        try:
            return self.router.route(request)
        except Exception as exc:
            klog.error("handler raised: %r", exc)
            return HTTPResponse(status=500)

"""Concurrent serving subsystem: asyncio event-loop HTTP front-end with
micro-batched device dispatch (docs/serving.md).

Opt-in alternative to the threaded reference-parity server
(``--serving=async`` on the service mains): one event loop owns all
connections, concurrent Prioritize/Filter requests coalesce inside a
short window into ONE fused device solve, and responses — byte-identical
to the per-request path — are demultiplexed per request.  Bounded
admission with 503 + Retry-After backpressure; per-stage latency and
queue-depth metrics on /metrics.
"""

from platform_aware_scheduling_tpu.serving.batch import BatchExecutor
from platform_aware_scheduling_tpu.serving.dispatcher import (
    MicroBatchDispatcher,
)
from platform_aware_scheduling_tpu.serving.http import AsyncServer

__all__ = ["AsyncServer", "BatchExecutor", "MicroBatchDispatcher"]

"""Micro-batching dispatcher: the continuous-batching core of the async
serving path (docs/serving.md).

Requests submitted from the event loop land in a bounded admission queue;
a single batcher coroutine coalesces whatever arrives within a short
window (default 1 ms, tunable) into one batch and hands it to a
single-worker thread pool, where the batch route fuses the device work
(one batched solve per coalesced batch — serving/batch.py) and demuxes
per-request responses.  One worker thread means the Python-side encode
work of concurrent requests is SERIALIZED instead of racing N handler
threads into the interpreter lock — at c=8 this is the difference between
one device dispatch + 8 cheap encodes and 8 GIL-thrashing threads (the
round-5 verdict's 8-12x p99 inflation).

Backpressure: past ``max_queue_depth`` queued requests, new submissions
are rejected immediately with 503 + ``Retry-After`` (never queued, never
dropped silently); the queue draining restores admission with no other
recovery action needed.

Every stage records into utils/tracing.py primitives, exported on the
server's /metrics endpoint:

  * ``serving_queue_wait`` / ``serving_batch_solve`` / ``serving_total``
    latency histograms (LatencyRecorder);
  * ``pas_serving_queue_depth`` gauge, ``pas_serving_requests_total`` /
    ``pas_serving_batches_total`` / ``pas_serving_rejected_total`` /
    ``pas_serving_batch_fallback_total`` counters (CounterSet).
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional

from platform_aware_scheduling_tpu.extender.server import (
    HTTPRequest,
    HTTPResponse,
)
from platform_aware_scheduling_tpu.utils import events, klog, trace
from platform_aware_scheduling_tpu.utils.tracing import (
    CounterSet,
    LatencyRecorder,
)


class MicroBatchDispatcher:
    """Admission queue + coalescing window + single-worker batch solve."""

    def __init__(
        self,
        route: Callable[[HTTPRequest], HTTPResponse],
        batch_route: Optional[
            Callable[[List[HTTPRequest]], List[HTTPResponse]]
        ] = None,
        window_s: float = 0.001,
        max_batch: int = 64,
        max_queue_depth: int = 256,
        retry_after_s: float = 1.0,
        recorder: Optional[LatencyRecorder] = None,
        counters: Optional[CounterSet] = None,
    ):
        self.route = route
        self.batch_route = batch_route
        self.window_s = window_s
        self.max_batch = max(1, max_batch)
        self.max_queue_depth = max(1, max_queue_depth)
        self.retry_after_s = retry_after_s
        self.recorder = recorder if recorder is not None else LatencyRecorder()
        self.counters = counters if counters is not None else CounterSet()
        self._queue: deque = deque()  # (request, future, t_enqueue)
        self._wakeup: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        # ONE worker: batches execute serially by design (module doc)
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="serving-batch"
        )

    # -- lifecycle (event-loop thread only) -----------------------------------

    def start(self, loop: asyncio.AbstractEventLoop) -> None:
        self._wakeup = asyncio.Event()
        self._task = loop.create_task(self._run(loop))

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        for _, future, _ in self._queue:
            if not future.done():
                future.set_result(HTTPResponse(status=503))
        self._queue.clear()
        self._executor.shutdown(wait=False)

    # -- submission (event-loop thread only) ----------------------------------

    def submit(self, request: HTTPRequest) -> "asyncio.Future[HTTPResponse]":
        """Queue one request; resolves to its response.  A saturated queue
        answers 503 + Retry-After immediately (backpressure, module doc)."""
        loop = asyncio.get_running_loop()
        future: asyncio.Future = loop.create_future()
        self.counters.inc("pas_serving_requests_total")
        if len(self._queue) >= self.max_queue_depth:
            self.counters.inc("pas_serving_rejected_total")
            trace.of(request).set("rejected", True)
            events.JOURNAL.publish(
                "serving",
                "request shed",
                request_id=trace.of(request).trace_id,
                data={"path": request.path, "depth": len(self._queue)},
            )
            future.set_result(
                HTTPResponse(
                    status=503,
                    headers={
                        "Retry-After": str(
                            max(1, int(round(self.retry_after_s)))
                        )
                    },
                )
            )
            return future
        self._queue.append((request, future, time.perf_counter()))
        self.counters.set_gauge("pas_serving_queue_depth", len(self._queue))
        if self._wakeup is not None:
            self._wakeup.set()
        return future

    # -- the batcher loop ------------------------------------------------------

    async def _run(self, loop: asyncio.AbstractEventLoop) -> None:
        while True:
            while not self._queue:
                self._wakeup.clear()
                await self._wakeup.wait()
            t_wake = time.perf_counter()
            # coalescing window, deadline-based: the batch dispatches at
            # head-arrival + window_s, so stragglers landing within the
            # window of the FIRST request fuse with it (skipped when a
            # full batch is already waiting — no reason to add latency
            # then, and never over-slept when the batcher wakes late)
            if self.window_s > 0 and len(self._queue) < self.max_batch:
                remaining = self.window_s - (
                    time.perf_counter() - self._queue[0][2]
                )
                if remaining > 0:
                    await asyncio.sleep(remaining)
            batch = []
            while self._queue and len(batch) < self.max_batch:
                batch.append(self._queue.popleft())
            self.counters.set_gauge(
                "pas_serving_queue_depth", len(self._queue)
            )
            self.counters.inc("pas_serving_batches_total")
            self.counters.inc("pas_serving_batched_requests_total", len(batch))
            t_solve = time.perf_counter()
            # the BATCH span: links every member request span, records the
            # coalesce window + fused solve (the N:1 edge of the trace
            # graph — member spans carry their own queue_wait/coalesce)
            batch_span = trace.Span("serving_batch", t0=t_wake)
            batch_span.set("size", len(batch))
            batch_span.add_stage("coalesce", t_solve - t_wake)
            for request, _, t_enq in batch:
                span = trace.of(request)
                span.add_stage("queue_wait", max(0.0, t_wake - t_enq))
                span.add_stage(
                    "coalesce", max(0.0, t_solve - max(t_enq, t_wake))
                )
                if span is not trace.NULL_SPAN:
                    batch_span.link(span.trace_id)
                    span.set("batch_id", batch_span.trace_id)
                self.recorder.observe("serving_queue_wait", t_solve - t_enq)
            requests = [request for request, _, _ in batch]
            try:
                responses = await loop.run_in_executor(
                    self._executor, self._solve, requests
                )
            except Exception as exc:  # executor trouble: fail the batch loud
                klog.error("batch executor failed: %s", exc)
                responses = [HTTPResponse(status=500) for _ in batch]
            done = time.perf_counter()
            self.recorder.observe("serving_batch_solve", done - t_solve)
            batch_span.add_stage("batch_solve", done - t_solve)
            trace.TRACES.add(batch_span.finish())
            for (_, future, t_enq), response in zip(batch, responses):
                if not future.done():
                    future.set_result(response)
                self.recorder.observe("serving_total", done - t_enq)

    # -- batch execution (worker thread) ---------------------------------------

    def _solve(self, requests: List[HTTPRequest]) -> List[HTTPResponse]:
        if self.batch_route is not None:
            try:
                responses = self.batch_route(requests)
                if len(responses) == len(requests):
                    return responses
                klog.error(
                    "batch route returned %d responses for %d requests; "
                    "per-request fallback",
                    len(responses),
                    len(requests),
                )
            except Exception as exc:
                klog.error(
                    "batch route failed, per-request fallback: %s", exc
                )
            self.counters.inc("pas_serving_batch_fallback_total")
        out = []
        for request in requests:
            try:
                out.append(self.route(request))
            except Exception as exc:
                klog.error("handler raised: %r", exc)
                out.append(HTTPResponse(status=500))
        return out

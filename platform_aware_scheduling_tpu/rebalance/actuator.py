"""Safe eviction actuation: the only component that touches the cluster.

Every planned move passes four gates before the pods/eviction
subresource is called, in order:

  1. per-pod cooldown — a pod EVICTED recently is left alone, so a
     workload cannot be bounced every cycle (skipped moves do not start
     a cooldown: a pdb- or rate-blocked pod stays eligible and is simply
     re-gated next cycle);
  2. per-workload-group min-available — evicting must not drop the
     group's running count below the floor (the in-tree analogue of a
     PodDisruptionBudget, enforced BEFORE the API server gets a say);
  3. token-bucket rate limit — cluster-wide evictions per second with a
     small burst, so even a pathological plan drains slowly;
  4. mode — ``dry-run`` stops here (the move is recorded as skipped with
     reason ``dry_run``), ``active`` evicts.

A 409 from the API server (a real PodDisruptionBudget) is recorded as a
skipped move with reason ``pdb`` and never retried within the cycle.
Every outcome increments ``pas_rebalance_moves_{executed,skipped}_total``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from platform_aware_scheduling_tpu.kube.client import KubeError
from platform_aware_scheduling_tpu.kube.objects import Pod
from platform_aware_scheduling_tpu.rebalance.replan import Move
from platform_aware_scheduling_tpu.utils import klog, trace

MODE_OFF = "off"
MODE_DRY_RUN = "dry-run"
MODE_ACTIVE = "active"
MODES = (MODE_OFF, MODE_DRY_RUN, MODE_ACTIVE)

DEFAULT_RATE_PER_S = 0.5
DEFAULT_BURST = 3
DEFAULT_COOLDOWN_S = 300.0
DEFAULT_MIN_AVAILABLE = 1
GROUP_LABEL = "pas-workload-group"


class TokenBucket:
    """Classic token bucket; ``clock`` injectable for hermetic tests."""

    def __init__(
        self,
        rate_per_s: float = DEFAULT_RATE_PER_S,
        burst: int = DEFAULT_BURST,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.rate_per_s = float(rate_per_s)
        self.burst = max(1, int(burst))
        self._clock = clock
        self._tokens = float(self.burst)
        self._last = clock()
        self._lock = threading.Lock()

    def try_take(self) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(
                float(self.burst),
                self._tokens + (now - self._last) * self.rate_per_s,
            )
            self._last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False


@dataclass
class ActuationResult:
    executed: List[Move] = field(default_factory=list)
    skipped: Dict[str, List[Move]] = field(default_factory=dict)

    def skip(self, reason: str, move: Move) -> None:
        self.skipped.setdefault(reason, []).append(move)

    def skip_counts(self) -> Dict[str, int]:
        return {reason: len(moves) for reason, moves in self.skipped.items()}


def workload_group(pod: Pod) -> str:
    """The min-available accounting unit: the explicit group label, else
    the first ownerReference's name (ReplicaSet/Job/StatefulSet), else
    the pod's own name (a bare pod is its own group of one)."""
    label = pod.get_labels().get(GROUP_LABEL)
    if label:
        return f"label/{pod.namespace}/{label}"
    owners = pod.metadata.get("ownerReferences") or []
    if owners and owners[0].get("name"):
        return f"owner/{pod.namespace}/{owners[0]['name']}"
    return f"pod/{pod.namespace}/{pod.name}"


class SafeActuator:
    """Executes a plan's moves through the eviction subresource, behind
    the cooldown / min-available / rate-limit gates."""

    def __init__(
        self,
        kube_client,
        mode: str = MODE_DRY_RUN,
        rate_per_s: float = DEFAULT_RATE_PER_S,
        burst: int = DEFAULT_BURST,
        cooldown_s: float = DEFAULT_COOLDOWN_S,
        min_available: int = DEFAULT_MIN_AVAILABLE,
        clock: Callable[[], float] = time.monotonic,
    ):
        if mode not in MODES:
            raise ValueError(f"unknown rebalance mode {mode!r}")
        self.kube_client = kube_client
        self.mode = mode
        self.cooldown_s = float(cooldown_s)
        self.min_available = int(min_available)
        self._clock = clock
        self._bucket = TokenBucket(rate_per_s, burst, clock)
        self._lock = threading.Lock()
        self._last_evicted: Dict[str, float] = {}  # pod key -> stamp

    # -- gates -----------------------------------------------------------------

    def _in_cooldown(self, pod_key: str) -> bool:
        with self._lock:
            stamp = self._last_evicted.get(pod_key)
        return stamp is not None and (self._clock() - stamp) < self.cooldown_s

    def _stamp(self, pod_key: str) -> None:
        with self._lock:
            self._last_evicted[pod_key] = self._clock()

    # -- actuation -------------------------------------------------------------

    def actuate(
        self,
        moves: List[Move],
        pods_by_key: Dict[str, Pod],
        all_pods: Optional[List[Pod]] = None,
    ) -> ActuationResult:
        """Apply the plan.  ``pods_by_key`` maps move.pod_key to the live
        Pod object; ``all_pods`` is the cluster pod list used for group
        min-available accounting (group members evicted earlier in this
        same call count against the floor)."""
        result = ActuationResult()
        group_running: Dict[str, int] = {}
        if all_pods is not None:
            for pod in all_pods:
                # terminating pods (deletionTimestamp set) are already on
                # their way out — counting them as available would let an
                # eviction drop the group below the floor
                if (
                    pod.phase in ("Succeeded", "Failed")
                    or pod.deletion_timestamp is not None
                ):
                    continue
                group = workload_group(pod)
                group_running[group] = group_running.get(group, 0) + 1
        for move in moves:
            pod = pods_by_key.get(move.pod_key)
            if pod is None:
                result.skip("error", move)
                continue
            if self._in_cooldown(move.pod_key):
                result.skip("cooldown", move)
                continue
            group = workload_group(pod)
            if all_pods is not None:
                if group_running.get(group, 0) - 1 < self.min_available:
                    result.skip("min_available", move)
                    continue
            if not self._bucket.try_take():
                result.skip("rate_limit", move)
                continue
            if self.mode != MODE_ACTIVE:
                result.skip("dry_run", move)
                continue
            try:
                self.kube_client.evict_pod(pod.namespace, pod.name)
            except KubeError as exc:
                reason = "pdb" if exc.status == 409 else "error"
                klog.v(2).info_s(
                    f"eviction of {move.pod_key} refused ({reason}): {exc}",
                    component="rebalance",
                )
                result.skip(reason, move)
                continue
            self._stamp(move.pod_key)
            if group in group_running:
                group_running[group] -= 1
            result.executed.append(move)
            klog.v(2).info_s(
                f"evicted {move.pod_key}: {move.from_node} -> "
                f"{move.to_node} (gain {move.gain})",
                component="rebalance",
            )
        if result.executed:
            trace.COUNTERS.inc(
                "pas_rebalance_moves_executed_total", len(result.executed)
            )
        for reason, skipped in result.skipped.items():
            trace.COUNTERS.inc(
                "pas_rebalance_moves_skipped_total",
                len(skipped),
                labels={"reason": reason},
            )
        return result

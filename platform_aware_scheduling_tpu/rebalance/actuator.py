"""Safe eviction actuation: the only component that touches the cluster.

Every planned move passes four gates before the pods/eviction
subresource is called, in order:

  1. per-pod cooldown — a pod EVICTED recently is left alone, so a
     workload cannot be bounced every cycle (skipped moves do not start
     a cooldown: a pdb- or rate-blocked pod stays eligible and is simply
     re-gated next cycle);
  2. per-workload-group min-available — evicting must not drop the
     group's running count below the floor (the in-tree analogue of a
     PodDisruptionBudget, enforced BEFORE the API server gets a say);
  3. token-bucket rate limit — cluster-wide evictions per second with a
     small burst, so even a pathological plan drains slowly;
  4. mode — ``dry-run`` stops here (the move is recorded as skipped with
     reason ``dry_run``), ``active`` evicts.

A 409 from the API server (a real PodDisruptionBudget) is recorded as a
skipped move with reason ``pdb`` and never retried within the cycle.
Every outcome increments ``pas_rebalance_moves_{executed,skipped}_total``.

Gang atomicity (docs/gang.md): a pod that is a gang member (carries
``pas-workload-group`` + ``pas-gang-size``) is never evicted as a
subset — a plan naming only part of a gang skips those moves with
reason ``gang_partial``; a plan naming the WHOLE gang gates the gang as
one unit (any member in cooldown, a group floor breach, or missing rate
tokens skips the entire gang) and then evicts its members together.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from platform_aware_scheduling_tpu.kube.client import KubeError
from platform_aware_scheduling_tpu.kube.objects import Pod, object_key
from platform_aware_scheduling_tpu.rebalance.replan import Move
from platform_aware_scheduling_tpu.utils import klog, trace
from platform_aware_scheduling_tpu.utils import labels as shared_labels

MODE_OFF = "off"
MODE_DRY_RUN = "dry-run"
MODE_ACTIVE = "active"
MODES = (MODE_OFF, MODE_DRY_RUN, MODE_ACTIVE)

DEFAULT_RATE_PER_S = 0.5
DEFAULT_BURST = 3
DEFAULT_COOLDOWN_S = 300.0
DEFAULT_MIN_AVAILABLE = 1
#: back-compat alias — the definition moved to utils/labels.py so
#: gang/, rebalance/, and the decision records share one constant
GROUP_LABEL = shared_labels.GROUP_LABEL


class TokenBucket:
    """Classic token bucket; ``clock`` injectable for hermetic tests."""

    def __init__(
        self,
        rate_per_s: float = DEFAULT_RATE_PER_S,
        burst: int = DEFAULT_BURST,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.rate_per_s = float(rate_per_s)
        self.burst = max(1, int(burst))
        self._clock = clock
        self._tokens = float(self.burst)
        self._last = clock()
        self._lock = threading.Lock()

    def try_take(self) -> bool:
        return self.try_take_n(1)

    def try_take_n(self, n: int) -> bool:
        """Take ``n`` tokens atomically or none at all — the gang-atomic
        eviction gate (a gang larger than ``burst`` can never pass; the
        operator sizes the burst to the largest gang they will evict)."""
        with self._lock:
            now = self._clock()
            self._tokens = min(
                float(self.burst),
                self._tokens + (now - self._last) * self.rate_per_s,
            )
            self._last = now
            if self._tokens >= float(n):
                self._tokens -= float(n)
                return True
            return False


@dataclass
class ActuationResult:
    executed: List[Move] = field(default_factory=list)
    skipped: Dict[str, List[Move]] = field(default_factory=dict)

    def skip(self, reason: str, move: Move) -> None:
        self.skipped.setdefault(reason, []).append(move)

    def skip_counts(self) -> Dict[str, int]:
        return {reason: len(moves) for reason, moves in self.skipped.items()}


def workload_group(pod: Pod) -> str:
    """The min-available accounting unit: the explicit group label, else
    the first ownerReference's name (ReplicaSet/Job/StatefulSet), else
    the pod's own name (a bare pod is its own group of one)."""
    label = pod.get_labels().get(GROUP_LABEL)
    if label:
        return f"label/{pod.namespace}/{label}"
    owners = pod.metadata.get("ownerReferences") or []
    if owners and owners[0].get("name"):
        return f"owner/{pod.namespace}/{owners[0]['name']}"
    return f"pod/{pod.namespace}/{pod.name}"


class SafeActuator:
    """Executes a plan's moves through the eviction subresource, behind
    the cooldown / min-available / rate-limit gates."""

    def __init__(
        self,
        kube_client,
        mode: str = MODE_DRY_RUN,
        rate_per_s: float = DEFAULT_RATE_PER_S,
        burst: int = DEFAULT_BURST,
        cooldown_s: float = DEFAULT_COOLDOWN_S,
        min_available: int = DEFAULT_MIN_AVAILABLE,
        clock: Callable[[], float] = time.monotonic,
    ):
        if mode not in MODES:
            raise ValueError(f"unknown rebalance mode {mode!r}")
        self.kube_client = kube_client
        self.mode = mode
        self.cooldown_s = float(cooldown_s)
        self.min_available = int(min_available)
        self._clock = clock
        self._bucket = TokenBucket(rate_per_s, burst, clock)
        self._lock = threading.Lock()
        self._last_evicted: Dict[str, float] = {}  # pod key -> stamp
        # optional gang.GangTracker (set by assembly when --gang=on): a
        # fully-evicted gang's slice reservation is released so the mesh
        # nodes return to the pool instead of being held by a dead gang
        self.gang_tracker = None
        # optional kube.lease.LeaseElector: with --leaderElect, EVERY
        # eviction re-verifies the fencing token against the live lease
        # first.  A leader deposed mid-cycle (its plan already computed,
        # a standby already promoted) fails this check and the move is
        # skipped with reason ``fenced`` — the new leader owns that pod
        # now, and acting anyway is the double-eviction split-brain
        # (docs/robustness.md "HA & leader election")
        self.leadership = None

    # -- gates -----------------------------------------------------------------

    def _in_cooldown(self, pod_key: str) -> bool:
        with self._lock:
            stamp = self._last_evicted.get(pod_key)
        return stamp is not None and (self._clock() - stamp) < self.cooldown_s

    def _stamp(self, pod_key: str) -> None:
        with self._lock:
            self._last_evicted[pod_key] = self._clock()

    # -- actuation -------------------------------------------------------------

    def actuate(
        self,
        moves: List[Move],
        pods_by_key: Dict[str, Pod],
        all_pods: Optional[List[Pod]] = None,
    ) -> ActuationResult:
        """Apply the plan.  ``pods_by_key`` maps move.pod_key to the live
        Pod object; ``all_pods`` is the cluster pod list used for group
        min-available accounting (group members evicted earlier in this
        same call count against the floor) AND for gang-membership
        completeness — without it a gang's planned moves are taken as
        the full membership (nothing to verify against).

        Gang members are never evicted as a subset: partial-gang moves
        skip with reason ``gang_partial``; whole-gang moves gate and
        evict atomically (module doc)."""
        result = ActuationResult()
        group_running: Dict[str, int] = {}
        gang_members: Dict[str, set] = {}  # gang id -> live member keys
        if all_pods is not None:
            for pod in all_pods:
                # terminating pods (deletionTimestamp set) are already on
                # their way out — counting them as available would let an
                # eviction drop the group below the floor
                if (
                    pod.phase in ("Succeeded", "Failed")
                    or pod.deletion_timestamp is not None
                ):
                    continue
                group = workload_group(pod)
                group_running[group] = group_running.get(group, 0) + 1
                gang = shared_labels.gang_id_for(
                    pod.namespace, pod.get_labels()
                )
                if gang is not None:
                    # membership is compared via object_key on the Pod
                    # objects THEMSELVES — Move.pod_key's format
                    # (object_key in production, free-form in tests) is
                    # never assumed
                    gang_members.setdefault(gang, set()).add(
                        object_key(pod)
                    )
        singles: List[Move] = []
        gang_moves: Dict[str, List[Move]] = {}
        for move in moves:
            pod = pods_by_key.get(move.pod_key)
            gang = (
                shared_labels.gang_id_for(pod.namespace, pod.get_labels())
                if pod is not None
                else None
            )
            if gang is not None:
                gang_moves.setdefault(gang, []).append(move)
            else:
                singles.append(move)
        for gang, gmoves in gang_moves.items():
            planned = {
                object_key(pods_by_key[m.pod_key])
                for m in gmoves
                if m.pod_key in pods_by_key
            }
            members = gang_members.get(gang, planned)
            if planned != members:
                # evicting a subset would leave a half-dead gang holding
                # its slice: whole gangs or nothing
                for move in gmoves:
                    result.skip("gang_partial", move)
                continue
            self._actuate_gang(gang, gmoves, pods_by_key, group_running,
                               all_pods, result)
        for move in singles:
            pod = pods_by_key.get(move.pod_key)
            if pod is None:
                result.skip("error", move)
                continue
            if self._in_cooldown(move.pod_key):
                result.skip("cooldown", move)
                continue
            group = workload_group(pod)
            if all_pods is not None:
                if group_running.get(group, 0) - 1 < self.min_available:
                    result.skip("min_available", move)
                    continue
            if not self._bucket.try_take():
                result.skip("rate_limit", move)
                continue
            if self.mode != MODE_ACTIVE:
                result.skip("dry_run", move)
                continue
            if not self._evict(move, pod, result):
                continue
            if group in group_running:
                group_running[group] -= 1
        if result.executed:
            trace.COUNTERS.inc(
                "pas_rebalance_moves_executed_total", len(result.executed)
            )
        for reason, skipped in result.skipped.items():
            trace.COUNTERS.inc(
                "pas_rebalance_moves_skipped_total",
                len(skipped),
                labels={"reason": reason},
            )
        return result

    def _evict(self, move: Move, pod: Pod, result: ActuationResult) -> bool:
        """One eviction through the subresource; False records the skip
        (409 -> ``pdb``, fencing refusal -> ``fenced``, anything else ->
        ``error``).  The fencing check runs before EACH eviction, not
        once per cycle: leadership can move between the first and last
        move of one plan."""
        if self.leadership is not None and not self.leadership.check_fencing():
            klog.v(1).info_s(
                f"eviction of {move.pod_key} refused: fencing token no "
                f"longer valid (leadership moved)",
                component="rebalance",
            )
            result.skip("fenced", move)
            return False
        try:
            self.kube_client.evict_pod(pod.namespace, pod.name)
        except KubeError as exc:
            reason = "pdb" if exc.status == 409 else "error"
            klog.v(2).info_s(
                f"eviction of {move.pod_key} refused ({reason}): {exc}",
                component="rebalance",
            )
            result.skip(reason, move)
            return False
        self._stamp(move.pod_key)
        result.executed.append(move)
        klog.v(2).info_s(
            f"evicted {move.pod_key}: {move.from_node} -> "
            f"{move.to_node} (gain {move.gain})",
            component="rebalance",
        )
        return True

    def _actuate_gang(
        self,
        gang: str,
        gmoves: List[Move],
        pods_by_key: Dict[str, Pod],
        group_running: Dict[str, int],
        all_pods: Optional[List[Pod]],
        result: ActuationResult,
    ) -> None:
        """Whole-gang atomic actuation: every gate is evaluated for the
        gang as one unit BEFORE any eviction, so a mid-gang gate trip can
        never strand a half-evicted gang.  (An API-server refusal on one
        member mid-flight is recorded per pod — the server, not this
        actuator, broke atomicity there.)"""
        pods = []
        for move in gmoves:
            pod = pods_by_key.get(move.pod_key)
            if pod is None:
                for m in gmoves:
                    result.skip("error", m)
                return
            pods.append(pod)
        if any(self._in_cooldown(m.pod_key) for m in gmoves):
            for m in gmoves:
                result.skip("cooldown", m)
            return
        if all_pods is not None:
            floor_breach: Dict[str, int] = {}
            for pod in pods:
                group = workload_group(pod)
                floor_breach[group] = floor_breach.get(group, 0) + 1
            for group, n in floor_breach.items():
                if group_running.get(group, 0) - n < self.min_available:
                    for m in gmoves:
                        result.skip("min_available", m)
                    return
        if not self._bucket.try_take_n(len(gmoves)):
            for m in gmoves:
                result.skip("rate_limit", m)
            return
        if self.mode != MODE_ACTIVE:
            for m in gmoves:
                result.skip("dry_run", m)
            return
        klog.v(2).info_s(
            f"evicting gang {gang} atomically ({len(gmoves)} pods)",
            component="rebalance",
        )
        evicted = 0
        for move, pod in zip(gmoves, pods):
            if self._evict(move, pod, result):
                evicted += 1
                group = workload_group(pod)
                if group in group_running:
                    group_running[group] -= 1
        if evicted == len(gmoves) and self.gang_tracker is not None:
            # the whole gang is gone: free its slice reservation (a
            # partially-refused gang keeps its hold; the tracker's
            # dead-gang sweep reclaims it once every member disappears)
            self.gang_tracker.release(gang)

    # -- preemption (admission/preempt.py; docs/admission.md) ------------------

    def preempt_gang(
        self,
        gang_id: str,
        pods: List[Pod],
        counters=None,
    ) -> Tuple[bool, ActuationResult]:
        """The preemption verb — deliberate whole-gang displacement for
        the admission plane, distinct from drift eviction in three ways:

          * **no min-available floor**: preemption removes the victim
            group entirely by design; the floor exists to stop a drift
            plan from accidentally gutting a group, and here the planner
            chose the whole gang deliberately (whole-gang atomicity is
            the safety property, not the floor);
          * **no slice release on success**: the victim flips to
            DRAINING (caller) and keeps holding its nodes until its pods
            are actually gone — reservation-while-draining;
          * **its own accounting**: outcomes land in the admission
            plane's ``pas_preemption_*`` families via ``counters``, not
            in ``pas_rebalance_moves_*`` (the off path registers
            nothing).

        The shared gates stay: any member in eviction cooldown, missing
        rate tokens (taken atomically for the whole gang), or a
        non-active mode refuses the WHOLE preemption before any API
        call, and every eviction re-verifies the fencing token.  Returns
        ``(fully_evicted, result)``."""
        result = ActuationResult()
        moves = [
            Move(
                pod_key=object_key(pod),
                namespace=pod.namespace,
                name=pod.name,
                from_node=pod.spec_node_name or "",
                to_node="",
                gain=0.0,
            )
            for pod in pods
        ]

        def refuse(reason: str) -> Tuple[bool, ActuationResult]:
            for m in moves:
                result.skip(reason, m)
            if counters is not None and moves:
                counters.inc(
                    "pas_preemption_skipped_total",
                    len(moves),
                    labels={"reason": reason},
                )
            return False, result

        if not moves:
            return False, result
        if any(self._in_cooldown(m.pod_key) for m in moves):
            return refuse("cooldown")
        if not self._bucket.try_take_n(len(moves)):
            return refuse("rate_limit")
        if self.mode != MODE_ACTIVE:
            return refuse("dry_run")
        klog.v(1).info_s(
            f"preempting gang {gang_id} atomically ({len(moves)} pods)",
            component="rebalance",
        )
        evicted = 0
        for move, pod in zip(moves, pods):
            if self._evict(move, pod, result):
                evicted += 1
        if counters is not None:
            if evicted:
                counters.inc("pas_preemption_evictions_total", evicted)
            for reason, skipped in result.skipped.items():
                counters.inc(
                    "pas_preemption_skipped_total",
                    len(skipped),
                    labels={"reason": reason},
                )
        return evicted == len(moves), result

"""Closed-loop rebalancer: native descheduling with incremental TPU
replan and safe eviction actuation (docs/rebalance.md).

The reference's enforcement layer stops at node labels
(deschedule/enforce.go) and delegates actual eviction to the external
kubernetes-sigs/descheduler, so the loop from "telemetry says this node
is bad" to "workload lands somewhere good" is never closed in-tree
(SURVEY §L6, §7 step 6).  This package closes it natively:

  * :mod:`drift` — hysteresis over per-cycle violation sets: a node must
    violate for K consecutive enforcement cycles before it becomes an
    eviction candidate; a clean cycle resets the streak;
  * :mod:`replan` — the incremental on-device solve: evictable pods on
    candidate nodes + the current telemetry matrix, scored through the
    existing batched kernels with a migration-cost penalty so pods stay
    put unless moving buys real headroom, bounded by a per-cycle churn
    budget;
  * :mod:`actuator` — eviction through the pods/eviction subresource
    behind a token-bucket rate limit, per-pod cooldown, and a
    per-workload-group min-available guard;
  * :mod:`loop` — the controller tying them together, driven by the
    MetricEnforcer's per-cycle violation publications, with
    ``off | dry-run | active`` modes, ``pas_rebalance_*`` metrics, and
    the ``GET /debug/rebalance`` last-plan view.
"""

from platform_aware_scheduling_tpu.rebalance.actuator import (
    ActuationResult,
    SafeActuator,
    TokenBucket,
)
from platform_aware_scheduling_tpu.rebalance.drift import DriftDetector
from platform_aware_scheduling_tpu.rebalance.loop import Rebalancer
from platform_aware_scheduling_tpu.rebalance.replan import (
    IncrementalReplanner,
    Move,
)

__all__ = [
    "ActuationResult",
    "DriftDetector",
    "IncrementalReplanner",
    "Move",
    "Rebalancer",
    "SafeActuator",
    "TokenBucket",
]

"""Drift detection with hysteresis over per-cycle violation sets.

One transiently hot scrape must not evict anything: a node becomes an
eviction candidate only after K CONSECUTIVE enforcement cycles in the
violation set (the deschedule strategy publishes its node -> [policies]
map every cycle, empty included).  A cycle in which the node is absent
resets its streak to zero — recovery is immediate, escalation is slow,
which is the asymmetry a safe eviction loop wants.
"""

from __future__ import annotations

from typing import Dict, List

DEFAULT_HYSTERESIS_CYCLES = 3


class DriftDetector:
    """Streak counter over violation cycles.  Not thread-safe on its own;
    the rebalance loop calls :meth:`observe` from the single enforcement
    thread that publishes violations."""

    def __init__(self, k: int = DEFAULT_HYSTERESIS_CYCLES):
        if k < 1:
            raise ValueError(f"hysteresis cycles must be >= 1, got {k}")
        self.k = k
        self._streaks: Dict[str, int] = {}

    def observe(self, violations: Dict[str, List[str]]) -> Dict[str, List[str]]:
        """Fold one enforcement cycle in; returns the candidate map
        (node -> policies violated this cycle) for nodes whose streak has
        reached K."""
        streaks: Dict[str, int] = {}
        for node in violations:
            streaks[node] = self._streaks.get(node, 0) + 1
        # nodes absent from this cycle's set simply drop out: streak reset
        self._streaks = streaks
        return {
            node: list(policies)
            for node, policies in violations.items()
            if streaks[node] >= self.k
        }

    def streaks(self) -> Dict[str, int]:
        """Current per-node consecutive-violation counts (for /debug)."""
        return dict(self._streaks)

    def reset(self) -> None:
        self._streaks = {}

"""Drift detection with hysteresis over per-cycle violation sets.

One transiently hot scrape must not evict anything: a node becomes an
eviction candidate only after K CONSECUTIVE enforcement cycles in the
violation set (the deschedule strategy publishes its node -> [policies]
map every cycle, empty included).  A cycle in which the node is absent
resets its streak to zero — recovery is immediate, escalation is slow,
which is the asymmetry a safe eviction loop wants.

With forecasting on (docs/forecast.md), the loop additionally passes a
``hold`` set: nodes violating NOW whose violated metrics are all
trending back DOWN (a transient spike mid-resolution).  A held node's
streak neither advances (the spike is not evidence of drift) nor resets
(it is still violating) — so a spike that self-resolves never reaches
the eviction threshold, while a genuine trend keeps escalating at the
same speed as before.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List

DEFAULT_HYSTERESIS_CYCLES = 3


class DriftDetector:
    """Streak counter over violation cycles.  Not thread-safe on its own;
    the rebalance loop calls :meth:`observe` from the single enforcement
    thread that publishes violations."""

    def __init__(self, k: int = DEFAULT_HYSTERESIS_CYCLES):
        self.k = k
        self._streaks: Dict[str, int] = {}

    @property
    def k(self) -> int:
        return self._k

    @k.setter
    def k(self, value: int) -> None:
        # mutated at runtime by the budget controller
        # (loop.set_aggressiveness); a bad write must never silently
        # disable hysteresis, so the invariant holds at every assignment
        if value < 1:
            raise ValueError(f"hysteresis cycles must be >= 1, got {value}")
        self._k = int(value)

    def observe(
        self,
        violations: Dict[str, List[str]],
        hold: FrozenSet[str] = frozenset(),
    ) -> Dict[str, List[str]]:
        """Fold one enforcement cycle in; returns the candidate map
        (node -> policies violated this cycle) for nodes whose streak has
        reached K.  Nodes in ``hold`` (violating but trending down) keep
        their prior streak instead of advancing, AND are never candidates
        this cycle regardless of streak — a node whose eviction was
        deferred at streak K and is now resolving on its own is exactly
        the useless eviction the hold exists to prevent."""
        streaks: Dict[str, int] = {}
        for node in violations:
            prior = self._streaks.get(node, 0)
            streaks[node] = prior if node in hold else prior + 1
        # nodes absent from this cycle's set simply drop out: streak reset
        self._streaks = streaks
        if hold:
            return {
                node: policies
                for node, policies in violations.items()
                if streaks[node] >= self.k and node not in hold
            }
        return {
            node: list(policies)
            for node, policies in violations.items()
            if streaks[node] >= self.k
        }

    def streaks(self) -> Dict[str, int]:
        """Current per-node consecutive-violation counts (for /debug)."""
        return dict(self._streaks)

    def reset(self) -> None:
        self._streaks = {}

"""The rebalance control loop: drift -> replan -> actuate, once per
enforcement cycle.

The loop owns no timer.  It subscribes to the MetricEnforcer's
per-cycle violation publications (``enforcer.violation_observers``), so
each deschedule enforcement pass IS a rebalance cycle: the drift
detector folds the cycle in, nodes past the hysteresis threshold become
candidates, the evictable pods on candidate nodes are replanned
on-device with the migration-cost penalty, and the actuator applies the
bounded move list behind its guards.  Everything runs in the enforcer's
thread — a failing cycle is logged and the next enforcement pass simply
starts a fresh one.

The most recent plan (and the loop's configuration and streaks) is
published as JSON on ``GET /debug/rebalance`` on both front-ends.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional

from platform_aware_scheduling_tpu.kube.objects import Pod, object_key
from platform_aware_scheduling_tpu.rebalance.actuator import (
    DEFAULT_BURST,
    DEFAULT_COOLDOWN_S,
    DEFAULT_MIN_AVAILABLE,
    DEFAULT_RATE_PER_S,
    MODE_ACTIVE,
    MODE_OFF,
    MODES,
    SafeActuator,
)
from platform_aware_scheduling_tpu.rebalance.drift import (
    DEFAULT_HYSTERESIS_CYCLES,
    DriftDetector,
)
from platform_aware_scheduling_tpu.rebalance.replan import (
    DEFAULT_MAX_MOVES,
    DEFAULT_MIGRATION_COST,
    IncrementalReplanner,
    PlanResult,
)
from platform_aware_scheduling_tpu.tas.planner import (
    DEFAULT_NODE_CAPACITY,
    TAS_POLICY_LABEL,
)
from platform_aware_scheduling_tpu.utils import decisions, events, klog, trace
from platform_aware_scheduling_tpu.utils.quantity import Quantity

DESCHEDULE_STRATEGY = "deschedule"


class Rebalancer:
    """Drift detector + incremental replanner + safe actuator, driven by
    enforcement-cycle violation publications."""

    def __init__(
        self,
        kube_client,
        mirror,
        mode: str = "dry-run",
        hysteresis_cycles: int = DEFAULT_HYSTERESIS_CYCLES,
        solver: str = "greedy",
        max_moves: int = DEFAULT_MAX_MOVES,
        migration_cost: float = DEFAULT_MIGRATION_COST,
        rate_per_s: float = DEFAULT_RATE_PER_S,
        burst: int = DEFAULT_BURST,
        cooldown_s: float = DEFAULT_COOLDOWN_S,
        min_available: int = DEFAULT_MIN_AVAILABLE,
        clock: Callable[[], float] = time.monotonic,
    ):
        if mode not in MODES:
            raise ValueError(f"unknown rebalance mode {mode!r}")
        self.kube_client = kube_client
        self.mode = mode
        self.drift = DriftDetector(k=hysteresis_cycles)
        self.replanner = IncrementalReplanner(
            mirror,
            solver=solver,
            migration_cost=migration_cost,
            max_moves=max_moves,
        )
        self.actuator = SafeActuator(
            kube_client,
            mode=mode,
            rate_per_s=rate_per_s,
            burst=burst,
            cooldown_s=cooldown_s,
            min_available=min_available,
            clock=clock,
        )
        self._lock = threading.Lock()
        self._cycles = 0
        self._last_plan: Optional[Dict] = None
        # optional tas.degraded.DegradedModeController: while evictions
        # are suspended (stale telemetry / open kube circuit) the cycle
        # does NOTHING — no drift accounting, no planning, no actuation.
        # Defense in depth on top of the deschedule-side gate: this loop
        # must hold the zero-evictions invariant even when driven
        # directly (docs/robustness.md)
        self.degraded = None
        # optional kube.lease.LeaseElector (docs/robustness.md "HA &
        # leader election"): the rebalance cycle is a singleton loop —
        # followers freeze exactly like degraded cycles (streaks neither
        # grow nor reset; violations are not this replica's to act on)
        # and the idleness surfaces as actuation.reason="follower"
        self.leadership = None
        # optional forecast.Forecaster (docs/forecast.md): per-node trend
        # signs classify a violation as trending-up (streak advances as
        # before) vs transient-spike-with-negative-slope (streak HOLDS —
        # the eviction that spike would have triggered is suppressed and
        # counted on pas_forecast_suppressed_evictions_total)
        self.forecaster = None
        # nodes whose hold-at-threshold-minus-one already counted a
        # suppressed eviction: a spike held for many cycles is ONE
        # suppressed eviction, not one per cycle (membership drops when
        # the node leaves the at-threshold hold, so a later fresh spike
        # counts again)
        self._suppress_counted: set = set()
        # convergence episode tracking: first violating cycle after a
        # clean one opens an episode; the next clean cycle closes it and
        # publishes its length
        self._episode_start: Optional[int] = None
        self._last_convergence: Optional[int] = None

    # -- wiring ----------------------------------------------------------------

    def attach(self, enforcer) -> None:
        """Subscribe to the enforcer's violation publications."""
        enforcer.violation_observers.append(self.on_violations)

    def set_aggressiveness(
        self,
        max_moves: Optional[int] = None,
        hysteresis_k: Optional[int] = None,
    ) -> None:
        """Runtime modulation of how hard the rebalancer pushes — the
        budget controller's eviction-safety actuator.  Both fields are
        read live inside the cycle (plan() caps on replanner.max_moves,
        streak promotion compares against drift.k), so a mid-flight
        tightening applies to the very next cycle without restart.
        Raising k mid-streak never evicts retroactively: streaks only
        promote when they REACH the threshold, so a longer fuse simply
        delays candidates already burning."""
        if max_moves is not None:
            if max_moves < 1:
                raise ValueError(f"max_moves must be >= 1, got {max_moves}")
            self.replanner.max_moves = int(max_moves)
        if hysteresis_k is not None:
            if hysteresis_k < 1:
                raise ValueError(
                    f"hysteresis_k must be >= 1, got {hysteresis_k}"
                )
            self.drift.k = int(hysteresis_k)

    def on_violations(
        self, strategy_type: str, violations: Dict[str, List[str]]
    ) -> None:
        if strategy_type != DESCHEDULE_STRATEGY:
            return
        try:
            self.cycle(violations)
        except Exception as exc:  # a bad cycle must not break enforcement
            klog.error("rebalance cycle failed: %r", exc)

    # -- the cycle -------------------------------------------------------------

    def cycle(self, violations: Dict[str, List[str]]) -> Dict:
        """One rebalance cycle over this enforcement pass's violation
        map; returns (and stores for /debug/rebalance) the plan record."""
        if self.leadership is not None and not self.leadership.is_leader():
            # follower: same freeze semantics as degraded (streaks
            # neither grow nor reset — the leader owns the hysteresis
            # trajectory), surfaced with its own idle reason.  No
            # decision record: every follower idles every cycle, and
            # spamming the ring with non-decisions would evict real ones
            record = {
                "mode": self.mode,
                "suspended": "follower: not the leader replica",
                "idle_reason": "follower",
                "violating_nodes": sorted(violations),
                "moves": [],
                "executed": [],
                "skipped": {},
            }
            with self._lock:
                self._last_plan = record
            return record
        if self.degraded is not None:
            allowed, reason = self.degraded.evictions_allowed()
            if not allowed:
                # freeze: streaks neither grow (stale violations are not
                # evidence) nor reset (the hot node may still be hot);
                # the suspension is visible on /debug/rebalance
                record = {
                    "mode": self.mode,
                    "suspended": reason,
                    "idle_reason": "degraded",
                    "violating_nodes": sorted(violations),
                    "moves": [],
                    "executed": [],
                    "skipped": {},
                }
                with self._lock:
                    self._last_plan = record
                decisions.DECISIONS.record_rebalance(dict(record))
                klog.v(2).info_s(
                    f"rebalance cycle suspended: {reason}",
                    component="rebalance",
                )
                return record
        with self._lock:
            self._cycles += 1
            cycle_no = self._cycles
            if violations and self._episode_start is None:
                self._episode_start = cycle_no
            elif not violations and self._episode_start is not None:
                self._last_convergence = cycle_no - self._episode_start
                self._episode_start = None
                trace.COUNTERS.set_gauge(
                    "pas_rebalance_convergence_cycles",
                    float(self._last_convergence),
                )
        hold = self._trend_holds(violations)
        # suppressed = held nodes snapshot hysteresis would have evicted
        # this cycle: streak at k-1 (advancing would reach k) OR already
        # at/past k (a deferred eviction the hold now blocks outright).
        # A held node's streak is frozen, so it re-satisfies the test
        # every cycle of the spike; the counted set de-duplicates the
        # episode to ONE
        prior = self.drift.streaks()
        at_threshold = {
            node
            for node in hold
            if prior.get(node, 0) + 1 >= self.drift.k
        }
        newly_suppressed = at_threshold - self._suppress_counted
        self._suppress_counted = at_threshold
        if newly_suppressed and self.forecaster is not None:
            self.forecaster.count_suppressed_eviction(len(newly_suppressed))
        candidates = self.drift.observe(violations, hold=hold)
        trace.COUNTERS.set_gauge(
            "pas_rebalance_candidate_nodes", float(len(candidates))
        )
        record: Dict = {
            "cycle": cycle_no,
            "mode": self.mode,
            "violating_nodes": sorted(violations),
            "trend_held_nodes": sorted(hold),
            "candidate_nodes": sorted(candidates),
            "moves": [],
            "executed": [],
            "skipped": {},
            "plan_ms": 0.0,
            "view_version": None,
        }
        if self.mode == MODE_OFF or not candidates:
            with self._lock:
                self._last_plan = record
            return record
        evictable, pods_by_key, all_pods, remaining = self._evictable_pods(
            candidates
        )
        plan = self.replanner.plan(evictable, violations, remaining)
        trace.COUNTERS.inc("pas_rebalance_plans_total")
        trace.COUNTERS.set_gauge(
            "pas_rebalance_plan_latency_seconds", plan.latency_s
        )
        if plan.moves:
            trace.COUNTERS.inc(
                "pas_rebalance_moves_planned_total", len(plan.moves)
            )
        actuation = self.actuator.actuate(plan.moves, pods_by_key, all_pods)
        record.update(
            {
                "considered_pods": plan.considered,
                "skipped_pods": plan.skipped_pods,
                "truncated_moves": plan.truncated,
                "deferred_moves": plan.deferred,
                "moves": [m._asdict() for m in plan.moves],
                "executed": [m.pod_key for m in actuation.executed],
                "skipped": actuation.skip_counts(),
                "plan_ms": round(plan.latency_s * 1e3, 3),
                "view_version": plan.view_version,
            }
        )
        with self._lock:
            self._last_plan = record
        if plan.moves:
            # decision provenance: the cycle itself becomes a record, and
            # each planned pod's open Filter/Prioritize records gain the
            # evict/skip outcome as an event (utils/decisions.py)
            decisions.DECISIONS.record_rebalance(dict(record))
            for move in actuation.executed:
                decisions.DECISIONS.observe_rebalance(
                    move.namespace, move.name, "evicted",
                    f"{move.from_node} -> {move.to_node}",
                )
                events.JOURNAL.publish(
                    "rebalance",
                    "move executed",
                    pod=move.pod_key,
                    node=move.from_node,
                    data={"to": move.to_node, "cycle": cycle_no},
                )
            for reason, skipped in actuation.skipped.items():
                for move in skipped:
                    decisions.DECISIONS.observe_rebalance(
                        move.namespace, move.name, f"evict_skipped:{reason}"
                    )
            klog.v(2).info_s(
                f"rebalance cycle {cycle_no}: {len(plan.moves)} moves "
                f"planned, {len(actuation.executed)} executed, "
                f"skipped {actuation.skip_counts()}",
                component="rebalance",
            )
        return record

    def _trend_holds(self, violations: Dict[str, List[str]]) -> frozenset:
        """Violating nodes whose violated deschedule metrics are ALL
        trending strictly down (docs/forecast.md): the transient-spike
        signature whose streak the drift detector holds.  Fails open to
        the empty set — snapshot hysteresis — on any trouble."""
        forecaster = self.forecaster
        if forecaster is None or not violations:
            return frozenset()
        try:
            mirror = self.replanner.mirror
            metric_names: Dict[str, tuple] = {}
            held = set()
            for node, policies in violations.items():
                metrics: List[str] = []
                for policy_name in policies:
                    names = metric_names.get(policy_name)
                    if names is None:
                        compiled, _view = mirror.policy_with_view_by_name(
                            policy_name
                        )
                        rules = (
                            compiled.deschedule
                            if compiled is not None
                            else None
                        )
                        names = (
                            tuple(rules.metric_names)
                            if rules is not None
                            else ()
                        )
                        metric_names[policy_name] = names
                    metrics.extend(names)
                if metrics and forecaster.trending_down(node, metrics):
                    held.add(node)
            return frozenset(held)
        except Exception as exc:  # trend trouble must never stop the loop
            klog.error("trend classification failed open: %r", exc)
            return frozenset()

    def _evictable_pods(self, candidates: Dict[str, List[str]]):
        """(evictable pods on candidate nodes, key -> Pod, all pods,
        remaining capacity per node).  Evictable = bound to a candidate
        node, policy-managed (carries the telemetry-policy label), still
        running, and not already terminating."""
        all_pods = self.kube_client.list_pods()
        bound: Dict[str, int] = {}
        evictable: List[Pod] = []
        pods_by_key: Dict[str, Pod] = {}
        for pod in all_pods:
            node = pod.spec_node_name
            if node and pod.phase not in ("Succeeded", "Failed"):
                bound[node] = bound.get(node, 0) + 1
            if (
                node in candidates
                and pod.phase not in ("Succeeded", "Failed")
                and pod.deletion_timestamp is None
                and TAS_POLICY_LABEL in pod.get_labels()
            ):
                evictable.append(pod)
                pods_by_key[object_key(pod)] = pod
        remaining: Dict[str, int] = {}
        # a list_nodes failure aborts the cycle (cycle() propagates to the
        # guarded observer): proceeding would hand the replan a fabricated
        # default capacity for every node and actuate evictions against it
        nodes = self.kube_client.list_nodes()
        for node in nodes:
            alloc = DEFAULT_NODE_CAPACITY
            raw = node.allocatable.get("pods")
            if raw is not None:
                try:
                    value, _exact = Quantity(str(raw)).as_int64()
                    alloc = int(value)
                except Exception:
                    pass
            remaining[node.name] = alloc - bound.get(node.name, 0)
        return evictable, pods_by_key, all_pods, remaining

    # -- debug surface ---------------------------------------------------------

    def status(self) -> Dict:
        with self._lock:
            last_plan = self._last_plan
            cycles = self._cycles
            episode_start = self._episode_start
            last_convergence = self._last_convergence
        degraded_status = (
            self.degraded.status() if self.degraded is not None else None
        )
        # why actuation is idle, as a concrete reason — not one opaque
        # suspended flag: "off" (operator choice), "follower" (another
        # replica leads), "degraded" (eviction suspension), or an active
        # idle=False.  Precedence mirrors the cycle's own gate order.
        if self.mode == MODE_OFF:
            actuation = {"idle": True, "reason": "off"}
        elif self.leadership is not None and not self.leadership.is_leader():
            actuation = {"idle": True, "reason": "follower"}
        elif degraded_status and not degraded_status["evictions"]["allowed"]:
            actuation = {"idle": True, "reason": "degraded"}
        else:
            actuation = {"idle": False, "reason": None}
        return {
            "mode": self.mode,
            "actuation": actuation,
            "role": (
                self.leadership.role() if self.leadership is not None else None
            ),
            "degraded": degraded_status,
            "evictions_suspended": bool(
                degraded_status
                and not degraded_status["evictions"]["allowed"]
            ),
            "solver": self.replanner.solver,
            "hysteresis_cycles": self.drift.k,
            "max_moves_per_cycle": self.replanner.max_moves,
            "migration_cost": self.replanner.migration_cost,
            "cooldown_s": self.actuator.cooldown_s,
            "min_available": self.actuator.min_available,
            "cycles": cycles,
            "streaks": self.drift.streaks(),
            "in_episode": episode_start is not None,
            "last_convergence_cycles": last_convergence,
            "last_plan": last_plan,
        }

    def to_json(self) -> bytes:
        return json.dumps(self.status()).encode() + b"\n"

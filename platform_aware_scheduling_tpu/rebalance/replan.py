"""Incremental replan: the penalized batched solve behind the rebalancer.

Each cycle the evictable pods on candidate nodes plus the current
telemetry matrix become one bounded assignment problem, solved on-device
through the SAME kernels the batch planner uses (``_score_keys`` from
models/batch_scheduler, greedy/sinkhorn rounding from ops/) with two
penalty terms layered on the normalized utilities:

  * ``violation_penalty`` pushes every currently-violating node's lanes
    far below any clean node — the whole point of the move;
  * ``migration_cost`` is a bonus on each pod's CURRENT node — a pod
    moves only when the destination's utility beats staying put by more
    than the cost of the migration, so the plan converges to "no moves"
    instead of oscillating.

The solve is incremental in the scheduling sense: pods not on candidate
nodes never enter the problem, every pod's stay-put option is always
feasible (its own slot is added back to its node's remaining capacity),
and the host-side churn budget truncates the move list to the
highest-gain ``max_moves`` per cycle so actuation is always bounded.

Shapes are padded (pods to 8, nodes to the mirror's capacity buckets) so
XLA recompiles per bucket, never per pod.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from platform_aware_scheduling_tpu.kube.objects import Pod, object_key
from platform_aware_scheduling_tpu.models.batch_scheduler import _score_keys
from platform_aware_scheduling_tpu.ops import i64
from platform_aware_scheduling_tpu.ops.assign import greedy_assign_kernel
from platform_aware_scheduling_tpu.ops.sinkhorn import (
    _normalize_scores,
    sinkhorn_assign_kernel,
)
from platform_aware_scheduling_tpu.ops.state import TensorStateMirror
from platform_aware_scheduling_tpu.tas.planner import (
    DEFAULT_NODE_CAPACITY,
    TAS_POLICY_LABEL,
)
from platform_aware_scheduling_tpu.utils import klog

POD_PAD = 8
#: utility drop applied to every violating node's lanes; utilities are
#: normalized into [0, 1], so anything > 1 + migration bonus guarantees a
#: clean node with capacity always beats staying on a violating one
DEFAULT_VIOLATION_PENALTY = 4.0
#: stay-put bonus in normalized-utility units: a move must buy at least
#: this much headroom over the pod's current node
DEFAULT_MIGRATION_COST = 0.1
DEFAULT_MAX_MOVES = 5
#: incoming moves any one destination accepts per cycle.  Telemetry
#: utilities rank nodes globally, so every evictee prefers the SAME
#: least-loaded node; slot capacity alone lets the whole herd land
#: there, which overshoots the very threshold the move was curing and
#: ping-pongs the same pods between destinations every hysteresis
#: window (found by the scenario fuzzer: tests/scenarios/
#: rebalance_herd.json).  One-in-per-cycle spreads the herd across
#: distinct destinations; the next cycle replans against fresh
#: telemetry that already includes the landed pods.
DEFAULT_MAX_INFLOW = 1


class Move(NamedTuple):
    pod_key: str
    namespace: str
    name: str
    from_node: str
    to_node: str
    gain: float  # adjusted-utility headroom the move buys


class PlanResult(NamedTuple):
    moves: List[Move]
    considered: int  # pods that entered the solve
    skipped_pods: int  # evictable pods the solve could not score
    truncated: int  # moves dropped by the churn budget
    latency_s: float
    view_version: int
    deferred: int = 0  # moves held back by the per-destination inflow cap


@partial(jax.jit, static_argnames=("solver",))
def penalized_assign_kernel(
    values_hi,  # int32 [M, N]
    values_lo,  # uint32 [M, N]
    present,  # bool [M, N]
    metric_row,  # int32 [P]
    op_id,  # int32 [P]
    violating,  # bool [N]
    current,  # int32 [P] — each pod's current node index
    capacity,  # int32 [N] — remaining slots incl. the pods' own
    active,  # bool [P] — real pod vs shape padding
    migration_bonus,  # f32 scalar
    violation_penalty,  # f32 scalar
    solver: str = "greedy",
):
    """(node_for_pod [P], adjusted utility [P, N]).  Padding rows are
    inactive (no eligible lane) and come back UNASSIGNED."""
    values = i64.I64(hi=values_hi, lo=values_lo)
    score = _score_keys(values, present, metric_row, op_id)  # [P, N]
    present_rows = present[metric_row]  # [P, N]
    n = present.shape[1]
    is_current = (
        jnp.arange(n, dtype=jnp.int32)[None, :] == current[:, None]
    )  # [P, N]; padding rows carry current = -1 -> no current lane
    utility = _normalize_scores(score, present_rows)
    adj = (
        utility
        - violation_penalty * violating[None, :].astype(jnp.float32)
        + migration_bonus * is_current.astype(jnp.float32)
    )
    # stay-put must always be representable, even when the pod's metric
    # is absent on its own node
    eligible = (present_rows | is_current) & active[:, None]
    # quantize the adjusted utilities to exact keys (micro-units) for the
    # deterministic i64 comparators, sign-extended into the limbs —
    # exactly the sinkhorn module's rounding trick
    q = jnp.clip(adj * jnp.float32(1e6), -2.0e9, 2.0e9).astype(jnp.int32)
    keys = i64.I64(
        hi=jnp.where(q < 0, jnp.int32(-1), jnp.int32(0)),
        lo=jax.lax.bitcast_convert_type(q, jnp.uint32),
    )
    if solver == "sinkhorn":
        assignment = sinkhorn_assign_kernel(keys, eligible, capacity).assignment
    else:
        assignment = greedy_assign_kernel(keys, eligible, capacity)
    return assignment.node_for_pod, adj


class IncrementalReplanner:
    """Builds and solves the per-cycle reassignment problem against the
    mirror's current device view."""

    def __init__(
        self,
        mirror: TensorStateMirror,
        solver: str = "greedy",
        migration_cost: float = DEFAULT_MIGRATION_COST,
        violation_penalty: float = DEFAULT_VIOLATION_PENALTY,
        max_moves: int = DEFAULT_MAX_MOVES,
        default_node_capacity: int = DEFAULT_NODE_CAPACITY,
        max_inflow: Optional[int] = DEFAULT_MAX_INFLOW,
    ):
        if solver not in ("greedy", "sinkhorn"):
            raise ValueError(f"unknown rebalance solver {solver!r}")
        self.mirror = mirror
        self.solver = solver
        self.migration_cost = float(migration_cost)
        self.violation_penalty = float(violation_penalty)
        self.max_moves = int(max_moves)
        self.default_node_capacity = int(default_node_capacity)
        self.max_inflow = None if max_inflow is None else max(1, int(max_inflow))

    def plan(
        self,
        pods: List[Pod],
        violations: Dict[str, List[str]],
        remaining_capacity: Optional[Dict[str, int]] = None,
    ) -> PlanResult:
        """Solve the reassignment for ``pods`` (the evictable set on
        candidate nodes) against the full current ``violations`` map.
        ``remaining_capacity``: node -> free pod slots EXCLUDING the
        pods being replanned (their own slots are added back here so
        stay-put is always feasible)."""
        t0 = time.perf_counter()
        empty = PlanResult([], 0, len(pods), 0, 0.0, self.mirror.version)
        if not pods:
            return empty._replace(latency_s=time.perf_counter() - t0)
        policy_keys = {
            (pod.namespace, pod.get_labels().get(TAS_POLICY_LABEL))
            for pod in pods
        }
        policies, view, host_only = self.mirror.policies_with_view(
            [key for key in policy_keys if key[1]]
        )
        rows: List[Tuple[Pod, int, int, int]] = []  # pod, row, op, current
        skipped = 0
        for pod in pods:
            compiled = policies.get(
                (pod.namespace, pod.get_labels().get(TAS_POLICY_LABEL))
            )
            current_idx = view.node_index.get(pod.spec_node_name)
            if (
                compiled is None
                or compiled.scheduleonmetric_row < 0
                or compiled.scheduleonmetric_metric in host_only
                or current_idx is None
            ):
                skipped += 1
                continue
            rows.append(
                (
                    pod,
                    compiled.scheduleonmetric_row,
                    compiled.scheduleonmetric_op,
                    current_idx,
                )
            )
        if not rows:
            return PlanResult(
                [], 0, skipped, 0, time.perf_counter() - t0, view.version
            )
        n_cap = view.node_capacity
        p = len(rows)
        p_pad = max(POD_PAD, -(-p // POD_PAD) * POD_PAD)
        metric_row = np.zeros(p_pad, dtype=np.int32)
        op_id = np.zeros(p_pad, dtype=np.int32)
        current = np.full(p_pad, -1, dtype=np.int32)
        active = np.zeros(p_pad, dtype=bool)
        for idx, (_pod, row, op, cur) in enumerate(rows):
            metric_row[idx], op_id[idx], current[idx] = row, op, cur
            active[idx] = True
        violating = np.zeros(n_cap, dtype=bool)
        for node in violations:
            node_idx = view.node_index.get(node)
            if node_idx is not None:
                violating[node_idx] = True
        capacity = self._capacity_vector(view, remaining_capacity, current, p)
        node_for_pod, adj = penalized_assign_kernel(
            view.values.hi,
            view.values.lo,
            view.present,
            jnp.asarray(metric_row),
            jnp.asarray(op_id),
            jnp.asarray(violating),
            jnp.asarray(current),
            jnp.asarray(capacity),
            jnp.asarray(active),
            jnp.float32(self.migration_cost),
            jnp.float32(self.violation_penalty),
            solver=self.solver,
        )
        assigned = np.asarray(node_for_pod)
        adj_np = np.asarray(adj)
        moves: List[Move] = []
        for idx, (pod, _row, _op, cur) in enumerate(rows):
            target = int(assigned[idx])
            if target < 0 or target == cur or target >= len(view.node_names):
                continue
            gain = float(adj_np[idx, target] - adj_np[idx, cur])
            if gain <= 0.0:
                continue  # solver contention artifact: staying is better
            moves.append(
                Move(
                    pod_key=object_key(pod),
                    namespace=pod.namespace,
                    name=pod.name,
                    from_node=pod.spec_node_name,
                    to_node=view.node_names[target],
                    gain=round(gain, 6),
                )
            )
        moves.sort(key=lambda m: (-m.gain, m.pod_key))
        deferred = 0
        if self.max_inflow is not None:
            # anti-herding (DEFAULT_MAX_INFLOW): keep only the
            # highest-gain ``max_inflow`` moves per destination; the
            # rest stay put this cycle and replan next cycle against
            # telemetry that already includes the landed pods.  Applied
            # host-side so the solvers' capacity semantics (sinkhorn's
            # column scaling in particular) are untouched.
            inflow: Dict[str, int] = {}
            spread: List[Move] = []
            for move in moves:
                landed = inflow.get(move.to_node, 0)
                if landed >= self.max_inflow:
                    deferred += 1
                    continue
                inflow[move.to_node] = landed + 1
                spread.append(move)
            if deferred:
                klog.v(4).info_s(
                    f"inflow cap: {deferred} moves deferred "
                    f"(max {self.max_inflow} per destination/cycle)",
                    component="rebalance",
                )
            moves = spread
        truncated = max(0, len(moves) - self.max_moves)
        if truncated:
            klog.v(4).info_s(
                f"churn budget: {truncated} moves dropped "
                f"(cap {self.max_moves})",
                component="rebalance",
            )
        moves = moves[: self.max_moves]
        return PlanResult(
            moves=moves,
            considered=p,
            skipped_pods=skipped,
            truncated=truncated,
            latency_s=time.perf_counter() - t0,
            view_version=view.version,
            deferred=deferred,
        )

    def _capacity_vector(
        self, view, remaining_capacity, current: np.ndarray, p: int
    ) -> np.ndarray:
        """int32 [N_cap] slots per interned node: caller-observed remaining
        capacity (or the kubelet default), plus each replanned pod's own
        slot at its current node so the stay-put assignment is feasible."""
        cap = np.full(view.node_capacity, self.default_node_capacity, dtype=np.int64)
        if remaining_capacity is not None:
            for name, idx in view.node_index.items():
                if idx < cap.shape[0]:
                    cap[idx] = remaining_capacity.get(
                        name, self.default_node_capacity
                    )
        cap = np.clip(cap, 0, None)
        for idx in current[:p]:
            if idx >= 0:
                cap[idx] += 1
        return np.clip(cap, 0, np.iinfo(np.int32).max).astype(np.int32)

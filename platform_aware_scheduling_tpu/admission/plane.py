"""The bounded, priority-ordered admission queue both front-ends consult.

The extender model is retry-driven: kube-scheduler re-runs Filter for a
pending pod until it passes, so the queue is a *gatekeeper over
retries*, not a dispatcher — it never holds a request open.  One
``review`` call per Filter decision classifies the outcome:

  * **Filter passed** — the gate decides whether this pod may actually
    take the capacity now.  Head-of-line order is (class, arrival);
    a pod behind a higher-priority waiter is held (every candidate
    fails with ``CODE_ADMISSION_BLOCKED``) unless **backfill** applies
    (the waiter's demand stays covered: it either already holds a gang
    reservation or enough eligible nodes remain after this admission)
    or **fairness** does (the streak class has taken ``fairness_streak``
    consecutive admissions while another class waits — the per-class cap
    that keeps batch work from starving forever).

  * **Filter failed, every reason capacity-class** (the queueable set in
    utils/decisions.py) — the pod enqueues (bounded depth: overflow
    sheds the worst-ranked entry, or the arrival itself when it ranks
    worst), its consult count ages toward the starvation threshold, and
    an infeasible *gang* above another class's holdings arms the
    preemption planner (preempt.py).

  * **Filter failed with any policy/error-class reason** — terminal:
    the queue never retries a ``dontschedule`` rejection; a queued entry
    that turns terminal is dropped.

Wire contract: the plane only ever *substitutes one failure for
another* (the admission-blocked hold) or passes the verdict through
untouched — it never invents an admit, so ``--admission=off`` responses
are byte-identical to a build without the plane.  All
``pas_admission_*`` families live in the plane's own CounterSet and
appear on /metrics only where a plane is wired — the off path registers
nothing.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from platform_aware_scheduling_tpu.gang.group import GangSpec
from platform_aware_scheduling_tpu.kube.objects import Pod
from platform_aware_scheduling_tpu.utils import decisions, events, klog
from platform_aware_scheduling_tpu.utils import labels as shared_labels
from platform_aware_scheduling_tpu.utils.tracing import CounterSet

#: class ladder, most important first (rank 0 outranks rank 1, ...)
DEFAULT_CLASSES = ("high", "normal", "batch")
DEFAULT_CLASS = "normal"
DEFAULT_MAX_DEPTH = 64
#: consecutive same-class admissions before a waiting other class must
#: be let through (the anti-starvation cap)
DEFAULT_FAIRNESS_STREAK = 8
#: queue consults after which every further consult counts as a
#: starvation event (the per-class availability SLO's bad signal)
DEFAULT_STARVE_CONSULTS = 16
#: bound on remembered gang -> class associations (preemption victim
#: classing); far above any live gang count, just an leak stop
_GANG_CLASS_CAP = 4096


def blocked_reason(klass: str, depth: int) -> str:
    """The Filter FailedNodes reason for an admission hold — one
    formatter so the wire string can never fork between front-ends."""
    return (
        f"admission: queued behind higher-priority work "
        f"(class={klass}, depth={depth})"
    )


class _Entry:
    """One queued pod (all access under the plane's lock)."""

    __slots__ = (
        "pod_key",
        "namespace",
        "name",
        "klass",
        "rank",
        "seq",
        "gang_id",
        "size",
        "enqueued_at",
        "consults",
    )

    def __init__(
        self,
        pod_key: str,
        namespace: str,
        name: str,
        klass: str,
        rank: int,
        seq: int,
        gang_id: Optional[str],
        size: int,
        now: float,
    ):
        self.pod_key = pod_key
        self.namespace = namespace
        self.name = name
        self.klass = klass
        self.rank = rank
        self.seq = seq
        self.gang_id = gang_id
        self.size = size
        self.enqueued_at = now
        self.consults = 0

    def order(self) -> Tuple[int, int]:
        return (self.rank, self.seq)

    def to_dict(self, now: float) -> Dict:
        return {
            "pod": self.pod_key,
            "class": self.klass,
            "seq": self.seq,
            "gang": self.gang_id,
            "size": self.size,
            "waiting_s": round(max(0.0, now - self.enqueued_at), 3),
            "consults": self.consults,
        }


class AdmissionPlane:
    """The admission gatekeeper: priority classes, the bounded queue,
    backfill and fairness, and the preemption trigger.

    Collaborators (set by assembly, all optional):

      * ``gangs`` — gang.GangTracker: reservation state for backfill's
        covered-demand check and (via the planner) preemption;
      * ``preemption`` — preempt.PreemptionPlanner (``--preemption=on``).
    """

    def __init__(
        self,
        classes: Sequence[str] = DEFAULT_CLASSES,
        default_class: str = DEFAULT_CLASS,
        max_depth: int = DEFAULT_MAX_DEPTH,
        fairness_streak: int = DEFAULT_FAIRNESS_STREAK,
        starve_consults: int = DEFAULT_STARVE_CONSULTS,
        clock: Callable[[], float] = time.monotonic,
        decision_log: Optional[decisions.DecisionLog] = None,
    ):
        self.classes = tuple(classes)
        if len(self.classes) < 1 or len(set(self.classes)) != len(
            self.classes
        ):
            raise ValueError(f"malformed class ladder: {classes!r}")
        if default_class not in self.classes:
            raise ValueError(
                f"default class {default_class!r} not in {self.classes}"
            )
        self.default_class = default_class
        self._rank = {name: i for i, name in enumerate(self.classes)}
        self.max_depth = max(1, int(max_depth))
        self.fairness_streak = max(1, int(fairness_streak))
        self.starve_consults = max(1, int(starve_consults))
        self._clock = clock
        self.decision_log = (
            decision_log if decision_log is not None else decisions.DECISIONS
        )
        self.counters = CounterSet()
        self.gangs = None  # gang.GangTracker (assembly, --gang=on)
        self.preemption = None  # PreemptionPlanner (--preemption=on)
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}
        self._seq = 0
        # fairness streak: which class took the last admission and how
        # many it has taken consecutively
        self._streak_class: Optional[str] = None
        self._streak = 0
        # gang id -> class name, learned from member pods: the
        # preemption planner's victim census classes gangs through this
        self._gang_class: Dict[str, str] = {}

    # -- classification --------------------------------------------------------

    def classify(self, pod: Pod) -> Tuple[str, int]:
        """(class name, rank) for a pod; unlabeled or unknown-class pods
        take the default class (utils/labels.priority_class_for is the
        single validator)."""
        klass = shared_labels.priority_class_for(
            pod.get_labels(), self._rank
        )
        if klass is None:
            klass = self.default_class
        return klass, self._rank[klass]

    def rank_of_gang(self, gang_id: str) -> int:
        """The remembered class rank of a gang (victim census); a gang
        the plane never saw a member of takes the default class."""
        with self._lock:
            klass = self._gang_class.get(gang_id, self.default_class)
        return self._rank.get(klass, self._rank[self.default_class])

    def class_of_gang(self, gang_id: str) -> str:
        with self._lock:
            return self._gang_class.get(gang_id, self.default_class)

    def _note_gang_class(self, gang_id: Optional[str], klass: str) -> None:
        if gang_id is None:
            return
        with self._lock:
            if len(self._gang_class) >= _GANG_CLASS_CAP:
                self._gang_class.clear()  # crude, bounded, never wrong
            self._gang_class[gang_id] = klass

    # -- the consult -----------------------------------------------------------

    def review(
        self,
        pod: Pod,
        candidates: List[str],
        failed: Dict[str, str],
        codes: Dict[str, int],
        request_id: str = "",
    ) -> Optional[Tuple[Dict[str, str], Dict[str, int]]]:
        """One Filter decision through the gate (module doc).  Returns
        None when the verdict stands, or a replacement ``(failed,
        codes)`` pair failing every candidate when the pod is held.
        Never turns a failure into an admit.  ``request_id`` is the
        consulting Filter span's id — carried into provenance records
        and causal-spine events so the decision joins to its span."""
        spec = GangSpec.from_pod(pod)
        klass, rank = self.classify(pod)
        self._note_gang_class(
            spec.gang_id if spec is not None else None, klass
        )
        pod_key = f"{pod.namespace}/{pod.name}"
        size = spec.size if spec is not None else 1
        eligible = [name for name in candidates if name not in failed]
        if eligible:
            return self._gate(
                pod, pod_key, klass, rank, size, eligible, request_id
            )
        return self._capacity_miss(
            pod, pod_key, spec, klass, rank, size, candidates, codes,
            request_id,
        )

    def _gate(
        self,
        pod: Pod,
        pod_key: str,
        klass: str,
        rank: int,
        size: int,
        eligible: List[str],
        request_id: str = "",
    ) -> Optional[Tuple[Dict[str, str], Dict[str, int]]]:
        """Filter passed: may the pod take the capacity now?"""
        now = self._clock()
        with self._lock:
            entry = self._entries.get(pod_key)
            my_order = entry.order() if entry is not None else (rank, 1 << 60)
            blockers = [
                e
                for e in self._entries.values()
                if e.pod_key != pod_key and e.order() < my_order
            ]
            if not blockers:
                self._admit_locked(
                    pod_key, klass, event=None, request_id=request_id
                )
                return None
            # fairness: the streak class has monopolized admissions while
            # other classes wait — let this one through and reset
            if (
                self._streak_class is not None
                and self._streak_class != klass
                and self._streak >= self.fairness_streak
            ):
                self._admit_locked(
                    pod_key, klass, event="fairness", request_id=request_id
                )
                return None
            # backfill: admitting this pod must leave the head waiter's
            # demand covered — either the head already holds its slice
            # (gang reservation: the overlay protects it from this pod's
            # eligible set entirely), or enough eligible nodes remain
            head = min(blockers, key=lambda e: e.order())
            head_unmet = head.size
            if head.gang_id is not None and self.gangs is not None:
                state = self.gangs.gang_state(head.gang_id)
                if state in ("reserved", "bound", "draining"):
                    head_unmet = 0
            if len(eligible) - head_unmet >= size:
                self._admit_locked(
                    pod_key, klass, event="backfill", request_id=request_id
                )
                return None
            self.counters.inc(
                "pas_admission_blocked_total", labels={"class": klass}
            )
            newly_queued = entry is None
            if newly_queued:
                # it must wait its turn: enqueue so its arrival order is
                # pinned from THIS consult, not a later retry
                self._enqueue_locked(pod, pod_key, klass, rank, size, now)
            depth = len(self._entries)
            head_class = head.klass
        if newly_queued:
            events.JOURNAL.publish(
                "admission",
                "enqueue",
                request_id=request_id,
                pod=pod_key,
                data={"class": klass, "depth": depth},
            )
        events.JOURNAL.publish(
            "admission",
            "blocked",
            request_id=request_id,
            pod=pod_key,
            data={"class": klass, "head_class": head_class, "depth": depth},
        )
        failed = {
            name: blocked_reason(head_class, depth) for name in eligible
        }
        codes = {
            name: decisions.CODE_ADMISSION_BLOCKED for name in eligible
        }
        return failed, codes

    def _capacity_miss(
        self,
        pod: Pod,
        pod_key: str,
        spec: Optional[GangSpec],
        klass: str,
        rank: int,
        size: int,
        candidates: List[str],
        codes: Dict[str, int],
        request_id: str = "",
    ) -> None:
        """Filter failed everywhere: enqueue if (and only if) every
        reason is capacity-class."""
        reason_counts: Dict[int, int] = {}
        for code in codes.values():
            reason_counts[code] = reason_counts.get(code, 0) + 1
        queueable = candidates and decisions.queueable_counts(reason_counts)
        arm_preemption = False
        starved = False
        gang = spec.gang_id if spec is not None else ""
        with self._lock:
            entry = self._entries.get(pod_key)
            if not queueable:
                if entry is not None:
                    # a queued pod whose failure turned terminal (policy
                    # now rejects it) leaves: the queue never retries a
                    # dontschedule rejection
                    del self._entries[pod_key]
                    self.counters.inc(
                        "pas_admission_rejected_total",
                        labels={"class": entry.klass, "reason": "terminal"},
                    )
                    self._publish_depth_locked()
                    detail = {
                        "pod": pod_key,
                        "event": "terminal",
                        "class": entry.klass,
                        "request_id": request_id,
                    }
                else:
                    detail = None
            elif entry is not None:
                entry.consults += 1
                if entry.consults >= self.starve_consults:
                    # every consult past the threshold is one starvation
                    # event — the bad half of the class availability SLO
                    self.counters.inc(
                        "pas_admission_starved_total",
                        labels={"class": klass},
                    )
                    starved = True
                arm_preemption = (
                    spec is not None and self.preemption is not None
                )
                detail = None
            else:
                shed = self._make_room_locked(rank)
                if shed is False:
                    # the queue is full of equal-or-better work: this
                    # arrival is the one that sheds
                    self.counters.inc(
                        "pas_admission_rejected_total",
                        labels={"class": klass, "reason": "overflow"},
                    )
                    detail = {
                        "pod": pod_key,
                        "event": "overflow_shed",
                        "class": klass,
                        "request_id": request_id,
                    }
                else:
                    self._enqueue_locked(
                        pod, pod_key, klass, rank, size, self._clock()
                    )
                    arm_preemption = (
                        spec is not None and self.preemption is not None
                    )
                    detail = {
                        "pod": pod_key,
                        "event": "enqueue",
                        "class": klass,
                        "depth": len(self._entries),
                        "request_id": request_id,
                    }
                    if isinstance(shed, _Entry):
                        detail["shed"] = shed.pod_key
        if starved:
            events.JOURNAL.publish(
                "admission",
                "starved",
                request_id=request_id,
                pod=pod_key,
                gang=gang,
                data={"class": klass},
            )
        if detail is not None:
            if self.decision_log is not None:
                self.decision_log.record_admission(detail)
            events.JOURNAL.publish(
                "admission",
                str(detail["event"]),
                request_id=request_id,
                pod=pod_key,
                gang=gang,
                data={
                    k: v
                    for k, v in detail.items()
                    if k not in ("pod", "event", "request_id")
                },
            )
        if arm_preemption:
            # planning runs OUTSIDE the plane lock: it walks the gang
            # tracker and may call the cluster through the actuator
            self.preemption.maybe_preempt(
                pod, klass, rank, request_id=request_id
            )
        return None

    # -- queue internals (under the lock) --------------------------------------

    def _enqueue_locked(
        self,
        pod: Pod,
        pod_key: str,
        klass: str,
        rank: int,
        size: int,
        now: float,
    ) -> _Entry:
        self._seq += 1
        spec = GangSpec.from_pod(pod)
        entry = _Entry(
            pod_key=pod_key,
            namespace=pod.namespace,
            name=pod.name,
            klass=klass,
            rank=rank,
            seq=self._seq,
            gang_id=spec.gang_id if spec is not None else None,
            size=size,
            now=now,
        )
        self._entries[pod_key] = entry
        self.counters.inc(
            "pas_admission_queued_total", labels={"class": klass}
        )
        self._publish_depth_locked()
        return entry

    def _make_room_locked(self, rank: int):
        """Bounded depth: True when room exists, the shed _Entry when a
        worse-ranked entry was dropped to make room, False when the
        arrival itself should shed."""
        if len(self._entries) < self.max_depth:
            return True
        worst = max(self._entries.values(), key=lambda e: e.order())
        if worst.rank <= rank:
            return False
        del self._entries[worst.pod_key]
        self.counters.inc(
            "pas_admission_rejected_total",
            labels={"class": worst.klass, "reason": "overflow"},
        )
        klog.v(1).info_s(
            f"admission queue full: shed {worst.pod_key} "
            f"(class={worst.klass}) for a class-rank-{rank} arrival",
            component="admission",
        )
        return worst

    def _admit_locked(
        self,
        pod_key: str,
        klass: str,
        event: Optional[str],
        request_id: str = "",
    ) -> None:
        entry = self._entries.pop(pod_key, None)
        if entry is not None:
            self._publish_depth_locked()
        self.counters.inc(
            "pas_admission_admitted_total", labels={"class": klass}
        )
        if event == "backfill":
            self.counters.inc(
                "pas_admission_backfill_total", labels={"class": klass}
            )
        if self._streak_class == klass:
            self._streak += 1
        else:
            self._streak_class = klass
            self._streak = 1
        if event is not None and self.decision_log is not None:
            self.decision_log.record_admission(
                {
                    "pod": pod_key,
                    "event": event,
                    "class": klass,
                    "request_id": request_id,
                }
            )
        # the journal publish is one short lock + a deque append — the
        # same weight as the record_admission above, safe under the
        # plane lock (the journal never calls back into the plane)
        events.JOURNAL.publish(
            "admission",
            event or "admit",
            request_id=request_id,
            pod=pod_key,
            gang=entry.gang_id if entry is not None and entry.gang_id else "",
            data={"class": klass, "waited": entry is not None},
        )

    def _publish_depth_locked(self) -> None:
        depths = {name: 0 for name in self.classes}
        for entry in self._entries.values():
            depths[entry.klass] = depths.get(entry.klass, 0) + 1
        for name, depth in depths.items():
            self.counters.set_gauge(
                "pas_admission_queue_depth",
                float(depth),
                labels={"class": name},
            )

    # -- outcome feedback ------------------------------------------------------

    def observe_bind(self, namespace: str, name: str) -> None:
        """A pod landed: whatever the queue thought about it is moot."""
        with self._lock:
            if self._entries.pop(f"{namespace}/{name}", None) is not None:
                self._publish_depth_locked()

    # -- the debug surface -----------------------------------------------------

    def snapshot(self) -> Dict:
        now = self._clock()
        with self._lock:
            entries = sorted(
                self._entries.values(), key=lambda e: e.order()
            )
            out = {
                "enabled": True,
                "classes": list(self.classes),
                "default_class": self.default_class,
                "max_depth": self.max_depth,
                "fairness_streak": self.fairness_streak,
                "starve_consults": self.starve_consults,
                "depth": len(entries),
                "streak": {
                    "class": self._streak_class,
                    "count": self._streak,
                },
                "queue": [e.to_dict(now) for e in entries],
            }
        out["preemption"] = (
            self.preemption.snapshot() if self.preemption is not None else None
        )
        # cumulative totals (summed over classes), so one /debug/admission
        # read answers "has this plane ever queued/blocked/preempted?"
        # without a /metrics scrape — the twin's quiet-day pin reads these
        get = self.counters.get
        out["counters"] = {
            "queued": get("pas_admission_queued_total", kind="counter"),
            "admitted": get("pas_admission_admitted_total", kind="counter"),
            "blocked": get("pas_admission_blocked_total", kind="counter"),
            "backfills": get("pas_admission_backfill_total", kind="counter"),
            "starved": get("pas_admission_starved_total", kind="counter"),
            "rejected": get("pas_admission_rejected_total", kind="counter"),
            "preemptions": get(
                "pas_preemption_reservations_total", kind="counter"
            ),
        }
        return out

    def to_json(self) -> bytes:
        return json.dumps(self.snapshot()).encode() + b"\n"

"""Gang-aware preemption: the admission plane's sharp edge.

When a higher-priority gang is infeasible for capacity reasons, the
planner selects the **cheapest set of lower-priority victims** — whole
gangs only, never equal-or-higher class — whose release makes the
target's demand feasible, evicts them all-or-nothing through the
``SafeActuator``'s atomic gang path (fencing-token re-verification per
eviction, breaker-gated kube client, token-bucket rate limit), and
**reserves the freed slice for the target before the victims finish
draining** (GangTracker.reserve_slice over the DRAINING holds), so the
hole can never be observed free by third parties.

Safety argument, in gate order:

  1. **leader-only** — only the replica holding the lease plans or
     actuates (a standby planning against its own ledger could pick
     different victims);
  2. **never equal-or-higher** — the victim pool is strictly
     lower-ranked gangs; two same-class gangs can never preempt each
     other into a livelock;
  3. **whole gangs only** — victims come from the tracker's census and
     are evicted via the atomic gang verb; a partial refusal (pdb,
     fencing, rate) aborts the rest of the plan and, critically,
     **creates no reservation**: nothing is ever admitted on the back
     of a half-executed plan (fenced-refusal containment);
  4. **bounded appetite** — at most ``max_victims`` pods per plan, the
     BudgetController's preemption-aggressiveness knob
     (utils/control.attach_preemption): sustained availability burn in
     the victim classes steps the ceiling down.

Every executed preemption lands a provenance record
(DecisionLog.record_preemption) naming target, victims, and the
reserved slice.  All ``pas_preemption_*`` families live in the
admission plane's CounterSet — the off path registers nothing.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from platform_aware_scheduling_tpu.gang.group import GangSpec
from platform_aware_scheduling_tpu.kube.objects import Pod
from platform_aware_scheduling_tpu.ops import topology
from platform_aware_scheduling_tpu.utils import events, klog

DEFAULT_MAX_VICTIMS = 8
#: minimum seconds between plans for the SAME target gang — the retry
#: loop re-consults every Filter; replanning each time would hammer the
#: census and the actuator gates for a target that just got refused
DEFAULT_RETRY_S = 5.0


class PreemptionPlanner:
    """Victim selection + atomic execution for one admission plane.

    ``plane`` supplies class ranks (its single classifier) and the
    CounterSet; ``tracker`` is the gang ledger (census, feasibility
    what-ifs, reservation-while-draining); ``actuator`` the SafeActuator
    whose ``preempt_gang`` verb does the evicting."""

    def __init__(
        self,
        plane,
        tracker,
        actuator,
        max_victims: int = DEFAULT_MAX_VICTIMS,
        retry_s: float = DEFAULT_RETRY_S,
        leadership=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.plane = plane
        self.tracker = tracker
        self.actuator = actuator
        self.max_victims = max(1, int(max_victims))
        self.retry_s = float(retry_s)
        self.leadership = leadership
        self._clock = clock
        self._lock = threading.Lock()
        self._last_attempt: Dict[str, float] = {}  # target gang -> stamp
        self._plans = 0
        self._last_plan: Optional[Dict] = None

    @property
    def counters(self):
        return self.plane.counters

    # -- trigger ---------------------------------------------------------------

    def maybe_preempt(
        self, pod: Pod, klass: str, rank: int, request_id: str = ""
    ) -> bool:
        """Plan-and-execute for one starving gang pod; True when a
        preemption fully executed and the slice is reserved.
        ``request_id`` is the triggering Filter span's id, carried into
        the provenance record and causal-spine events."""
        spec = GangSpec.from_pod(pod)
        if spec is None:
            return False
        now = self._clock()
        with self._lock:
            last = self._last_attempt.get(spec.gang_id)
            if last is not None and (now - last) < self.retry_s:
                return False
            self._last_attempt[spec.gang_id] = now
            if len(self._last_attempt) > 4096:
                self._last_attempt = {spec.gang_id: now}
        if self.leadership is not None and not self.leadership.is_leader():
            self._outcome("not_leader")
            return False
        target_state = self.tracker.gang_state(spec.gang_id)
        if target_state in ("reserved", "bound", "draining"):
            # already holds (or is itself being preempted): nothing to do
            return False
        plan = self._plan(spec, rank)
        if plan is None:
            self._outcome("infeasible")
            return False
        victims, nodes, anchor = plan
        return self._execute(
            pod, spec, klass, victims, nodes, anchor, request_id
        )

    # -- victim selection ------------------------------------------------------

    def _plan(
        self, spec: GangSpec, rank: int
    ) -> Optional[Tuple[List[Dict], List[str], Optional[tuple]]]:
        """The cheapest strictly-lower-class victim set that makes
        ``spec`` feasible, or None.  Greedy add (lowest class first,
        fewest pods) to feasibility, then reverse-prune — small, exact
        enough, and O(victims^2) over a census that is already tiny."""
        census = self.tracker.preemption_census()
        pool = [
            c
            for c in census
            if c["gang"] != spec.gang_id
            and self.plane.rank_of_gang(c["gang"]) > rank
        ]
        if not pool:
            return None
        pool.sort(
            key=lambda c: (
                -self.plane.rank_of_gang(c["gang"]),
                len(c["members"]) or c["size"],
                c["gang"],
            )
        )
        mesh = self.tracker.mesh()
        held = self.tracker.reserved_nodes()
        chosen: List[Dict] = []
        freed: set = set()
        feasible = None
        for candidate in pool:
            chosen.append(candidate)
            freed.update(candidate["nodes"])
            feasible = self._feasible(spec, mesh, held, freed)
            if feasible is not None:
                break
        if feasible is None:
            return None
        # reverse-prune: drop any victim whose nodes turn out unneeded
        # (greedy may have added a cheap gang that the final anchor
        # doesn't touch)
        for candidate in list(reversed(chosen[:-1])):
            trial = freed - set(candidate["nodes"])
            result = self._feasible(spec, mesh, held, trial)
            if result is not None:
                chosen.remove(candidate)
                freed = trial
                feasible = result
        victim_pods = sum(
            len(c["members"]) or c["size"] for c in chosen
        )
        if victim_pods > self.max_victims:
            self._outcome("over_budget")
            return None
        nodes, anchor = feasible
        return chosen, nodes, anchor

    def _feasible(
        self,
        spec: GangSpec,
        mesh,
        held: Dict[str, str],
        freed: set,
    ) -> Optional[Tuple[List[str], Optional[tuple]]]:
        """Would ``spec`` place if ``freed`` nodes returned to the pool?
        Returns (slice nodes, anchor) or None — the same solve shape as
        GangTracker._try_reserve_locked, run as a what-if."""
        if spec.topology is None:
            try:
                names = {n.name for n in self.tracker.nodes_provider()}
            except Exception:
                return None
            free = sorted(
                name
                for name in names
                if name not in held or name in freed
            )
            if len(free) < spec.size:
                return None
            return free[: spec.size], None
        if mesh is None or len(mesh) == 0:
            return None
        free_names = [
            name
            for name in mesh.coord_of
            if name not in held or name in freed
        ]
        free_mask = mesh.free_mask(free_names)
        h, w = spec.topology
        best = None
        for idx, (hh, ww) in enumerate(
            [(h, w)] if h == w else [(h, w), (w, h)]
        ):
            feas = topology.topology_feasibility(
                free_mask, hh, ww, use_device=self.tracker.use_device
            )
            anchor = topology.best_anchor(feas)
            if anchor is None:
                continue
            i, j, score = anchor
            key = (score, idx, i, j)
            if best is None or key < best[0]:
                best = (key, i, j, hh, ww)
        if best is None:
            return None
        _, i, j, hh, ww = best
        names = mesh.names_for(topology.slice_cells(i, j, hh, ww))
        if names is None:
            return None
        return names, (i, j, hh, ww)

    # -- execution -------------------------------------------------------------

    def _execute(
        self,
        pod: Pod,
        spec: GangSpec,
        klass: str,
        victims: List[Dict],
        nodes: List[str],
        anchor: Optional[tuple],
        request_id: str = "",
    ) -> bool:
        pods_by_key = self._live_pods()
        if pods_by_key is None:
            self._outcome("no_pod_view")
            return False
        executed: List[Dict] = []
        for victim in victims:
            members = victim["members"]
            pods = [
                pods_by_key[key] for key in members if key in pods_by_key
            ]
            if not pods:
                # every member already gone: the sweep will release it;
                # treat as drained and move on
                self.tracker.mark_draining(victim["gang"])
                executed.append(victim)
                continue
            fully, _result = self.actuator.preempt_gang(
                victim["gang"], pods, counters=self.counters
            )
            if not fully:
                # containment: a refused victim (fencing moved, pdb,
                # rate, dry-run) aborts the remaining plan and creates
                # NO reservation — already-drained victims free up
                # capacity the normal retry loop will use, but nothing
                # is admitted on the back of a half-executed plan
                self._outcome("actuation_refused")
                klog.v(1).info_s(
                    f"preemption for gang {spec.gang_id} aborted at "
                    f"victim {victim['gang']} (refused); no reservation "
                    f"created",
                    component="admission",
                )
                return False
            self.tracker.mark_draining(victim["gang"])
            executed.append(victim)
        if not self.tracker.reserve_slice(pod, nodes, anchor):
            self._outcome("reserve_failed")
            return False
        self.counters.inc("pas_preemption_reservations_total")
        self.counters.inc(
            "pas_preemption_victim_gangs_total", len(executed)
        )
        target = f"{pod.namespace}/{pod.name}"
        detail = {
            "target": target,
            "target_gang": spec.gang_id,
            "class": klass,
            "outcome": "planned",
            "request_id": request_id,
            "victims": [
                {
                    "gang": v["gang"],
                    "class": self.plane.class_of_gang(v["gang"]),
                    "pods": len(v["members"]) or v["size"],
                }
                for v in executed
            ],
            "reserved_nodes": list(nodes),
            "anchor": list(anchor) if anchor is not None else None,
        }
        self._outcome("planned", detail)
        if self.plane.decision_log is not None:
            self.plane.decision_log.record_preemption(detail)
        events.JOURNAL.publish(
            "preemption",
            "planned",
            request_id=request_id,
            pod=target,
            gang=spec.gang_id or "",
            data={
                "class": klass,
                "victims": [v["gang"] for v in detail["victims"]],
            },
        )
        for victim in detail["victims"]:
            events.JOURNAL.publish(
                "preemption",
                "victim evicted",
                request_id=request_id,
                pod=target,
                gang=victim["gang"],
                data={"class": victim["class"], "pods": victim["pods"]},
            )
        events.JOURNAL.publish(
            "preemption",
            "slice reserved",
            request_id=request_id,
            pod=target,
            gang=spec.gang_id or "",
            data={"nodes": len(nodes)},
        )
        klog.v(1).info_s(
            f"preempted {len(executed)} gang(s) for {spec.gang_id} "
            f"(class={klass}); slice reserved while victims drain",
            component="admission",
        )
        return True

    def _live_pods(self) -> Optional[Dict[str, Pod]]:
        provider = getattr(self.tracker, "pods_provider", None)
        if provider is None:
            return None
        try:
            return {
                f"{p.namespace}/{p.name}": p
                for p in provider()
                if p.phase not in ("Succeeded", "Failed")
                and p.deletion_timestamp is None
            }
        except Exception as exc:
            klog.error("preemption pod list failed: %s", exc)
            return None

    def _outcome(self, outcome: str, detail: Optional[Dict] = None) -> None:
        self.counters.inc(
            "pas_preemption_plans_total", labels={"outcome": outcome}
        )
        with self._lock:
            self._plans += 1
            self._last_plan = detail if detail is not None else {
                "outcome": outcome
            }

    # -- the debug surface -----------------------------------------------------

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "enabled": True,
                "max_victims": self.max_victims,
                "retry_s": self.retry_s,
                "plans": self._plans,
                "last_plan": self._last_plan,
            }

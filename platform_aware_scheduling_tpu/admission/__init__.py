"""Priority-aware admission plane (docs/admission.md).

Everything the extender did before this package was admit-or-reject at
Filter time.  The admission plane adds the third answer — *wait, in
order*: a bounded queue over capacity-class Filter failures, priority
classes from the ``pas-priority`` pod label, backfill so small work
flows around a large gang's pending reservation, per-class fairness so
batch work cannot starve forever, and gang-atomic preemption so a
high-priority gang can displace lower-priority work through the
``SafeActuator``'s fenced, breaker-gated eviction path.

``AdmissionPlane`` (plane.py) is the opt-in collaborator both
front-ends consult (``--admission=on``); ``PreemptionPlanner``
(preempt.py) is its optional sharp edge (``--preemption=on``, requires
``--gang=on``).  The off path constructs neither and stays
byte-identical on the wire.
"""

from platform_aware_scheduling_tpu.admission.plane import (  # noqa: F401
    DEFAULT_CLASSES,
    DEFAULT_CLASS,
    AdmissionPlane,
    blocked_reason,
)
from platform_aware_scheduling_tpu.admission.preempt import (  # noqa: F401
    PreemptionPlanner,
)

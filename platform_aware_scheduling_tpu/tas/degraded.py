"""Degraded-mode policy: what every telemetry consumer does when the
remote dependencies misbehave (docs/robustness.md).

PR 3 made telemetry staleness *visible* (``AutoUpdatingCache.
telemetry_freshness``) and PR 4 added an actuator that *evicts real
pods*; this controller is the strategy between them.  It consumes the
freshness signal and the circuit-breaker states (kube/retry.py) and
answers three questions, one per consumer:

  * ``filter_decision`` — dontschedule/Filter: ``--degradedMode``
    decides between ``fail_open`` (stop filtering: every candidate
    passes — capacity over precision), ``fail_closed`` (every candidate
    fails — precision over capacity), and ``last-known-good`` (keep
    serving the cache's retained values while their age stays within a
    bounded multiple of the freshness bound, then fail open);
  * ``prioritize_decision`` — scheduleonmetric is NOT flag-driven: it
    serves last-known-good scores within the bounded age and degrades to
    NEUTRAL priorities (every node scored equally) past it.  A stale
    ranking mis-orders placements; a neutral one just stops helping;
  * ``evictions_allowed`` — the HARD invariant, not configurable: the
    deschedule labeler and the PR 4 rebalancer suspend ALL evictions
    whenever telemetry is degraded or the kube circuit is not closed.
    Eviction is the one action that destroys work; it never runs on
    data we cannot trust or against an API server we cannot see.

Degraded state surfaces three ways: the ``pas_degraded{subsystem}``
gauge family, a named ``/readyz`` condition (the service keeps serving,
but reports why it is not fully ready), and the rebalance status JSON.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from platform_aware_scheduling_tpu.kube.retry import (
    GROUP_KUBE,
    GROUP_METRICS,
    STATE_CLOSED,
)
from platform_aware_scheduling_tpu.utils import trace
from platform_aware_scheduling_tpu.utils.tracing import CounterSet

MODE_FAIL_OPEN = "fail-open"
MODE_FAIL_CLOSED = "fail-closed"
MODE_LAST_KNOWN_GOOD = "last-known-good"
MODES = (MODE_FAIL_OPEN, MODE_FAIL_CLOSED, MODE_LAST_KNOWN_GOOD)

#: last-known-good values stay servable this many freshness bounds past
#: freshness loss (with the default 3x-period bound: 3x3 = 9 periods)
DEFAULT_LKG_BOUND_MULTIPLE = 3.0

ACTION_NORMAL = "normal"
ACTION_LAST_KNOWN_GOOD = "last_known_good"
ACTION_NEUTRAL = "neutral"
ACTION_FAIL_OPEN = "fail_open"
ACTION_FAIL_CLOSED = "fail_closed"


class DegradedModeController:
    """One per assembled service; attached to the extender (verbs), the
    enforcer (deschedule labeling), and the rebalancer (actuation)."""

    def __init__(
        self,
        cache=None,
        breakers=None,
        mode: str = MODE_LAST_KNOWN_GOOD,
        lkg_max_age_s: Optional[float] = None,
        counters: Optional[CounterSet] = None,
    ):
        if mode not in MODES:
            raise ValueError(f"unknown degraded mode {mode!r}")
        self.cache = cache
        self.breakers = breakers  # CircuitBreakerRegistry or None
        self.mode = mode
        #: explicit last-known-good age bound; None derives it from the
        #: cache's freshness bound x lkg_bound_multiple
        self.lkg_max_age_s = lkg_max_age_s
        #: how many freshness bounds past staleness LKG answers stay
        #: servable — the budget controller tightens this toward 1.0
        #: when the freshness error budget is spent (utils/control.py)
        self.lkg_bound_multiple = DEFAULT_LKG_BOUND_MULTIPLE
        self.counters = counters if counters is not None else trace.COUNTERS
        self._lock = threading.Lock()
        # optional forecast.Forecaster (docs/forecast.md): while telemetry
        # is stale PAST the frozen-LKG window, last-known-good mode keeps
        # serving under *bounded extrapolation* — Prioritize ranks on the
        # grown-horizon predictions themselves, Filter keeps its
        # last-known-good threshold VERDICTS alive (the forecast gates
        # how long they stand, it does not re-evaluate the rules) —
        # until the widening uncertainty band exceeds its bound, then
        # falls back to today's frozen-LKG/neutral behavior.  The
        # eviction suspension is NOT relaxed: extrapolation serves
        # verbs, never actuation.
        self.forecaster = None

    # -- inputs ----------------------------------------------------------------

    def _circuit_state(self, group: str) -> str:
        if self.breakers is None:
            return STATE_CLOSED
        return self.breakers.states().get(group, STATE_CLOSED)

    def telemetry_status(self) -> Tuple[bool, str]:
        """(healthy, reason): telemetry counts as degraded when the cache
        reports staleness OR the metrics-API circuit is not closed (an
        open metrics circuit means refreshes are being refused — the
        values WILL go stale; act before they mislead)."""
        if self.cache is not None:
            fresh, reason = self.cache.telemetry_freshness()
            if not fresh:
                return False, f"telemetry stale: {reason}"
        state = self._circuit_state(GROUP_METRICS)
        if state != STATE_CLOSED:
            return False, f"metrics-API circuit {state}"
        return True, "telemetry fresh"

    def kube_status(self) -> Tuple[bool, str]:
        state = self._circuit_state(GROUP_KUBE)
        if state != STATE_CLOSED:
            return False, f"kube-API circuit {state}"
        return True, "kube API reachable"

    def _lkg_bound(self) -> Optional[float]:
        if self.lkg_max_age_s is not None:
            return self.lkg_max_age_s
        bound = None
        if self.cache is not None:
            bound = self.cache.freshness_bound()
        if bound is None:
            return None
        return bound * self.lkg_bound_multiple

    def _within_lkg_bound(self) -> bool:
        """Every registered metric still has retained data younger than
        the last-known-good bound."""
        if self.cache is None:
            return False
        bound = self._lkg_bound()
        if bound is None:
            return False
        ages = self.cache.metric_ages()
        if not ages:
            return False
        return all(age is not None and age <= bound for age in ages.values())

    def _extrapolation_ok(self) -> Tuple[bool, str]:
        """May a forecaster carry this consumer past the frozen-LKG
        window?  The band check is the forecaster's (it widens with
        extrapolation distance, so a long outage always trips back);
        any trouble fails closed to the pre-forecast behavior."""
        if self.forecaster is None:
            return False, ""
        try:
            return self.forecaster.extrapolation_ok()
        except Exception:
            return False, "forecast extrapolation check failed"

    # -- the three consumer answers --------------------------------------------

    def filter_decision(self) -> Tuple[str, str]:
        """dontschedule/Filter behavior right now: ``normal`` when
        telemetry is healthy, else per ``--degradedMode``.  In
        last-known-good mode a wired forecaster extends the LKG window
        with bounded extrapolation (docs/forecast.md)."""
        ok, reason = self.telemetry_status()
        if ok:
            self._publish(telemetry=False)
            return ACTION_NORMAL, reason
        self._publish(telemetry=True)
        if self.mode == MODE_FAIL_CLOSED:
            return ACTION_FAIL_CLOSED, reason
        if self.mode == MODE_LAST_KNOWN_GOOD:
            if self._within_lkg_bound():
                return ACTION_LAST_KNOWN_GOOD, reason
            extrapolate, band_reason = self._extrapolation_ok()
            if extrapolate:
                self.forecaster.count_extrapolated_serve()
                return ACTION_LAST_KNOWN_GOOD, (
                    f"{reason}; extrapolating: {band_reason}"
                )
        return ACTION_FAIL_OPEN, reason

    def prioritize_decision(self) -> Tuple[str, str]:
        """scheduleonmetric behavior right now (mode-independent):
        last-known-good scores within the bounded age, then bounded
        forecast extrapolation while the uncertainty band holds, then
        neutral."""
        ok, reason = self.telemetry_status()
        if ok:
            self._publish(telemetry=False)
            return ACTION_NORMAL, reason
        self._publish(telemetry=True)
        if self._within_lkg_bound():
            return ACTION_LAST_KNOWN_GOOD, reason
        extrapolate, band_reason = self._extrapolation_ok()
        if extrapolate:
            self.forecaster.count_extrapolated_serve()
            return ACTION_LAST_KNOWN_GOOD, (
                f"{reason}; extrapolating: {band_reason}"
            )
        return ACTION_NEUTRAL, reason

    def evictions_allowed(self) -> Tuple[bool, str]:
        """The hard invariant: no eviction while telemetry is degraded
        or the kube circuit is not closed.  Not configurable."""
        telemetry_ok, telemetry_reason = self.telemetry_status()
        kube_ok, kube_reason = self.kube_status()
        allowed = telemetry_ok and kube_ok
        reasons = [
            r
            for ok, r in (
                (telemetry_ok, telemetry_reason),
                (kube_ok, kube_reason),
            )
            if not ok
        ]
        self._publish(
            telemetry=not telemetry_ok,
            kube=not kube_ok,
            evictions=not allowed,
        )
        if allowed:
            return True, "telemetry fresh, kube circuit closed"
        return False, "evictions suspended: " + "; ".join(reasons)

    # -- surfaces --------------------------------------------------------------

    def degraded_subsystems(self) -> List[str]:
        out = []
        if not self.telemetry_status()[0]:
            out.append("telemetry")
        if self._circuit_state(GROUP_METRICS) != STATE_CLOSED:
            out.append("metrics_api")
        if not self.kube_status()[0]:
            out.append("kube_api")
        if not self.evictions_allowed()[0]:
            out.append("evictions")
        return out

    def readiness_condition(self) -> Tuple[bool, str]:
        """The /readyz "degraded_mode" condition: the process keeps
        serving while degraded, but /readyz reports WHY it is not fully
        ready so rollouts and dashboards see the outage."""
        telemetry_ok, telemetry_reason = self.telemetry_status()
        kube_ok, kube_reason = self.kube_status()
        if telemetry_ok and kube_ok:
            return True, f"not degraded (mode {self.mode})"
        reasons = [
            r
            for ok, r in (
                (telemetry_ok, telemetry_reason),
                (kube_ok, kube_reason),
            )
            if not ok
        ]
        filter_action, _ = self.filter_decision()
        prioritize_action, _ = self.prioritize_decision()
        return False, (
            f"degraded ({'; '.join(reasons)}); filter={filter_action}, "
            f"prioritize={prioritize_action}, evictions=suspended"
        )

    def status(self) -> Dict:
        """The JSON block for /debug surfaces (rebalance status, tests)."""
        telemetry_ok, telemetry_reason = self.telemetry_status()
        kube_ok, kube_reason = self.kube_status()
        evictions_ok, evictions_reason = self.evictions_allowed()
        filter_action, _ = self.filter_decision()
        prioritize_action, _ = self.prioritize_decision()
        return {
            "mode": self.mode,
            "lkg_bound_multiple": self.lkg_bound_multiple,
            "degraded": sorted(self.degraded_subsystems()),
            "telemetry": {"ok": telemetry_ok, "reason": telemetry_reason},
            "kube_api": {"ok": kube_ok, "reason": kube_reason},
            "evictions": {
                "allowed": evictions_ok,
                "reason": evictions_reason,
            },
            "filter_action": filter_action,
            "prioritize_action": prioritize_action,
            "circuits": dict(self.breakers.states()) if self.breakers else {},
        }

    def _publish(
        self,
        telemetry: Optional[bool] = None,
        kube: Optional[bool] = None,
        evictions: Optional[bool] = None,
    ) -> None:
        """Keep the pas_degraded{subsystem} gauges current; each decision
        call refreshes the subsystems it actually evaluated."""
        updates = {
            "telemetry": telemetry,
            "kube_api": kube,
            "evictions": evictions,
        }
        for subsystem, value in updates.items():
            if value is None:
                continue
            self.counters.set_gauge(
                "pas_degraded", 1 if value else 0,
                labels={"subsystem": subsystem},
            )
        if telemetry is not None:
            self.counters.set_gauge(
                "pas_degraded",
                1 if self._circuit_state(GROUP_METRICS) != STATE_CLOSED else 0,
                labels={"subsystem": "metrics_api"},
            )

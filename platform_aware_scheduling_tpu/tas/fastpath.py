"""Per-request fast path for the Prioritize/Filter verbs.

The reference re-sorts per HTTP request (telemetryscheduler.go:128-149).
But the ordering is *request-independent*: for one (metric, operator) the
rank order over all nodes is fixed until the cluster state changes, and a
request's answer is exactly the global order restricted to its candidate
set (the sort key — metric value with node-index tiebreak, ops/scoring.py
— does not depend on which candidates are present).  Same for Filter's
violation set (noted request-independent at SURVEY §3.3).

So the device work moves OFF the request path entirely:

  * on a state-version change, ``prioritize_kernel`` ranks ALL nodes in
    one XLA pass per (metric row, op) in use — amortized over every
    request in the sync window (the reference recomputes per request);
  * a request then costs: candidate-row lookup (dict), a vectorized
    subsequence selection (numpy), and JSON assembly from per-node byte
    fragments pre-rendered at view-build time.

No host↔device round trip, no sort, no per-node Python objects at
request time — this is what makes p99 at 10k nodes flat.

Byte-for-byte output parity with ``encode_host_priority_list`` over the
equivalent HostPriority list is covered by tests/test_fastpath.py.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from platform_aware_scheduling_tpu.ops.scoring import (
    filter_kernel,
    prioritize_kernel,
)
from platform_aware_scheduling_tpu.ops.state import CompiledPolicy, DeviceView

# rank -> b'<score>}' suffix bytes; grown on demand (scores are ordinal
# 10 - rank and go negative past rank 10, telemetryscheduler.go:145)
_SCORE_SUFFIX: List[bytes] = []
_SCORE_LOCK = threading.Lock()


def _score_suffixes(n: int) -> List[bytes]:
    if len(_SCORE_SUFFIX) < n:
        with _SCORE_LOCK:
            for i in range(len(_SCORE_SUFFIX), n):
                _SCORE_SUFFIX.append(f"{10 - i}}}".encode())
    return _SCORE_SUFFIX


class _ViewTable:
    """Per-view-version request-time tables: name->row index, pre-rendered
    JSON fragments (Python path), and the native NameTable (_wirec path).
    Both table kinds build lazily — only the serving variant in use pays."""

    __slots__ = (
        "version",
        "node_index",
        "node_names",
        "node_capacity",
        "_fragments",
        "_native",
    )

    def __init__(self, view: DeviceView):
        self.version = view.version
        self.node_index = view.node_index  # immutable snapshot dict
        self.node_names = view.node_names
        self.node_capacity = view.node_capacity
        self._fragments: Optional[List[bytes]] = None
        self._native = None

    @property
    def fragments(self) -> List[bytes]:
        fragments = self._fragments
        if fragments is None:
            # json.dumps handles any escaping exactly like the slow path
            fragments = [
                f'{{"Host": {json.dumps(name)}, "Score": '.encode()
                for name in self.node_names
            ]
            self._fragments = fragments
        return fragments

    def native(self, wirec):
        table = self._native
        if table is None:
            table = wirec.build_table(self.node_names)
            self._native = table
        return table


class PrioritizeFastPath:
    """Caches global rankings + violation sets per state version and
    answers verbs with numpy selections over them."""

    def __init__(self):
        self._lock = threading.Lock()
        self._table: Optional[_ViewTable] = None
        # (version, metric_row, op) -> int32 np [valid_count] global order
        self._rank: Dict[Tuple[int, int, int], np.ndarray] = {}
        # (version, ruleset signature) -> frozenset of violating row indices
        self._violations: Dict[Tuple, frozenset] = {}

    # -- table/cache maintenance ----------------------------------------------

    def _table_for(self, view: DeviceView) -> _ViewTable:
        table = self._table
        if table is None or table.version != view.version:
            table = _ViewTable(view)
            with self._lock:
                if self._table is None or self._table.version != view.version:
                    self._table = table
                    # rankings/violations of older versions are dead weight
                    self._rank = {
                        k: v for k, v in self._rank.items() if k[0] == view.version
                    }
                    self._violations = {
                        k: v
                        for k, v in self._violations.items()
                        if k[0] == view.version
                    }
                else:
                    table = self._table
        return table

    def _ranking(self, view: DeviceView, row: int, op: int) -> np.ndarray:
        key = (view.version, row, op)
        ranked = self._rank.get(key)
        if ranked is None:
            # ONE device pass ranks all nodes; every request until the next
            # state change reuses it (the recompute runs at most once per
            # version per rule — off the steady-state request path)
            res = prioritize_kernel(
                view.values,
                view.present,
                jnp.int32(row),
                jnp.int32(op),
                jnp.ones(view.node_capacity, dtype=bool),
            )
            count = int(res.valid_count)
            ranked = np.asarray(res.perm)[:count].astype(np.int64)
            with self._lock:
                self._rank[key] = ranked
        return ranked

    def precompute(self, view: DeviceView, pairs) -> None:
        """Warm the ranking cache for (metric_row, op) pairs — called from
        state-refresh threads so requests never pay the device pass."""
        self._table_for(view)
        for row, op in pairs:
            self._ranking(view, int(row), int(op))

    # -- prioritize ------------------------------------------------------------

    def prioritize_parsed(
        self,
        wirec,
        compiled: CompiledPolicy,
        view: DeviceView,
        parsed,
        planned: Optional[str] = None,
    ) -> bytes:
        """Native variant: candidate lookup + selection + byte assembly all
        happen in ``_wirec.select_encode`` over the parsed body's zero-copy
        name slices — no per-node Python objects at any point."""
        table = self._table_for(view)
        ranked = self._ranking(
            view, compiled.scheduleonmetric_row, compiled.scheduleonmetric_op
        )
        planned_row = -1
        if planned is not None:
            planned_row = table.node_index.get(planned, -1)
        return wirec.select_encode(parsed, table.native(wirec), ranked, planned_row)

    def prioritize_bytes(
        self,
        compiled: CompiledPolicy,
        view: DeviceView,
        names: List[str],
        planned: Optional[str] = None,
    ) -> bytes:
        """The full Prioritize response body for one request: global order
        restricted to ``names`` (candidate ∩ metric-present), ordinal
        scores, optional batch-plan promotion to rank 1."""
        table = self._table_for(view)
        ranked = self._ranking(
            view, compiled.scheduleonmetric_row, compiled.scheduleonmetric_op
        )
        index = table.node_index
        sentinel = table.node_capacity
        mask = np.zeros(sentinel + 1, dtype=bool)
        rows = np.fromiter(
            (index.get(n, sentinel) for n in names),
            dtype=np.int64,
            count=len(names),
        )
        mask[rows] = True
        mask[sentinel] = False
        sel = ranked[mask[ranked]]
        if planned is not None:
            prow = index.get(planned)
            if prow is not None:
                at = np.nonzero(sel == prow)[0]
                if at.size:
                    sel = np.concatenate(([prow], np.delete(sel, at[0])))
        return self._encode(table, sel)

    @staticmethod
    def _encode(table: _ViewTable, sel: np.ndarray) -> bytes:
        if sel.size == 0:
            return b"[]\n"
        fragments = table.fragments
        suffix = _score_suffixes(sel.size)
        parts = [fragments[r] + suffix[i] for i, r in enumerate(sel.tolist())]
        return b"[" + b", ".join(parts) + b"]\n"

    # -- filter ----------------------------------------------------------------

    def violating_names(
        self, compiled: CompiledPolicy, view: DeviceView
    ) -> Optional[Dict[str, None]]:
        """The dontschedule violation set over all nodes, cached per state
        version (request-independent, SURVEY §3.3); None when the policy
        has no device-evaluable dontschedule rules."""
        rules = compiled.dontschedule
        if rules is None:
            return None
        sig = (
            view.version,
            rules.metric_rows.tobytes(),
            rules.op_ids.tobytes(),
            rules.targets.tobytes(),
            rules.active.tobytes(),
        )
        cached = self._violations.get(sig)
        if cached is None:
            device_rules = compiled.device_rules("dontschedule")
            if device_rules is None:
                return None
            passing = filter_kernel(
                view.values,
                view.present,
                device_rules,
                jnp.ones(view.node_capacity, dtype=bool),
            )
            bad = ~np.asarray(passing)
            cached = frozenset(int(i) for i in np.nonzero(bad)[0])
            with self._lock:
                self._violations[sig] = cached
        # resolve rows back to names through the view (rows past the interned
        # range are padding and never violate real nodes)
        return {
            view.node_names[i]: None
            for i in cached
            if i < len(view.node_names)
        }

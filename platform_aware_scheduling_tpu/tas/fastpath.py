"""Per-request fast path for the Prioritize/Filter verbs.

The reference re-sorts per HTTP request (telemetryscheduler.go:128-149).
But the ordering is *request-independent*: for one (metric, operator) the
rank order over all nodes is fixed until the cluster state changes, and a
request's answer is exactly the global order restricted to its candidate
set (the sort key — metric value with node-index tiebreak, ops/scoring.py
— does not depend on which candidates are present).  Same for Filter's
violation set (noted request-independent at SURVEY §3.3).

So the device work moves OFF the request path entirely:

  * on a state-version change, ``prioritize_kernel`` ranks ALL nodes in
    one XLA pass per (metric row, op) in use — amortized over every
    request in the sync window (the reference recomputes per request);
  * a request then costs: candidate-row lookup (dict), a vectorized
    subsequence selection (numpy), and JSON assembly from per-node byte
    fragments pre-rendered at view-build time.

No host↔device round trip, no sort, no per-node Python objects at
request time — this is what makes p99 at 10k nodes flat.

Byte-for-byte output parity with ``encode_host_priority_list`` over the
equivalent HostPriority list is covered by tests/test_fastpath.py.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from platform_aware_scheduling_tpu.ops.rules import OP_IDS
from platform_aware_scheduling_tpu.ops.scoring import (
    batch_prioritize_kernel,
    filter_explain_kernel,
    prioritize_kernel,
)
from platform_aware_scheduling_tpu.ops import solveobs
from platform_aware_scheduling_tpu.ops.state import CompiledPolicy, DeviceView
from platform_aware_scheduling_tpu.utils import decisions, trace
from platform_aware_scheduling_tpu.utils import labels as shared_labels

# op id -> operator name, for decoding device rule indexes into the
# shared reason strings (decisions.rule_reason keeps host parity)
_OP_NAMES = {op_id: name for name, op_id in OP_IDS.items()}

# rank -> b'<score>}' suffix bytes; grown on demand (scores are ordinal
# 10 - rank and go negative past rank 10, telemetryscheduler.go:145)
_SCORE_SUFFIX: List[bytes] = []
_SCORE_LOCK = threading.Lock()


def _score_suffixes(n: int) -> List[bytes]:
    if len(_SCORE_SUFFIX) < n:
        with _SCORE_LOCK:
            for i in range(len(_SCORE_SUFFIX), n):
                _SCORE_SUFFIX.append(f"{10 - i}}}".encode())
    return _SCORE_SUFFIX


def _response_cache_size(default: int = 32) -> int:
    """PAS_TPU_RESPONSE_CACHE, validated: malformed or non-positive
    values fall back to the default rather than crashing the import or
    silently disabling the caches via negative slice bounds."""
    raw = os.environ.get("PAS_TPU_RESPONSE_CACHE", "")
    try:
        value = int(raw)
    except ValueError:
        return default
    return value if value >= 1 else default


def _universe_cache_size(default: int = 8) -> int:
    """PAS_TPU_UNIVERSE_CACHE: universes kept per fastpath (each holds
    the raw candidate span + slices + encode metadata — ~0.5 MB at 10k
    nodes).  ``0`` disables interning entirely (the wire then serves
    exactly the pre-universe span-cache paths, byte-identical — pinned
    by tests/test_wire_universe.py); malformed values fall back."""
    raw = os.environ.get("PAS_TPU_UNIVERSE_CACHE", "")
    try:
        value = int(raw)
    except ValueError:
        return default
    return value if value >= 0 else default


class _ViewTable:
    """Per-interning-version request-time tables: name->row index,
    pre-rendered JSON fragments (Python path), and the native NameTable
    (_wirec path).  Keyed by the view's ``intern_version`` — pure metric
    value churn does not invalidate name tables/fragments, so the encode
    table survives every sync period until a new node actually appears.
    Both table kinds build lazily — only the serving variant in use pays."""

    __slots__ = (
        "version",
        "node_index",
        "node_names",
        "node_capacity",
        "_fragments",
        "_native",
    )

    def __init__(self, view: DeviceView):
        self.version = view.intern_version
        self.node_index = view.node_index  # immutable snapshot dict
        self.node_names = view.node_names
        self.node_capacity = view.node_capacity
        self._fragments: Optional[List[bytes]] = None
        self._native = None

    @property
    def fragments(self) -> List[bytes]:
        fragments = self._fragments
        if fragments is None:
            # json.dumps handles any escaping exactly like the slow path
            fragments = [
                f'{{"Host": {json.dumps(name)}, "Score": '.encode()
                for name in self.node_names
            ]
            self._fragments = fragments
        return fragments

    def native(self, wirec):
        table = self._native
        if table is None:
            table = wirec.build_table(self.node_names)
            self._native = table
        return table


class PrioritizeFastPath:
    """Caches global rankings + violation sets per state version and
    answers verbs with numpy selections over them."""

    # response-reuse entries kept per fastpath (each ~ request span +
    # response bytes — ~0.5 MB at 10k nodes, so the default 32 costs at
    # most ~17 MB per verb).  The round-3 verdict flagged 8 as thrashable
    # by more than 8 interleaved candidate sets; override via
    # PAS_TPU_RESPONSE_CACHE for constrained deployments.
    RESPONSE_CACHE_SIZE = _response_cache_size()
    # interned node-name universes kept (bounded MRU, wirec.c; 0 = off)
    UNIVERSE_CACHE_SIZE = _universe_cache_size()

    def __init__(self):
        self._lock = threading.Lock()
        self._table: Optional[_ViewTable] = None
        # _wirec.UniverseCache, created lazily on the first probe (the
        # native module may not be loadable at construction time); False
        # marks "tried and unavailable" so the probe stays O(1)
        self._universes = None
        # (row_content_version, metric_row, op) -> int64 np global order
        self._rank: Dict[Tuple[int, int, int], np.ndarray] = {}
        # (row-version tuple, rows, ruleset tensors) -> (frozenset of
        # violating row indices, {row: first matching rule index}) — the
        # rule map is the device's compact reason code per violating node
        # (ops/scoring.filter_explain_kernel), decoded into reason
        # strings once per state by violation_reasons()
        self._violations: Dict[Tuple, Tuple[frozenset, Dict[int, int]]] = {}
        # decoded provenance per (violation-set identity, policy name):
        # [violations, policy_name, {name: reason str}, {name: rule idx},
        #  encoded-reason-bytes-per-row list or None (built lazily for
        #  the native filter_encode)] — MRU, shared by every request at
        # one state so record creation stays O(1)
        self._viol_reasons: List[list] = []
        # response-reuse cache: the kube-scheduler prioritizes every
        # pending pod against the same filter result, so consecutive
        # requests carry byte-identical candidate lists; entries are keyed
        # by (ranking identity, table identity, planned row) and VERIFIED
        # by comparing the raw candidate-span bytes — identical span +
        # identical ranking implies a byte-identical response, with zero
        # false positives (no hashing trust).  List of
        # [ranked, table, planned_row, span_bytes, response], MRU first.
        self._responses: List[list] = []
        # same idea for Filter: [violation_set, use_nn, span_bytes, body,
        # n_failed, gang_version] — the failed-entry count rides along so
        # decision records on cache hits stay O(1); gang_version keys the
        # reservation state a gang-mode response encoded (None = no gang
        # tracker), so a reservation change can never serve stale bytes
        self._filter_responses: List[list] = []
        # pre-rendered response skeletons, the universe-keyed layer UNDER
        # the span caches: once a request's candidate span is interned
        # (wirec.c UniverseCache), the full response body is keyed by
        # OBJECT IDENTITY — (violation set, universe, gang reservation
        # version) for Filter, (ranking, table, planned row, universe)
        # for Prioritize — so a warm hit costs identity compares instead
        # of a span memcmp, and any state change (new frozenset / new
        # ranking / new reservation version) misses by construction.
        # Entries: [violations, universe, gang_version, body, n_failed]
        # and [ranked, table, planned_row, universe, body].
        self._filter_skeletons: List[list] = []
        self._prioritize_skeletons: List[list] = []
        # merged (telemetry + gang reservation) Filter verdicts, one per
        # (violation-set identity, reservation version, policy):
        # [violations, version, policy, merged frozenset, merged reasons,
        #  merged reason-bytes table] — MRU, shared by every non-gang
        # request at one (state, reservation) generation
        self._gang_merged: List[list] = []
        # [ranked, table, top-K (name, score) head] — the shared
        # prioritize score breakdown decision records reference
        self._explain_heads: List[list] = []
        # violation frozenset -> uint8-per-row bitmask bytes for the
        # native filter_encode; keyed by OBJECT identity (sets are
        # identity-stable per state) with the set itself held in the
        # entry so an id can never alias a collected set
        self._viol_masks: List[list] = []

    # -- table/cache maintenance ----------------------------------------------

    def _table_for(self, view: DeviceView) -> _ViewTable:
        """The encode table for this view's interning.  Forward-only: a
        stale in-flight request (view older than the installed table) gets
        a throwaway table and must never displace the warmed current one
        — otherwise one slow request would make the next request pay the
        rebuild the warmer already did."""
        table = self._table
        if table is not None and table.version == view.intern_version:
            return table
        if table is not None and view.intern_version < table.version:
            return _ViewTable(view)
        with self._lock:
            current = self._table
            if current is None or current.version < view.intern_version:
                current = _ViewTable(view)
                self._table = current
            elif current.version > view.intern_version:  # raced past us
                return _ViewTable(view)
            return current

    def _ranking(self, view: DeviceView, row: int, op: int) -> np.ndarray:
        # keyed by the ROW's content version: metric churn on other rows
        # (or node interning alone) leaves this ranking valid
        key = (view.row_version(row), row, op)
        ranked = self._rank.get(key)
        if ranked is None:
            obs = solveobs.ACTIVE
            timer = obs.begin("prioritize_rank") if obs is not None else None
            compiled_before = (
                prioritize_kernel.cache_size() if timer is not None else 0
            )
            # ONE device pass ranks all nodes; every request until this
            # row's next content change reuses it
            res = prioritize_kernel(
                view.values,
                view.present,
                jnp.int32(row),
                jnp.int32(op),
                jnp.ones(view.node_capacity, dtype=bool),
            )
            if timer is not None:
                # attribute the dispatch to compile when the jit cache
                # grew during the call, then block so execute carries the
                # device time instead of hiding inside the readback
                grew = prioritize_kernel.cache_size() > compiled_before
                timer.mark("compile" if grew else "execute")
                res.perm.block_until_ready()
                timer.mark("execute")
            count = int(res.valid_count)
            ranked = np.asarray(res.perm)[:count]
            if timer is not None:
                timer.mark("readback")
            ranked = ranked.astype(np.int64)
            with self._lock:
                self._rank[key] = ranked
            if timer is not None:
                timer.mark("encode")
                timer.done(nodes=view.node_capacity)
        return ranked

    def warm_rankings_batched(self, view: DeviceView, pairs) -> int:
        """Seed the ranking cache for every not-yet-warm (metric row, op)
        pair in ONE device dispatch (``batch_prioritize_kernel`` vmapped
        over the pair axis) — the serving micro-batcher's fused solve:
        a coalesced batch of requests needing K distinct rankings costs
        one XLA program, not K (and zero when all are warm).  Cache
        entries are identical to what per-pair :meth:`_ranking` would
        store, so responses stay byte-identical to the per-request path.
        Returns the number of pairs actually computed."""
        missing = [
            (int(row), int(op))
            for row, op in pairs
            if (view.row_version(int(row)), int(row), int(op))
            not in self._rank
        ]
        if not missing:
            return 0
        obs = solveobs.ACTIVE
        timer = obs.begin("warm_batch") if obs is not None else None
        compiled_before = (
            batch_prioritize_kernel.cache_size() if timer is not None else 0
        )
        rows_dev = jnp.asarray([row for row, _ in missing], dtype=jnp.int32)
        ops_dev = jnp.asarray([op for _, op in missing], dtype=jnp.int32)
        mask_dev = jnp.ones((len(missing), view.node_capacity), dtype=bool)
        if timer is not None:
            timer.mark("transfer")
        res = batch_prioritize_kernel(
            view.values, view.present, rows_dev, ops_dev, mask_dev
        )
        if timer is not None:
            grew = batch_prioritize_kernel.cache_size() > compiled_before
            timer.mark("compile" if grew else "execute")
            res.perm.block_until_ready()
            timer.mark("execute")
        perms = np.asarray(res.perm)
        counts = np.asarray(res.valid_count)
        if timer is not None:
            timer.mark("readback")
        with self._lock:
            for i, (row, op) in enumerate(missing):
                key = (view.row_version(row), row, op)
                self._rank[key] = perms[i][: int(counts[i])].astype(np.int64)
        if timer is not None:
            timer.mark("encode")
            timer.done(pairs=len(missing), nodes=view.node_capacity)
        return len(missing)

    def warm_pairs(self, view: DeviceView, pairs) -> None:
        """Warm rankings for (metric row, op) pairs against ``view``
        WITHOUT the precompute pruning — the forecast warmer's entry
        (forecast views carry negative version markers the prune would
        drop; they expire naturally when the next fit publishes)."""
        for row, op in pairs:
            self._ranking(view, int(row), int(op))

    def precompute(self, view: DeviceView, pairs, wirec=None) -> None:
        """Warm the request-time state for (metric_row, op) pairs: the
        ranking cache (one device pass per pair whose row actually
        changed), plus the response table for whichever encoder will serve
        (native NameTable when ``wirec`` is given, fragments otherwise).

        Called from state-refresh threads via the mirror's post-publish
        hook (TensorStateMirror.on_state_change) so steady-state requests
        never pay a device pass or a table build.  Also prunes cache
        entries whose row content (or interning) has moved on."""
        table = self._table_for(view)
        if wirec is not None:
            table.native(wirec)
        else:
            table.fragments
        for row, op in pairs:
            self._ranking(view, int(row), int(op))
        with self._lock:
            self._rank = {
                k: v
                for k, v in self._rank.items()
                if k[0] == view.row_version(k[1])
            }
            self._violations = {
                k: v
                for k, v in self._violations.items()
                if k[0] == tuple(view.row_version(r) for r in k[1])
            }

    # -- universe interning ----------------------------------------------------

    def universe_probe(self, wirec, parsed, use_node_names: bool):
        """The interned universe for this request's candidate span, or
        None (cold span, interning disabled, or an old native artifact
        without universe support).  A span is interned on its SECOND
        sighting (the cache's once-seen digest ring), so one-shot
        candidate lists never pay intern/evict churn.  Counters:
        ``pas_wire_intern_{hits,misses,evictions}_total`` partition every
        probe against an available cache into hit/miss (evictions ride
        along).  Never raises into the verb."""
        cache = self._universe_cache(wirec)
        if cache is None:
            return None
        try:
            # ONE digest pass covers hit lookup, the once-seen check, and
            # a second-sighting intern (wirec.c UniverseCache.probe)
            universe, interned, evicted = cache.probe(parsed, use_node_names)
            if universe is not None and not interned:
                trace.COUNTERS.inc("pas_wire_intern_hits_total")
                return universe
            trace.COUNTERS.inc("pas_wire_intern_misses_total")
            if evicted:
                trace.COUNTERS.inc("pas_wire_intern_evictions_total", evicted)
            # freshly interned (or first sighting, None): the request
            # itself still renders, but may promote a span-cache body
            return universe
        except Exception:
            return None  # interning is an optimization, never a failure

    def _universe_cache(self, wirec):
        cache = self._universes
        if cache is not None:
            return cache or None  # False = tried, unavailable
        if (
            self.UNIVERSE_CACHE_SIZE <= 0
            or wirec is None
            or not hasattr(wirec, "UniverseCache")
        ):
            self._universes = False
            return None
        with self._lock:
            if self._universes is None:
                self._universes = wirec.UniverseCache(
                    capacity=self.UNIVERSE_CACHE_SIZE
                )
            return self._universes or None

    def warm_skeletons(
        self,
        wirec,
        compiled: CompiledPolicy,
        view: DeviceView,
        policy_name: str,
        filter_ok: bool = True,
        prioritize_ok: bool = True,
    ) -> int:
        """Pre-render response skeletons for every interned NodeNames
        universe at the CURRENT state — called from the state-refresh
        warm pass (MetricsExtender.warm_fastpath), so a metric refresh
        that mints a new violation set / ranking re-renders each live
        universe's body ONCE off the request path and the first request
        of the sync window still splices.  Only the no-gang keys are
        warmed (gang reservation versions move between warm passes; a
        gang-mode miss renders on demand as before).  Returns the number
        of bodies rendered; never raises past the warm pass's guard."""
        cache = self._universes
        if (
            not cache
            or wirec is None
            or not hasattr(wirec, "filter_respond")
        ):
            return 0
        rendered = 0
        table = self._table_for(view)
        n_rows = len(table.node_names)
        native = table.native(wirec)
        violations = None
        reasons = None
        if filter_ok:
            counted = self._violation_set_counted(compiled, view)
            if counted is not None:
                violations, rule_map = counted[0]
                reasons = self.reason_table(
                    compiled, view, policy_name, violations, rule_map,
                    n_rows,
                )
        ranked = None
        if prioritize_ok and compiled.scheduleonmetric_row >= 0:
            ranked = self._ranking(
                view,
                compiled.scheduleonmetric_row,
                compiled.scheduleonmetric_op,
            )
        for universe in cache.snapshot():
            if not universe.use_node_names:
                continue
            if violations is not None:
                with self._lock:
                    have = any(
                        entry[0] is violations
                        and entry[1] is universe
                        and entry[2] is None
                        for entry in self._filter_skeletons
                    )
                if not have:
                    mask = self._violation_mask(violations, n_rows)
                    body, n_failed = wirec.filter_respond(
                        universe, native, mask, reasons
                    )
                    self.filter_store(
                        violations, True, None, body, n_failed, None,
                        universe=universe,
                    )
                    rendered += 1
            if ranked is not None:
                with self._lock:
                    have = any(
                        entry[0] is ranked
                        and entry[1] is table
                        and entry[2] == -1
                        and entry[3] is universe
                        for entry in self._prioritize_skeletons
                    )
                if not have:
                    body = wirec.select_encode_universe(
                        universe, native, ranked, -1
                    )
                    with self._lock:
                        self._prioritize_skeletons.insert(
                            0, [ranked, table, -1, universe, body]
                        )
                        del self._prioritize_skeletons[
                            self.RESPONSE_CACHE_SIZE :
                        ]
                    rendered += 1
        return rendered

    def wire_debug(self) -> Dict:
        """The /debug/wire payload: universe-cache occupancy + interning
        counters + the skeleton-cache keys (universe uid, violation-set
        size, gang version / planned row) — the operator's view of why a
        request was cold, interned, or spliced."""
        out: Dict = {
            "enabled": bool(self._universes),
            "capacity": self.UNIVERSE_CACHE_SIZE,
            "counters": {
                "hits": trace.COUNTERS.get("pas_wire_intern_hits_total"),
                "misses": trace.COUNTERS.get("pas_wire_intern_misses_total"),
                "evictions": trace.COUNTERS.get(
                    "pas_wire_intern_evictions_total"
                ),
            },
        }
        cache = self._universes
        if not cache:
            out["occupancy"] = 0
            out["universes"] = []
        else:
            out["occupancy"] = cache.occupancy
            out["universes"] = cache.universes()
        with self._lock:
            out["skeletons"] = {
                "filter": [
                    {
                        "universe": entry[1].uid,
                        "violating": len(entry[0]),
                        "gang_version": entry[2],
                        "bytes": len(entry[3]),
                    }
                    for entry in self._filter_skeletons
                ],
                "prioritize": [
                    {
                        "universe": entry[3].uid,
                        "planned_row": entry[2],
                        "bytes": len(entry[4]),
                    }
                    for entry in self._prioritize_skeletons
                ],
            }
        return out

    # -- prioritize ------------------------------------------------------------

    def prioritize_parsed(
        self,
        wirec,
        compiled: CompiledPolicy,
        view: DeviceView,
        parsed,
        planned: Optional[str] = None,
        use_node_names: bool = False,
        span=trace.NULL_SPAN,
        universe=None,
    ) -> bytes:
        """Native variant: candidate lookup + selection + byte assembly all
        happen in ``_wirec.select_encode`` over the parsed body's zero-copy
        name slices — no per-node Python objects at any point.  When the
        request's raw candidate span matches a cached one under the same
        ranking/table/plan, the stored response is returned without any
        selection or encoding at all (see _responses).  With an interned
        ``universe`` the skeleton layer serves first — identity compares
        only, no span memcmp — and a miss renders through the universe's
        cached row map (``select_encode_universe``, zero hashing); either
        way the bytes are identical to the span path's."""
        table = self._table_for(view)
        with span.stage("kernel"):
            ranked = self._ranking(
                view,
                compiled.scheduleonmetric_row,
                compiled.scheduleonmetric_op,
            )
        planned_row = -1
        if planned is not None:
            planned_row = table.node_index.get(planned, -1)
        with self._lock:
            if universe is not None:
                skeletons = self._prioritize_skeletons
                for idx, entry in enumerate(skeletons):
                    if (
                        entry[0] is ranked
                        and entry[1] is table
                        and entry[2] == planned_row
                        and entry[3] is universe
                    ):
                        if idx:
                            skeletons.insert(0, skeletons.pop(idx))
                        span.set("fastpath", "hit")
                        trace.COUNTERS.inc("pas_fastpath_response_hit_total")
                        return entry[4]
            responses = self._responses
            for idx, entry in enumerate(responses):
                if (
                    entry[0] is ranked
                    and entry[1] is table
                    and entry[2] == planned_row
                    and parsed.span_matches(use_node_names, entry[3])
                ):
                    if idx:  # move to front (MRU)
                        responses.insert(0, responses.pop(idx))
                    if universe is not None:
                        # promote the span-cached body into the skeleton
                        # layer so the next warm request skips the memcmp
                        self._prioritize_skeletons.insert(
                            0,
                            [ranked, table, planned_row, universe, entry[4]],
                        )
                        del self._prioritize_skeletons[
                            self.RESPONSE_CACHE_SIZE :
                        ]
                    span.set("fastpath", "hit")
                    trace.COUNTERS.inc("pas_fastpath_response_hit_total")
                    return entry[4]
        span.set("fastpath", "miss")
        trace.COUNTERS.inc("pas_fastpath_response_miss_total")
        with span.stage("encode"):
            if universe is not None and hasattr(
                wirec, "select_encode_universe"
            ):
                response = wirec.select_encode_universe(
                    universe, table.native(wirec), ranked, planned_row
                )
            else:
                response = wirec.select_encode(
                    parsed, table.native(wirec), ranked, planned_row,
                    use_node_names,
                )
        if universe is not None:
            with self._lock:
                self._prioritize_skeletons.insert(
                    0, [ranked, table, planned_row, universe, response]
                )
                del self._prioritize_skeletons[self.RESPONSE_CACHE_SIZE :]
            return response
        # cand_span: the request's raw candidate byte-span (the cache key)
        # — distinct from the trace `span` parameter above
        cand_span = (
            parsed.node_names_span() if use_node_names else parsed.nodes_span()
        )
        if cand_span is not None:
            entry = [ranked, table, planned_row, cand_span, response]
            with self._lock:
                self._responses.insert(0, entry)
                del self._responses[self.RESPONSE_CACHE_SIZE :]
        return response

    def prioritize_bytes(
        self,
        compiled: CompiledPolicy,
        view: DeviceView,
        names: List[str],
        planned: Optional[str] = None,
        span=trace.NULL_SPAN,
    ) -> bytes:
        """The full Prioritize response body for one request: global order
        restricted to ``names`` (candidate ∩ metric-present), ordinal
        scores, optional batch-plan promotion to rank 1."""
        table = self._table_for(view)
        with span.stage("kernel"):
            ranked = self._ranking(
                view,
                compiled.scheduleonmetric_row,
                compiled.scheduleonmetric_op,
            )
        with span.stage("encode"):
            index = table.node_index
            sentinel = table.node_capacity
            mask = np.zeros(sentinel + 1, dtype=bool)
            rows = np.fromiter(
                (index.get(n, sentinel) for n in names),
                dtype=np.int64,
                count=len(names),
            )
            mask[rows] = True
            mask[sentinel] = False
            sel = ranked[mask[ranked]]
            if planned is not None:
                prow = index.get(planned)
                if prow is not None:
                    at = np.nonzero(sel == prow)[0]
                    if at.size:
                        sel = np.concatenate(([prow], np.delete(sel, at[0])))
            return self._encode(table, sel)

    @staticmethod
    def _encode(table: _ViewTable, sel: np.ndarray) -> bytes:
        if sel.size == 0:
            return b"[]\n"
        fragments = table.fragments
        suffix = _score_suffixes(sel.size)
        parts = [fragments[r] + suffix[i] for i, r in enumerate(sel.tolist())]
        return b"[" + b", ".join(parts) + b"]\n"

    # -- filter ----------------------------------------------------------------

    def violation_set(
        self, compiled: CompiledPolicy, view: DeviceView
    ) -> Optional[frozenset]:
        """Identity-stable violating-row frozenset for this policy at this
        state — the Filter response cache keys on the OBJECT identity, so
        a state change (new frozenset) can never serve stale bytes."""
        result = self._violation_set_counted(compiled, view)
        return result if result is None else result[0][0]

    def violation_rule_map(
        self, compiled: CompiledPolicy, view: DeviceView
    ) -> Optional[Dict[int, int]]:
        """{violating row: first matching rule index} at this state — the
        device's raw reason codes (decoded by violation_reasons)."""
        result = self._violation_set_counted(compiled, view)
        return result if result is None else result[0][1]

    def violation_reasons(
        self, compiled: CompiledPolicy, view: DeviceView, policy_name: str
    ):
        """Decision provenance for one policy at the current state:
        ``(violations frozenset, {node name: reason string},
        {node name: rule index})`` — or None when the policy has no
        device-evaluable dontschedule rules.

        The maps are built ONCE per (violation set, policy) and shared by
        reference across every request and decision record at that state;
        the strings are byte-identical to the host path's
        (dontschedule.violated_details) because both format the same
        milli integers through decisions.rule_reason."""
        counted = self._violation_set_counted(compiled, view)
        if counted is None:
            return None
        violations, rule_map = counted[0]
        entry = self._reason_entry(compiled, view, policy_name, violations, rule_map)
        return violations, entry[2], entry[3]

    def _reason_entry(
        self,
        compiled: CompiledPolicy,
        view: DeviceView,
        policy_name: str,
        violations: frozenset,
        rule_map: Dict[int, int],
    ) -> list:
        with self._lock:
            for idx, entry in enumerate(self._viol_reasons):
                if entry[0] is violations and entry[1] == policy_name:
                    if idx:
                        self._viol_reasons.insert(
                            0, self._viol_reasons.pop(idx)
                        )
                    return entry
        rules = compiled.dontschedule
        reasons: Dict[str, str] = {}
        indexes: Dict[str, int] = {}
        n_names = len(view.node_names)
        for row in sorted(rule_map):
            if row >= n_names:
                continue  # padding lanes never violate real nodes
            ridx = rule_map[row]
            metric = (
                rules.metric_names[ridx]
                if ridx < len(rules.metric_names)
                else ""
            )
            operator = _OP_NAMES.get(int(rules.op_ids[ridx]), "?")
            target_str = decisions.fmt_milli(int(rules.targets[ridx]))
            if view.values_milli is not None:
                value_str = decisions.fmt_milli(
                    int(view.values_milli[int(rules.metric_rows[ridx]), row])
                )
            else:
                value_str = "?"
            name = view.node_names[row]
            reasons[name] = decisions.rule_reason(
                policy_name, metric, operator, value_str, target_str
            )
            indexes[name] = ridx
        entry = [violations, policy_name, reasons, indexes, None]
        with self._lock:
            for existing in self._viol_reasons:
                if existing[0] is violations and existing[1] == policy_name:
                    return existing  # a concurrent builder won
            self._viol_reasons.insert(0, entry)
            del self._viol_reasons[self.RESPONSE_CACHE_SIZE :]
        return entry

    def reason_table(
        self,
        compiled: CompiledPolicy,
        view: DeviceView,
        policy_name: str,
        violations: frozenset,
        rule_map: Dict[int, int],
        n_rows: int,
    ) -> list:
        """Per-row pre-JSON-encoded reason bytes (aligned with the
        violation bitmask) for the native ``_wirec.filter_encode`` — the
        C encoder splices entry bytes verbatim, so parity with the exact
        path's json.dumps holds by construction.  Built lazily once per
        (violation set, policy) and cached on the reason entry."""
        entry = self._reason_entry(
            compiled, view, policy_name, violations, rule_map
        )
        table = entry[4]
        if table is None or len(table) < n_rows:
            table = [None] * n_rows
            index = view.node_index
            for name, reason in entry[2].items():
                row = index.get(name)
                if row is not None and row < n_rows:
                    table[row] = json.dumps(reason).encode()
            entry[4] = table
        return table

    def warm_violations(
        self, compiled: CompiledPolicy, view: DeviceView
    ) -> int:
        """Warm the violation set for one policy, reporting whether a
        device computation actually ran (1) or the set was already cached
        (0) — the serving micro-batcher's fused-solve accounting
        (MetricsExtender.warm_batch)."""
        result = self._violation_set_counted(compiled, view)
        return 0 if result is None else int(result[1])

    def _violation_set_counted(
        self, compiled: CompiledPolicy, view: DeviceView
    ):
        """((violation frozenset, {row: rule index}), computed-now?) or
        None (no device rules).  One fused device pass produces both the
        verdict and the per-node first-matching-rule index — the compact
        provenance vector decoded host-side by violation_reasons()."""
        rules = compiled.dontschedule
        if rules is None:
            return None
        # keyed by the rule rows' content versions (not the global state
        # version): churn on unrelated metrics keeps this set warm
        rule_rows = tuple(int(r) for r in rules.metric_rows[rules.active])
        sig = (
            tuple(view.row_version(r) for r in rule_rows),
            rule_rows,
            rules.op_ids.tobytes(),
            rules.targets.tobytes(),
            rules.active.tobytes(),
        )
        cached = self._violations.get(sig)
        if cached is not None:
            return cached, False
        device_rules = compiled.device_rules("dontschedule")
        if device_rules is None:
            return None
        obs = solveobs.ACTIVE
        timer = obs.begin("filter_explain") if obs is not None else None
        compiled_before = (
            filter_explain_kernel.cache_size() if timer is not None else 0
        )
        res = filter_explain_kernel(
            view.values,
            view.present,
            device_rules,
            jnp.ones(view.node_capacity, dtype=bool),
        )
        if timer is not None:
            grew = filter_explain_kernel.cache_size() > compiled_before
            timer.mark("compile" if grew else "execute")
            res.first_rule.block_until_ready()
            timer.mark("execute")
        first_rule = np.asarray(res.first_rule)
        if timer is not None:
            timer.mark("readback")
        rows = np.nonzero(first_rule >= 0)[0]
        cached = (
            frozenset(int(i) for i in rows),
            {int(i): int(first_rule[i]) for i in rows},
        )
        if timer is not None:
            timer.mark("encode")
            timer.done(nodes=view.node_capacity)
        with self._lock:
            # a concurrent computer may have won: keep ITS set so the
            # identity-keyed response caches see one object per state
            existing = self._violations.get(sig)
            if existing is not None:
                return existing, False
            self._violations[sig] = cached
        return cached, True

    def _violation_mask(self, violations: frozenset, n_rows: int) -> bytes:
        """uint8-per-row bitmask form of a violation frozenset (the shape
        ``_wirec.filter_encode`` consumes); cached per set identity."""
        with self._lock:
            for idx, entry in enumerate(self._viol_masks):
                if entry[0] is violations and entry[1] == n_rows:
                    if idx:
                        self._viol_masks.insert(0, self._viol_masks.pop(idx))
                    return entry[2]
        mask = np.zeros(n_rows, dtype=np.uint8)
        if violations:
            rows = np.fromiter(
                (i for i in violations if i < n_rows), dtype=np.int64
            )
            if rows.size:
                mask[rows] = 1
        mask_bytes = mask.tobytes()
        with self._lock:
            self._viol_masks.insert(0, [violations, n_rows, mask_bytes])
            del self._viol_masks[self.RESPONSE_CACHE_SIZE :]
        return mask_bytes

    def filter_parsed(
        self,
        wirec,
        view: DeviceView,
        parsed,
        violations: frozenset,
        compiled: Optional[CompiledPolicy] = None,
        policy_name: str = "",
        reason_table: Optional[list] = None,
        universe=None,
    ) -> Tuple[bytes, int]:
        """Native NodeNames-mode Filter response: candidate row lookup,
        violation partition, and byte assembly all happen in
        ``_wirec.filter_encode`` over the parsed body's zero-copy name
        slices — the Filter analog of :meth:`prioritize_parsed` (byte
        parity with the exact path pinned by tests/test_wirec.py).  With
        an interned ``universe``, ``_wirec.filter_respond`` partitions
        over the universe's cached row map instead (one int32 read per
        candidate, zero hashing) — identical bytes by construction.

        Returns ``(body, failed count)``.  With ``compiled`` given, the
        FailedNodes values carry the concrete per-rule reason strings
        (pre-encoded once per state via :meth:`reason_table`); without it
        the reference literal "Node violates" is emitted.  An explicit
        ``reason_table`` (the gang-merged overlay, :meth:`gang_merged`)
        overrides the per-rule one."""
        table = self._table_for(view)
        n_rows = len(table.node_names)
        mask = self._violation_mask(violations, n_rows)
        reasons = reason_table
        if reasons is None and compiled is not None:
            rule_map = self.violation_rule_map(compiled, view)
            if rule_map is not None:
                reasons = self.reason_table(
                    compiled, view, policy_name, violations, rule_map, n_rows
                )
        if universe is not None and hasattr(wirec, "filter_respond"):
            return wirec.filter_respond(
                universe, table.native(wirec), mask, reasons
            )
        return wirec.filter_encode(parsed, table.native(wirec), mask, reasons)

    def gang_merged(
        self,
        compiled: CompiledPolicy,
        view: DeviceView,
        policy_name: str,
        violations: frozenset,
        reasons: Dict[str, str],
        held: Dict[str, str],
        version: int,
    ) -> Tuple[frozenset, Dict[str, str], list]:
        """The non-gang-pod Filter verdict under active reservations:
        ``(merged violating rows, merged {node: reason}, merged per-row
        reason-bytes table)`` — telemetry violations plus gang-held
        nodes, with the telemetry reason winning a collision exactly like
        the exact path's overlay merge (the overlay only ever fails
        telemetry-CLEAN candidates).  Memoized per (violation-set
        identity, reservation version, policy) so every cached request at
        one generation shares the same objects."""
        with self._lock:
            for idx, entry in enumerate(self._gang_merged):
                if (
                    entry[0] is violations
                    and entry[1] == version
                    and entry[2] == policy_name
                ):
                    if idx:
                        self._gang_merged.insert(
                            0, self._gang_merged.pop(idx)
                        )
                    return entry[3], entry[4], entry[5]
        index = view.node_index
        gang_rows: Dict[int, Tuple[str, str]] = {}
        for node, gang_id in held.items():
            row = index.get(node)
            if row is not None and row not in violations:
                gang_rows[row] = (node, gang_id)
        merged = frozenset(violations | set(gang_rows))
        merged_reasons = dict(reasons)
        n_rows = len(view.node_names)
        rule_map = self.violation_rule_map(compiled, view)
        if rule_map is not None:
            base = self.reason_table(
                compiled, view, policy_name, violations, rule_map, n_rows
            )
            table = list(base[:n_rows])
            table += [None] * (n_rows - len(table))
        else:
            table = [None] * n_rows
        for row, (node, gang_id) in gang_rows.items():
            reason = shared_labels.gang_reserved_reason(gang_id)
            merged_reasons[node] = reason
            if row < n_rows:
                table[row] = json.dumps(reason).encode()
        entry = [violations, version, policy_name, merged, merged_reasons, table]
        with self._lock:
            for existing in self._gang_merged:
                if (
                    existing[0] is violations
                    and existing[1] == version
                    and existing[2] == policy_name
                ):
                    return existing[3], existing[4], existing[5]
            self._gang_merged.insert(0, entry)
            del self._gang_merged[self.RESPONSE_CACHE_SIZE :]
        return merged, merged_reasons, table

    # -- filter response reuse -------------------------------------------------

    def filter_lookup(
        self,
        violations: frozenset,
        use_node_names: bool,
        parsed,
        gang_version: Optional[int] = None,
        universe=None,
    ) -> Optional[Tuple[bytes, int]]:
        """Cached (response bytes, failed count) for this exact candidate
        span under this exact violation set (and, in gang mode, this
        exact reservation version), or None.  With an interned
        ``universe`` the skeleton layer is probed first (identity
        compares, no span memcmp); a span-layer hit is promoted into it
        so the next warm request splices without touching the span."""
        with self._lock:
            if universe is not None:
                skeletons = self._filter_skeletons
                for idx, entry in enumerate(skeletons):
                    if (
                        entry[0] is violations
                        and entry[1] is universe
                        and entry[2] == gang_version
                    ):
                        if idx:
                            skeletons.insert(0, skeletons.pop(idx))
                        return entry[3], entry[4]
            responses = self._filter_responses
            for idx, entry in enumerate(responses):
                if (
                    entry[0] is violations
                    and entry[1] == use_node_names
                    and entry[5] == gang_version
                    and parsed.span_matches(use_node_names, entry[2])
                ):
                    if idx:
                        responses.insert(0, responses.pop(idx))
                    if universe is not None:
                        self._filter_skeletons.insert(
                            0,
                            [violations, universe, gang_version, entry[3],
                             entry[4]],
                        )
                        del self._filter_skeletons[self.RESPONSE_CACHE_SIZE :]
                    return entry[3], entry[4]
        return None

    def filter_store(
        self,
        violations: frozenset,
        use_node_names: bool,
        parsed,
        body: bytes,
        n_failed: int = 0,
        gang_version: Optional[int] = None,
        universe=None,
    ) -> None:
        if universe is not None:
            with self._lock:
                self._filter_skeletons.insert(
                    0, [violations, universe, gang_version, body, n_failed]
                )
                del self._filter_skeletons[self.RESPONSE_CACHE_SIZE :]
            return
        span = (
            parsed.node_names_span() if use_node_names else parsed.nodes_span()
        )
        if span is None:
            return
        with self._lock:
            self._filter_responses.insert(
                0,
                [violations, use_node_names, span, body, n_failed,
                 gang_version],
            )
            del self._filter_responses[self.RESPONSE_CACHE_SIZE :]

    # -- decision provenance ---------------------------------------------------

    def explain_prioritize(
        self, compiled: CompiledPolicy, view: DeviceView, k: int = 10
    ):
        """(score head, ranked, node_index) for one policy at the current
        state: the top-``k`` ``(node, ordinal score)`` pairs of the
        GLOBAL ranking (shared by reference across every decision record
        at this state — O(1) per request after the first) plus the raw
        ranking + interning table for exact chosen-rank lookup at bind
        time (utils/decisions.DecisionRecord.chosen_rank)."""
        table = self._table_for(view)
        ranked = self._ranking(
            view,
            compiled.scheduleonmetric_row,
            compiled.scheduleonmetric_op,
        )
        with self._lock:
            for idx, entry in enumerate(self._explain_heads):
                if entry[0] is ranked and entry[1] is table:
                    if idx:
                        self._explain_heads.insert(
                            0, self._explain_heads.pop(idx)
                        )
                    return entry[2], ranked, table.node_index
        names = table.node_names
        head = [
            (names[r], 10 - i)
            for i, r in enumerate(ranked[:k].tolist())
            if r < len(names)
        ]
        with self._lock:
            self._explain_heads.insert(0, [ranked, table, head])
            del self._explain_heads[self.RESPONSE_CACHE_SIZE :]
        return head, ranked, table.node_index

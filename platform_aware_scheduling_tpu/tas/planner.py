"""Batch planner: the whole-pending-set solve wired into the service.

SURVEY §7 step 4's product form.  kube-scheduler's protocol is one pod
per round-trip; the planner watches pending pods carrying the
``telemetry-policy`` label, solves the ENTIRE set each sync period with
``models/batch_scheduler.scheduling_step``, and lets the per-pod verbs be
answered from the precomputed solution: when Prioritize arrives for a
planned pod, its batch-assigned node gets the top score, steering the
sequential scheduler onto the coordinated plan (capacity-aware placement
the per-pod ordinal scores alone cannot express).

OPT-IN (``--batchPlanner`` on cmd/tas.py): with the planner off the verbs
behave exactly like the reference.  Planner answers degrade gracefully:
unknown pod / stale plan / no assignment -> the ordinary per-request path.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from platform_aware_scheduling_tpu.kube.objects import Pod, object_key
from platform_aware_scheduling_tpu.models.batch_scheduler import (
    ClusterState,
    PendingPods,
    observed_scheduling_step,
    score_and_filter,
)
from platform_aware_scheduling_tpu.ops import i64, solveobs
from platform_aware_scheduling_tpu.ops.rules import OP_IDS, RuleSet
from platform_aware_scheduling_tpu.ops.state import TensorStateMirror
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.utils import klog
from platform_aware_scheduling_tpu.utils.quantity import Quantity

TAS_POLICY_LABEL = "telemetry-policy"
DEFAULT_NODE_CAPACITY = 110  # kubelet's default max pods per node


class _InformerGroup:
    """Stop-handle over the planner's pod + node informers."""

    def __init__(self, *informers):
        self._informers = informers

    def stop(self) -> None:
        for informer in self._informers:
            informer.stop()

    def wait_for_cache_sync(self, timeout: float = 30.0) -> bool:
        return all(i.wait_for_cache_sync(timeout) for i in self._informers)


class BatchPlanner:
    """Maintains the batch solution over the current pending set."""

    def __init__(
        self,
        cache: AutoUpdatingCache,
        mirror: TensorStateMirror,
        node_capacity: int = DEFAULT_NODE_CAPACITY,
        solver: str = "greedy",
    ):
        """``solver``: "greedy" reproduces what the sequential scheduler
        would do; "sinkhorn" globally coordinates the batch
        (ops/sinkhorn.py) — strictly an enhancement over the reference.

        ``node_capacity`` is only the fallback for nodes whose allocatable
        pod count hasn't been observed; observed nodes use
        ``allocatable.pods - bound pods`` (kube-scheduler's own NodePods
        predicate semantics), fed by :meth:`node_changed` /
        :meth:`pod_observed` (wired to informers by :meth:`watch`)."""
        self.cache = cache
        self.mirror = mirror
        self.node_capacity = node_capacity
        self.solver = solver
        self._lock = threading.Lock()
        self._pending: Dict[str, Pod] = {}
        # pod key -> (assigned node name, mirror version it was solved at)
        self._plan: Dict[str, Tuple[str, int]] = {}
        self._plan_version = -1
        # cluster capacity state: allocatable pods per node + bound pods
        self._cap_lock = threading.Lock()
        self._node_alloc: Dict[str, int] = {}
        self._bound_pods: Dict[str, str] = {}  # pod key -> node name
        self._bound_counts: Dict[str, int] = {}

    # -- pending-set maintenance ----------------------------------------------

    def pod_added(self, pod: Pod) -> None:
        if pod.spec_node_name or TAS_POLICY_LABEL not in pod.get_labels():
            return
        with self._lock:
            self._pending[object_key(pod)] = pod

    def pod_removed(self, pod: Pod) -> None:
        with self._lock:
            self._pending.pop(object_key(pod), None)
            self._plan.pop(object_key(pod), None)

    def pod_bound(self, pod: Pod) -> None:
        self.pod_removed(pod)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- cluster capacity feed ---------------------------------------------------

    def node_changed(self, node, deleted: bool = False) -> None:
        """Track a node's allocatable pod slots (``status.allocatable.pods``)."""
        with self._cap_lock:
            if deleted:
                self._node_alloc.pop(node.name, None)
                return
            pods = node.allocatable.get("pods")
            if pods is None:
                self._node_alloc.pop(node.name, None)
            else:
                try:
                    alloc, _exact = Quantity(str(pods)).as_int64()
                    self._node_alloc[node.name] = int(alloc)
                except Exception:
                    self._node_alloc.pop(node.name, None)

    def pod_observed(self, pod: Pod, deleted: bool = False) -> None:
        """Track every pod's binding so per-node remaining capacity is
        allocatable − bound (terminated pods free their slot)."""
        key = object_key(pod)
        node = pod.spec_node_name
        active = (
            not deleted and node and pod.phase not in ("Succeeded", "Failed")
        )
        with self._cap_lock:
            prev = self._bound_pods.pop(key, None)
            if prev is not None:
                remaining = self._bound_counts.get(prev, 1) - 1
                if remaining > 0:
                    self._bound_counts[prev] = remaining
                else:
                    self._bound_counts.pop(prev, None)
            if active:
                self._bound_pods[key] = node
                self._bound_counts[node] = self._bound_counts.get(node, 0) + 1

    def _remaining_capacity(self, view) -> np.ndarray:
        """int32 [node_capacity] remaining pod slots per interned node —
        observed nodes use allocatable − bound, unknown nodes fall back to
        the kubelet default (the plan systematically overcommitted hot
        nodes when this was a constant — VERDICT r1)."""
        cap = np.full(view.node_capacity, self.node_capacity, dtype=np.int64)
        with self._cap_lock:
            alloc = dict(self._node_alloc)
            counts = dict(self._bound_counts)
        for name, idx in view.node_index.items():
            if idx < cap.shape[0]:
                a = alloc.get(name, self.node_capacity)
                cap[idx] = a - counts.get(name, 0)
        return np.clip(cap, 0, np.iinfo(np.int32).max).astype(np.int32)

    # -- solve ----------------------------------------------------------------

    def replan(self) -> int:
        """Solve the current pending set; returns the number of planned
        pods.  Called from the sync-period loop (and on demand in tests)."""
        with self._lock:
            pods = list(self._pending.items())
        if not pods:
            with self._lock:
                self._plan = {}
            return 0
        # ONE atomic snapshot: every pod's compiled rule rows must resolve
        # against the same view the solve uses (a metric delete + row reuse
        # mid-loop would silently rebind earlier rows — ADVICE r1)
        policy_keys = {
            (pod.namespace, pod.get_labels().get(TAS_POLICY_LABEL))
            for _key, pod in pods
        }
        policies, view, host_only = self.mirror.policies_with_view(
            list(policy_keys)
        )
        compiled_rows: List[Tuple[str, int, int]] = []  # key, row, op
        for key, pod in pods:
            policy_name = pod.get_labels().get(TAS_POLICY_LABEL)
            compiled = policies.get((pod.namespace, policy_name))
            if compiled is None or compiled.scheduleonmetric_row < 0:
                continue
            if compiled.scheduleonmetric_metric in host_only:
                continue
            compiled_rows.append(
                (key, compiled.scheduleonmetric_row, compiled.scheduleonmetric_op)
            )
        if not compiled_rows:
            with self._lock:
                self._plan = {}
            return 0
        obs = solveobs.ACTIVE
        timer = obs.begin("replan") if obs is not None else None
        n_cap = view.node_capacity
        p = len(compiled_rows)
        metric_row = np.array([r for _, r, _ in compiled_rows], dtype=np.int32)
        op_id = np.array([o for _, _, o in compiled_rows], dtype=np.int32)
        candidates = np.zeros((p, n_cap), dtype=bool)
        candidates[:, : len(view.node_names)] = True
        # dontschedule filtering happens inside scheduling_step; here every
        # known node is a candidate (kube-scheduler's own predicates will
        # re-check its side)
        dontschedule = self._merged_dontschedule(pods, policies)
        remaining = self._remaining_capacity(view)
        if timer is not None:
            timer.mark("snapshot")
        state = ClusterState(
            metric_values=view.values,
            metric_present=view.present,
            dontschedule=dontschedule,
            capacity=jnp.asarray(remaining),
        )
        batch = PendingPods(
            metric_row=jnp.asarray(metric_row),
            op_id=jnp.asarray(op_id),
            candidates=jnp.asarray(candidates),
        )
        if timer is not None:
            timer.mark("transfer")
        if self.solver == "sinkhorn":
            from platform_aware_scheduling_tpu.ops.sinkhorn import (
                sinkhorn_assign_kernel,
            )

            _violating, score, eligible = score_and_filter(state, batch)
            sink = sinkhorn_assign_kernel(score, eligible, state.capacity)
            if timer is not None:
                timer.mark("execute")
            assigned = np.asarray(sink.assignment.node_for_pod)
        else:
            out = observed_scheduling_step(state, batch, timer=timer)
            assigned = np.asarray(out.assignment.node_for_pod)
        if timer is not None:
            timer.mark("readback")
        plan: Dict[str, Tuple[str, int]] = {}
        for i, (key, _row, _op) in enumerate(compiled_rows):
            node_idx = int(assigned[i])
            if 0 <= node_idx < len(view.node_names):
                plan[key] = (view.node_names[node_idx], view.version)
        with self._lock:
            self._plan = plan
            self._plan_version = view.version
        if timer is not None:
            timer.mark("encode")
            timer.done(pods=p, nodes=len(view.node_names))
        klog.v(4).info_s(
            f"batch plan: {len(plan)}/{p} pods assigned", component="planner"
        )
        return len(plan)

    def _merged_dontschedule(self, pods, policies) -> RuleSet:
        """Union of the pending pods' dontschedule rules (deduped), resolved
        against the compiled policies of the replan's atomic snapshot."""
        seen = set()
        rows, ops, targets = [], [], []
        for _key, pod in pods:
            policy_name = pod.get_labels().get(TAS_POLICY_LABEL)
            compiled = policies.get((pod.namespace, policy_name))
            if compiled is None or compiled.dontschedule is None:
                continue
            rs = compiled.dontschedule
            if rs.host_only:
                continue
            for i, name in enumerate(rs.metric_names):
                sig = (int(rs.metric_rows[i]), int(rs.op_ids[i]), int(rs.targets[i]))
                if sig in seen:
                    continue
                seen.add(sig)
                rows.append(sig[0])
                ops.append(sig[1])
                targets.append(sig[2])
        pad = max(8, -(-max(len(rows), 1) // 8) * 8)
        metric_rows = np.zeros(pad, dtype=np.int32)
        op_ids = np.zeros(pad, dtype=np.int32)
        t = np.zeros(pad, dtype=np.int64)
        active = np.zeros(pad, dtype=bool)
        for i, (r, o, tgt) in enumerate(zip(rows, ops, targets)):
            metric_rows[i], op_ids[i], t[i], active[i] = r, o, tgt, True
        t_hi, t_lo = i64.split_int64_np(t)
        return RuleSet(
            metric_row=jnp.asarray(metric_rows),
            op_id=jnp.asarray(op_ids),
            target=i64.I64(hi=jnp.asarray(t_hi), lo=jnp.asarray(t_lo)),
            active=jnp.asarray(active),
        )

    # -- serving --------------------------------------------------------------

    def planned_node(self, pod: Pod) -> Optional[str]:
        """The batch-assigned node for this pod, if the plan is current
        against the mirror (otherwise None -> per-request path)."""
        with self._lock:
            entry = self._plan.get(object_key(pod))
        if entry is None:
            return None
        node, version = entry
        if version != self.mirror.version:
            return None  # cluster state moved since the solve
        return node

    # -- pending-pod feed -------------------------------------------------------

    def watch(self, kube_client):
        """Informers over pods (pending set + per-node bound counts) and
        nodes (allocatable pod slots); returns a handle with ``.stop()``."""
        from platform_aware_scheduling_tpu.kube.informer import (
            DeletedFinalStateUnknown,
            Informer,
            ListWatch,
        )
        from platform_aware_scheduling_tpu.kube.objects import Node

        def on_event(pod: Pod) -> None:
            self.pod_observed(pod)
            if TAS_POLICY_LABEL not in pod.get_labels():
                # the label may have been removed while the pod was pending
                self.pod_removed(pod)
                return
            if pod.spec_node_name or pod.phase in ("Succeeded", "Failed"):
                self.pod_removed(pod)
            else:
                self.pod_added(pod)

        def on_delete(obj) -> None:
            if isinstance(obj, DeletedFinalStateUnknown):
                obj = obj.obj
            if isinstance(obj, Pod):
                self.pod_observed(obj, deleted=True)
                self.pod_removed(obj)

        pod_informer = Informer(
            ListWatch(
                lambda: (kube_client.list_pods(), ""),
                lambda rv: (
                    (etype, Pod(raw)) for etype, raw in kube_client.watch_pods()
                ),
                object_key,
            ),
            on_add=on_event,
            on_update=lambda _old, new: on_event(new),
            on_delete=on_delete,
        )

        def on_node_delete(obj) -> None:
            if isinstance(obj, DeletedFinalStateUnknown):
                obj = obj.obj
            if isinstance(obj, Node):
                self.node_changed(obj, deleted=True)

        node_informer = Informer(
            ListWatch(
                lambda: (kube_client.list_nodes(), ""),
                lambda rv: (
                    (etype, Node(raw)) for etype, raw in kube_client.watch_nodes()
                ),
                lambda node: node.name,
            ),
            on_add=self.node_changed,
            on_update=lambda _old, new: self.node_changed(new),
            on_delete=on_node_delete,
        )
        pod_informer.start()
        node_informer.start()
        return _InformerGroup(pod_informer, node_informer)

    # -- background loop -------------------------------------------------------

    def start(self, period_seconds: float) -> threading.Event:
        stop = threading.Event()

        def loop():
            while not stop.wait(period_seconds):
                try:
                    self.replan()
                except Exception as exc:
                    klog.error("replan failed: %s", exc)

        threading.Thread(target=loop, daemon=True).start()
        return stop

"""Batch planner: the whole-pending-set solve wired into the service.

SURVEY §7 step 4's product form.  kube-scheduler's protocol is one pod
per round-trip; the planner watches pending pods carrying the
``telemetry-policy`` label, solves the ENTIRE set each sync period with
``models/batch_scheduler.scheduling_step``, and lets the per-pod verbs be
answered from the precomputed solution: when Prioritize arrives for a
planned pod, its batch-assigned node gets the top score, steering the
sequential scheduler onto the coordinated plan (capacity-aware placement
the per-pod ordinal scores alone cannot express).

OPT-IN (``--batchPlanner`` on cmd/tas.py): with the planner off the verbs
behave exactly like the reference.  Planner answers degrade gracefully:
unknown pod / stale plan / no assignment -> the ordinary per-request path.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from platform_aware_scheduling_tpu.kube.objects import Pod, object_key
from platform_aware_scheduling_tpu.models.batch_scheduler import (
    ClusterState,
    PendingPods,
    scheduling_step,
    score_and_filter,
)
from platform_aware_scheduling_tpu.ops import i64
from platform_aware_scheduling_tpu.ops.rules import OP_IDS, RuleSet
from platform_aware_scheduling_tpu.ops.state import TensorStateMirror
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache, CacheMissError
from platform_aware_scheduling_tpu.utils import klog

TAS_POLICY_LABEL = "telemetry-policy"
DEFAULT_NODE_CAPACITY = 110  # kubelet's default max pods per node


class BatchPlanner:
    """Maintains the batch solution over the current pending set."""

    def __init__(
        self,
        cache: AutoUpdatingCache,
        mirror: TensorStateMirror,
        node_capacity: int = DEFAULT_NODE_CAPACITY,
        solver: str = "greedy",
    ):
        """``solver``: "greedy" reproduces what the sequential scheduler
        would do; "sinkhorn" globally coordinates the batch
        (ops/sinkhorn.py) — strictly an enhancement over the reference."""
        self.cache = cache
        self.mirror = mirror
        self.node_capacity = node_capacity
        self.solver = solver
        self._lock = threading.Lock()
        self._pending: Dict[str, Pod] = {}
        # pod key -> (assigned node name, mirror version it was solved at)
        self._plan: Dict[str, Tuple[str, int]] = {}
        self._plan_version = -1

    # -- pending-set maintenance ----------------------------------------------

    def pod_added(self, pod: Pod) -> None:
        if pod.spec_node_name or TAS_POLICY_LABEL not in pod.get_labels():
            return
        with self._lock:
            self._pending[object_key(pod)] = pod

    def pod_removed(self, pod: Pod) -> None:
        with self._lock:
            self._pending.pop(object_key(pod), None)
            self._plan.pop(object_key(pod), None)

    def pod_bound(self, pod: Pod) -> None:
        self.pod_removed(pod)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- solve ----------------------------------------------------------------

    def replan(self) -> int:
        """Solve the current pending set; returns the number of planned
        pods.  Called from the sync-period loop (and on demand in tests)."""
        with self._lock:
            pods = list(self._pending.items())
        if not pods:
            with self._lock:
                self._plan = {}
            return 0
        compiled_rows: List[Tuple[str, int, int]] = []  # key, row, op
        view = None
        for key, pod in pods:
            policy_name = pod.get_labels().get(TAS_POLICY_LABEL)
            compiled, view = self.mirror.policy_with_view(
                pod.namespace, policy_name
            )
            if compiled is None or compiled.scheduleonmetric_row < 0:
                continue
            if self.mirror.metric_host_only(compiled.scheduleonmetric_metric):
                continue
            compiled_rows.append(
                (key, compiled.scheduleonmetric_row, compiled.scheduleonmetric_op)
            )
        if not compiled_rows or view is None:
            with self._lock:
                self._plan = {}
            return 0
        n_cap = view.node_capacity
        p = len(compiled_rows)
        metric_row = np.array([r for _, r, _ in compiled_rows], dtype=np.int32)
        op_id = np.array([o for _, _, o in compiled_rows], dtype=np.int32)
        candidates = np.zeros((p, n_cap), dtype=bool)
        candidates[:, : len(view.node_names)] = True
        # dontschedule filtering happens inside scheduling_step; here every
        # known node is a candidate (kube-scheduler's own predicates will
        # re-check its side)
        dontschedule = self._merged_dontschedule(pods)
        state = ClusterState(
            metric_values=view.values,
            metric_present=view.present,
            dontschedule=dontschedule,
            capacity=jnp.full(n_cap, self.node_capacity, dtype=jnp.int32),
        )
        batch = PendingPods(
            metric_row=jnp.asarray(metric_row),
            op_id=jnp.asarray(op_id),
            candidates=jnp.asarray(candidates),
        )
        if self.solver == "sinkhorn":
            from platform_aware_scheduling_tpu.ops.sinkhorn import (
                sinkhorn_assign_kernel,
            )

            _violating, score, eligible = score_and_filter(state, batch)
            sink = sinkhorn_assign_kernel(score, eligible, state.capacity)
            assigned = np.asarray(sink.assignment.node_for_pod)
        else:
            out = scheduling_step(state, batch)
            assigned = np.asarray(out.assignment.node_for_pod)
        plan: Dict[str, Tuple[str, int]] = {}
        for i, (key, _row, _op) in enumerate(compiled_rows):
            node_idx = int(assigned[i])
            if 0 <= node_idx < len(view.node_names):
                plan[key] = (view.node_names[node_idx], view.version)
        with self._lock:
            self._plan = plan
            self._plan_version = view.version
        klog.v(4).info_s(
            f"batch plan: {len(plan)}/{p} pods assigned", component="planner"
        )
        return len(plan)

    def _merged_dontschedule(self, pods) -> RuleSet:
        """Union of the pending pods' dontschedule rules (deduped)."""
        seen = set()
        rows, ops, targets = [], [], []
        for _key, pod in pods:
            policy_name = pod.get_labels().get(TAS_POLICY_LABEL)
            try:
                policy = self.cache.read_policy(pod.namespace, policy_name)
            except CacheMissError:
                continue
            strat = policy.strategies.get("dontschedule")
            compiled, _ = self.mirror.policy_with_view(pod.namespace, policy_name)
            if strat is None or compiled is None or compiled.dontschedule is None:
                continue
            rs = compiled.dontschedule
            if rs.host_only:
                continue
            for i, name in enumerate(rs.metric_names):
                sig = (int(rs.metric_rows[i]), int(rs.op_ids[i]), int(rs.targets[i]))
                if sig in seen:
                    continue
                seen.add(sig)
                rows.append(sig[0])
                ops.append(sig[1])
                targets.append(sig[2])
        pad = max(8, -(-max(len(rows), 1) // 8) * 8)
        metric_rows = np.zeros(pad, dtype=np.int32)
        op_ids = np.zeros(pad, dtype=np.int32)
        t = np.zeros(pad, dtype=np.int64)
        active = np.zeros(pad, dtype=bool)
        for i, (r, o, tgt) in enumerate(zip(rows, ops, targets)):
            metric_rows[i], op_ids[i], t[i], active[i] = r, o, tgt, True
        t_hi, t_lo = i64.split_int64_np(t)
        return RuleSet(
            metric_row=jnp.asarray(metric_rows),
            op_id=jnp.asarray(op_ids),
            target=i64.I64(hi=jnp.asarray(t_hi), lo=jnp.asarray(t_lo)),
            active=jnp.asarray(active),
        )

    # -- serving --------------------------------------------------------------

    def planned_node(self, pod: Pod) -> Optional[str]:
        """The batch-assigned node for this pod, if the plan is current
        against the mirror (otherwise None -> per-request path)."""
        with self._lock:
            entry = self._plan.get(object_key(pod))
        if entry is None:
            return None
        node, version = entry
        if version != self.mirror.version:
            return None  # cluster state moved since the solve
        return node

    # -- pending-pod feed -------------------------------------------------------

    def watch(self, kube_client):
        """Informer over pods feeding the pending set (labelled, unbound,
        not completed)."""
        from platform_aware_scheduling_tpu.kube.informer import (
            DeletedFinalStateUnknown,
            Informer,
            ListWatch,
        )

        def on_event(pod: Pod) -> None:
            if TAS_POLICY_LABEL not in pod.get_labels():
                return
            if pod.spec_node_name or pod.phase in ("Succeeded", "Failed"):
                self.pod_removed(pod)
            else:
                self.pod_added(pod)

        def on_delete(obj) -> None:
            if isinstance(obj, DeletedFinalStateUnknown):
                obj = obj.obj
            if isinstance(obj, Pod):
                self.pod_removed(obj)

        informer = Informer(
            ListWatch(
                lambda: (kube_client.list_pods(), ""),
                lambda rv: (
                    (etype, Pod(raw)) for etype, raw in kube_client.watch_pods()
                ),
                object_key,
            ),
            on_add=on_event,
            on_update=lambda _old, new: on_event(new),
            on_delete=on_delete,
        )
        informer.start()
        return informer

    # -- background loop -------------------------------------------------------

    def start(self, period_seconds: float) -> threading.Event:
        stop = threading.Event()

        def loop():
            while not stop.wait(period_seconds):
                try:
                    self.replan()
                except Exception as exc:
                    klog.error("replan failed: %s", exc)

        threading.Thread(target=loop, daemon=True).start()
        return stop

"""TAS scheduling logic: the Prioritize/Filter/Bind verbs over policy rules.

Reference: telemetry-aware-scheduling/pkg/telemetryscheduler/
telemetryscheduler.go.  Wire behavior is reproduced quirk-for-quirk
(callers depend on it):

  * decode failures and empty node lists return an empty 200 body
    (telemetryscheduler.go:41-48 — the Go handler just returns);
  * a pod without the ``telemetry-policy`` label gets status 400 but the
    handler STILL runs and writes ``[]`` (no return after WriteHeader,
    telemetryscheduler.go:50-53);
  * a nil filter result is 404 with body ``null`` (:170-175);
  * FailedNodes messages carry the CONCRETE violation reason ("policy P:
    metric cpu=93 > threshold 80" — docs/observability.md "Decision
    provenance") where the reference emitted the opaque literal
    "Node violates" (:206); native and host paths produce byte-identical
    strings (tests/test_decisions.py), a deliberate wire improvement
    within the scheduler's contract (FailedNodes values are
    free-form diagnostics);
  * in the legacy Nodes branch FilterResult.NodeNames is built by
    splitting "n1 n2 " on spaces and so carries a trailing empty string
    (:212) — harmless there because the scheduler ignores NodeNames; the
    nodeCacheCapable branch instead emits exactly the passing names (the
    scheduler consumes them and rejects unknown entries);
  * Bind is 404 — TAS does not bind (:179-181).

Two execution paths produce identical wire bytes:

  * **device path** (default): the jitted kernels of ops/scoring.py over the
    TensorStateMirror — one fused XLA pass instead of the per-node Go loop;
  * **host path**: exact-semantics Python (strategies/core.py), used as
    fallback whenever the mirror marks a policy/metric host-only (inexact
    milli conversion, unknown operator) and as the control in tests.

For non-sorting operators the reference's output order is Go map iteration
— randomized per process.  The device path is deterministic (node interning
order), which is within the reference's behavior envelope.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from platform_aware_scheduling_tpu.extender.server import (
    HTTPRequest,
    HTTPResponse,
)
from platform_aware_scheduling_tpu.extender.types import (
    Args,
    FilterResult,
    HostPriority,
    encode_host_priority_list,
)
from platform_aware_scheduling_tpu.kube.objects import Node, Pod
from platform_aware_scheduling_tpu.ops.state import (
    CompiledPolicy,
    DeviceView,
    TensorStateMirror,
)
from platform_aware_scheduling_tpu.ops import solveobs
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache, CacheMissError
from platform_aware_scheduling_tpu.tas import degraded as degraded_mode
from platform_aware_scheduling_tpu.native import get_wirec
from platform_aware_scheduling_tpu.tas.fastpath import PrioritizeFastPath
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicy, TASPolicyRule
from platform_aware_scheduling_tpu.tas.strategies import core, dontschedule
from platform_aware_scheduling_tpu.utils import decisions, events, klog, trace
from platform_aware_scheduling_tpu.utils import labels as shared_labels
from platform_aware_scheduling_tpu.utils.tracing import LatencyRecorder

import jax.numpy as jnp

TAS_POLICY_LABEL = "telemetry-policy"


class _HostArgsShortcut:
    """Probe result marking a host-only-policy request whose candidate
    span is interned: the verb runs the EXACT Python filter flow over
    these Args (built from the native wire view + the universe's
    interned name tuple) instead of re-decoding the full body with
    json.loads.  Wire bytes are identical by construction — the Args
    content matches what the exact decode would produce for every field
    the Filter path reads."""

    __slots__ = ("args",)

    def __init__(self, args: Args):
        self.args = args


class MetricsExtender:
    """extender.Scheduler implementation for TAS
    (reference telemetryscheduler.go:25-34)."""

    def __init__(
        self,
        cache: AutoUpdatingCache,
        mirror: Optional[TensorStateMirror] = None,
        recorder: Optional[LatencyRecorder] = None,
        planner=None,
        node_cache_capable: bool = False,
    ):
        """``node_cache_capable``: serve Prioritize/Filter from
        ``Args.NodeNames`` when ``Args.Nodes`` is absent — the wire mode a
        ``nodeCacheCapable: true`` extender registration receives
        (extender/types.go:44-49; required by GAS, scheduler.go:455-461).
        The reference TAS ignores NodeNames and returns the empty-200
        quirk; that behavior is preserved when this flag is off (the
        default), so large clusters opt in via --nodeCacheCapable."""
        self.cache = cache
        self.mirror = mirror
        self.node_cache_capable = node_cache_capable
        self.recorder = recorder or LatencyRecorder()
        trace.install_jax_hooks()  # compile visibility from process start
        # opt-in tas.planner.BatchPlanner: prioritize answers steer planned
        # pods onto their batch-assigned node (see planner module doc)
        self.planner = planner
        # opt-in rebalance.Rebalancer, set by the service main when
        # --rebalance != off; the front-ends serve its last plan on
        # GET /debug/rebalance (404 while this is None)
        self.rebalancer = None
        # opt-in gang.GangTracker, set by assembly when --gang=on: gang
        # members Filter/Prioritize against their reserved slice, other
        # pods fail gang-held nodes, Bind promotes reservations, and the
        # front-ends serve GET /debug/gangs (404 while this is None).
        # While set, the Filter response cache and the native Prioritize
        # scanner are bypassed — the gang verdict is pod-label-dependent
        # state the span-keyed caches cannot key (docs/gang.md)
        self.gangs = None
        # opt-in forecast.Forecaster, set by assembly when --forecast=on:
        # scheduleonmetric ranks on predicted-at-bind values through the
        # SAME fastpath/host machinery (the forecaster publishes a
        # DeviceView of predicted milli values), decision records carry
        # "predicted cpu=93 (slope +2.1/s)" provenance, and the
        # front-ends serve GET /debug/forecast (404 while this is None).
        # Off (None) keeps snapshot ranking byte-identical to before.
        self.forecaster = None
        # opt-in utils.slo.SLOEngine, set by assembly when --slo=on: the
        # engine reads this extender's recorder + the counter families
        # and judges the declared SLOs over sliding windows; the
        # front-ends serve GET /debug/slo (404 while this is None) and
        # /metrics gains the pas_slo_* gauges.  Off (None) registers no
        # gauges and leaves the wire byte-identical — the engine never
        # touches the request path either way (docs/observability.md
        # "SLOs & error budgets")
        self.slo = None
        # opt-in utils.control.BudgetController, set by assembly when
        # --sloControl=on (requires --slo=on): subscribes to the SLO
        # engine's post-tick hook and steps the attached knobs; the
        # front-ends serve GET /debug/control (404 while this is None)
        # and /metrics gains the pas_control_* families.  Off (None)
        # constructs nothing and leaves the wire byte-identical — the
        # controller only ever mutates knobs other components already
        # read live (docs/observability.md "Budget feedback control")
        self.control = None
        # opt-in utils.record.FlightRecorder, set by assembly when
        # --flightRecorder=on: the verbs append one anonymized arrival
        # event each (universe digest + candidate count, never names),
        # the telemetry refresh pass appends decile summaries, and the
        # front-ends serve GET /debug/record + POST /debug/whatif (404
        # while this is None).  Off (None) costs the verbs a single
        # attribute check and keeps the wire byte-identical — pinned by
        # tests/test_record.py.  NOT self.recorder: that name is the
        # latency-histogram LatencyRecorder above.
        self.flight = None
        # opt-in ops.solveobs.SolveObservatory, set by assembly when
        # --solveObs=on: per-stage device-solve attribution rings +
        # refresh churn telemetry, served at GET /debug/solve (404 while
        # this is None).  The instrumented sites gate on the module
        # global ops.solveobs.ACTIVE (the pipeline spans layers that
        # never see this extender); this attribute only routes the debug
        # endpoint and documents ownership.  Off (None) costs the solve
        # one module-global read and keeps the wire byte-identical —
        # pinned by tests/test_solveobs.py.
        self.solveobs = None
        # opt-in tas.degraded.DegradedModeController, set by assembly:
        # when telemetry goes stale or a circuit opens, Filter fails
        # open/closed per --degradedMode and Prioritize degrades to
        # last-known-good then neutral scores (docs/robustness.md).
        # None (the default) keeps exact reference behavior.
        self.degraded = None
        # opt-in kube.lease.LeaseElector, set by assembly when
        # --leaderElect: leadership state surfaces on /readyz (an
        # informational condition — followers stay ready) and the
        # front-ends serve GET /debug/leader (404 while this is None).
        # Verb behavior is role-independent: every replica serves
        # Filter/Prioritize; only the actuation loops are gated
        # (docs/robustness.md "HA & leader election")
        self.leadership = None
        # opt-in admission.AdmissionPlane, set by assembly when
        # --admission=on: capacity-class Filter failures enqueue into a
        # bounded per-class queue, an otherwise-admissible pod may be
        # HELD behind higher-priority queued work (every candidate fails
        # CODE_ADMISSION_BLOCKED), small gangs backfill a large gang's
        # pending reservation, and the front-ends serve GET
        # /debug/admission (404 while this is None).  While set, the
        # Filter response cache is bypassed — the admission verdict is
        # per-pod queue state the span-keyed cache cannot key
        # (docs/admission.md).  Off (None) costs the verb one attribute
        # check and keeps the wire byte-identical — pinned by
        # tests/test_admission.py.
        self.admission = None
        # opt-in shard.ShardPlane, set by assembly when --shard=on: the
        # mirror holds only OWNED partitions, Filter merges remote
        # partitions' digest violators into the local verdict, Prioritize
        # ranks over local values + remote top-k summaries, and the
        # front-ends serve GET /debug/shard (404 while this is None).
        # While set, the Filter response cache is bypassed — the merged
        # verdict depends on digest freshness the span-keyed cache cannot
        # key (docs/sharding.md).  Off (None) costs the verbs one
        # attribute check and keeps the wire byte-identical — pinned by
        # tests/test_shard.py.
        self.shard = None
        # request-independent ranking/violation caches + byte-fragment
        # encoder (tas/fastpath.py) — the per-request device dispatch and
        # per-node Python objects the round-1 verdict flagged are gone
        self.fastpath = PrioritizeFastPath() if mirror is not None else None
        # /readyz "kernels_warmed": flips true at the end of the first
        # SUCCESSFUL warm pass (a warm that raised leaves it false)
        self._warmed = False
        if mirror is not None:
            # warm the fastpath from the state-refresh threads: every
            # mirror publish precomputes rankings/violations/tables for the
            # new version, so under metric churn (2-5 s syncPeriod,
            # tas-deployment.yaml) no request pays the device dispatch
            mirror.on_state_change.append(self.warm_fastpath)
            self.warm_fastpath()  # cover state written before construction

    # -- fastpath warming ------------------------------------------------------

    def warm_fastpath(self) -> None:
        """Precompute the request-time caches for the mirror's current
        state: one ranking pass per in-use (metric row, op) pair, the
        dontschedule violation sets, and the response-encode table.  Runs
        in whatever thread published the state change (the metric-refresh
        loop in production, reference cmd/main.go:76-78), keeping the
        device dispatch off the request path entirely."""
        fastpath = self.fastpath
        if fastpath is None:
            return
        obs = solveobs.ACTIVE
        warm_t0 = obs.clock() if obs is not None else 0.0
        try:
            policies, view, host_only_map = self.mirror.policies_snapshot()

            def host_only(name: str) -> bool:
                return host_only_map.get(name, False)

            pairs = {
                (compiled.scheduleonmetric_row, compiled.scheduleonmetric_op)
                for compiled in policies.values()
                if self._prioritize_device_eligible(compiled, host_only)
            }
            wirec = get_wirec()
            fastpath.precompute(view, pairs, wirec=wirec)
            for (_ns, name), compiled in policies.items():
                filter_ok = self._filter_device_eligible(compiled, host_only)
                if filter_ok:
                    # one call warms the violation set AND its decoded
                    # provenance (reason strings keyed by policy name)
                    fastpath.violation_reasons(compiled, view, name)
                if self.gangs is None:
                    # pre-render response skeletons for every interned
                    # universe at the NEW state, so the first request of
                    # the sync window still splices (a metric refresh
                    # mints a new violation-set/ranking identity; without
                    # this, one request per window pays the re-render).
                    # Gang mode skips: the skeleton key carries the live
                    # reservation version, which moves between passes.
                    fastpath.warm_skeletons(
                        wirec, compiled, view, name,
                        filter_ok=filter_ok,
                        prioritize_ok=self._prioritize_device_eligible(
                            compiled, host_only
                        ),
                    )
            if self.forecaster is not None:
                # forecast rankings warm AFTER precompute (whose pruning
                # keeps only real-view entries); the forecast view's
                # negative version markers can never collide with them
                self.warm_forecast_rankings()
            self._warmed = True
            if obs is not None:
                # the warm pass is the production solve cadence: one
                # "solve" event per pass into the causal spine, so
                # /debug/explain narratives can place verb answers
                # relative to when their rankings were recomputed
                events.JOURNAL.publish(
                    "solve",
                    "fastpath warmed",
                    data={
                        "pairs": len(pairs),
                        "policies": len(policies),
                        "version": view.version,
                        "duration_us": round(
                            (obs.clock() - warm_t0) * 1e6, 1
                        ),
                    },
                )
        except Exception as exc:  # warming must never break the writer
            klog.error("fastpath warm failed: %s", exc)

    def warm_forecast_rankings(self) -> None:
        """Warm the ranking cache for every device-eligible policy
        against the CURRENT forecast view.  Called from warm_fastpath,
        and — decisively — registered by assembly on the cache's
        refresh-pass hook AFTER the forecaster's own refit subscription:
        warm_fastpath fires on state change MID-pass, before the
        end-of-pass refit replaces the forecast view, so without this
        post-refit pass every fresh fit would go cold to its first
        request.  Never raises."""
        fastpath = self.fastpath
        if self.forecaster is None or fastpath is None:
            return
        try:
            policies, _view, host_only_map = self.mirror.policies_snapshot()

            def host_only(name: str) -> bool:
                return host_only_map.get(name, False)

            for compiled in policies.values():
                if not self._prioritize_device_eligible(compiled, host_only):
                    continue
                fview = self._forecast_rank_view(compiled)
                if fview is not None:
                    fastpath.warm_pairs(
                        fview,
                        [(
                            compiled.scheduleonmetric_row,
                            compiled.scheduleonmetric_op,
                        )],
                    )
        except Exception as exc:  # warming must never break the refresher
            klog.error("forecast ranking warm failed: %s", exc)

    # -- readiness (utils/health.py) -------------------------------------------

    def readiness_conditions(self):
        """The /readyz conditions this extender contributes: kernels
        warmed (device fastpath precomputed at least once) and telemetry
        freshness (cache synced + every registered metric's age within
        bound).  The front-end layers queue headroom on top."""
        conditions = [
            ("kernels_warmed", self._warm_status),
            ("telemetry_fresh", self.cache.telemetry_freshness),
        ]
        if self.degraded is not None:
            # degraded state surfaces on /readyz with its reason — the
            # service keeps serving (degraded), but rollouts see why it
            # is not fully ready (docs/robustness.md)
            conditions.append(
                ("degraded_mode", self.degraded.readiness_condition)
            )
        if self.leadership is not None:
            # informational: always ok (followers serve traffic at full
            # quality), the reason names the role and fencing token
            conditions.append(
                ("leadership", self.leadership.readiness_condition)
            )
        if self.slo is not None:
            # informational: always ok — a burning SLO pages an operator
            # via pas_slo_burn_rate; yanking the replica from the Service
            # would only burn the availability SLO faster
            conditions.append(("slo_burn", self.slo.readiness_condition))
        return conditions

    def _warm_status(self):
        if self.fastpath is None:
            return True, "host-only mode (no device path to warm)"
        if self._warmed:
            return True, "fastpath warmed"
        return False, "fastpath warm has not completed"

    def warm_batch(self, path: str, requests: List[HTTPRequest]) -> int:
        """Serving micro-batch hook (serving/batch.py): warm every device
        artifact the coalesced batch needs, so the per-request demux that
        follows serves entirely from caches — a batch of N concurrent
        requests costs a handful of device solves, not N.  Prioritize
        batches warm ALL needed rankings in ONE fused dispatch per state
        view (fastpath.warm_rankings_batched); Filter batches warm one
        violation set per distinct policy (each request-independent and
        cached thereafter).  Responses stay byte-identical to the
        per-request path because only cache WARMTH changes, never the
        encode path.  Returns the number of device computations actually
        performed (0 = everything already warm).  Must never raise: any
        trouble degrades to the per-request path, which owns correctness."""
        if self.fastpath is None:
            return 0
        wirec = get_wirec()
        pair_groups: Dict[int, tuple] = {}  # id(view) -> (view, set of pairs)
        filter_policies: Dict[tuple, tuple] = {}
        for request in requests:
            try:
                label = None
                namespace = ""
                if wirec is not None:
                    parsed = wirec.parse_prioritize(request.body)
                    label = parsed.policy_label
                    namespace = parsed.pod_namespace or ""
                else:
                    import json

                    obj = json.loads(request.body)
                    pod = obj.get("Pod") or obj.get("pod") or {}
                    md = pod.get("metadata") or {}
                    label = (md.get("labels") or {}).get(TAS_POLICY_LABEL)
                    namespace = md.get("namespace") or ""
                if not label:
                    continue
                policy = self.cache.read_policy(namespace, label)
                compiled, view = self._device_policy(policy)
                if compiled is None:
                    continue
                if path.endswith("/prioritize"):
                    if self._prioritize_device_eligible(
                        compiled, self.mirror.metric_host_only
                    ):
                        _, pairs = pair_groups.setdefault(
                            id(view), (view, set())
                        )
                        pairs.add(
                            (
                                compiled.scheduleonmetric_row,
                                compiled.scheduleonmetric_op,
                            )
                        )
                elif path.endswith("/filter"):
                    if self._filter_device_eligible(
                        compiled, self.mirror.metric_host_only
                    ):
                        filter_policies[(namespace, label)] = (compiled, view)
            except Exception:
                continue  # malformed member: the per-request path answers it
        solves = 0
        try:
            for view, pairs in pair_groups.values():
                if self.fastpath.warm_rankings_batched(view, pairs):
                    solves += 1
            for compiled, view in filter_policies.values():
                solves += self.fastpath.warm_violations(compiled, view)
        except Exception as exc:
            klog.error("batch warm failed, per-request path serves: %s", exc)
        return solves

    # -- verbs ----------------------------------------------------------------

    def metrics_text(self) -> str:
        """The /metrics provider for this extender: verb latency
        histograms + the process-wide path-attribution and JAX compile
        counters (utils/trace.py exposition), plus — only while an SLO
        engine is wired — its pas_slo_* gauges (the engine owns its own
        CounterSet precisely so --slo=off emits nothing)."""
        counter_sets = [self.slo.counters] if self.slo is not None else []
        if self.control is not None:
            counter_sets.append(self.control.counters)
        if self.flight is not None:
            counter_sets.append(self.flight.counters)
        if self.admission is not None:
            counter_sets.append(self.admission.counters)
        if self.shard is not None:
            counter_sets.append(self.shard.counters)
        return trace.exposition(
            recorders=[self.recorder], counter_sets=counter_sets
        )

    def _record_flight_verb(self, verb: str, request: HTTPRequest) -> None:
        """One anonymized arrival event in the verb's finally: the
        universe digest + candidate count stashed by the wire path (or
        nulls — the recorder never hashes names itself) and the gang
        size stashed by the exact decode.  Must never raise into the
        verb."""
        try:
            uid, candidates = getattr(
                request, "flight_universe", (None, 0)
            )
            self.flight.record_verb(
                verb,
                uid,
                candidates,
                getattr(request, "flight_gang", 0),
            )
        except Exception as exc:
            klog.error("flight record failed: %r", exc)

    def _stash_flight_exact(
        self, request: HTTPRequest, args, candidates: Optional[int] = None
    ) -> None:
        """Exact-path stash for the flight recorder: candidate count
        (unless the wire path already stashed an interned key) and the
        pod's gang size — the one pod-shape label a capture keeps."""
        try:
            if not hasattr(request, "flight_universe"):
                if candidates is None:
                    candidates = len(self._candidate_names(args))
                request.flight_universe = (None, int(candidates))
            gang = args.pod.get_labels().get(shared_labels.GANG_SIZE_LABEL)
            if gang:
                request.flight_gang = int(gang)
        except Exception:
            pass

    def prioritize(self, request: HTTPRequest) -> HTTPResponse:
        start = time.perf_counter()
        span = trace.of(request)
        span.set("verb", "prioritize")
        try:
            if self.degraded is not None:
                action, reason = self.degraded.prioritize_decision()
                if action == degraded_mode.ACTION_NEUTRAL:
                    # telemetry too stale even for last-known-good:
                    # neutral priorities (every candidate scored equally)
                    # keep the scheduler unblocked without letting a
                    # stale ranking mis-order placements
                    span.set("degraded", reason)
                    span.set("path", "neutral")
                    return self._neutral_prioritize(request, span)
                if action == degraded_mode.ACTION_LAST_KNOWN_GOOD:
                    span.set("degraded", reason)  # serving retained scores
            if self.shard is not None:
                # scatter/gather: local partitions from the mirror,
                # remote partitions from fresh digests; None falls
                # through to the full-world paths (which then answer
                # from whatever the partition-scoped mirror holds)
                response = self._shard_prioritize(request, span)
                if response is not None:
                    return response
            # the native path attributes itself (native vs native_host —
            # partition counters, see trace.py declarations)
            response = self._prioritize_native(request)
            if response is not None:
                return response
            trace.COUNTERS.inc("pas_prioritize_exact_total")
            span.set("path", "exact")
            klog.v(2).info_s("Received prioritize request", component="extender")
            decoded = self._decode_prioritize_args(request, span)
            if isinstance(decoded, HTTPResponse):
                return decoded
            args, names, status = decoded
            if self.flight is not None:
                self._stash_flight_exact(request, args, candidates=len(names))
            span.set("pod", f"{args.pod.namespace}/{args.pod.name}")
            body = self._prioritize_body(args, names, span=span)
            events.JOURNAL.publish(
                "verdict",
                "prioritize",
                request_id=span.trace_id,
                pod=f"{args.pod.namespace}/{args.pod.name}",
                data={
                    "candidates": len(names),
                    "path": str(span.attrs.get("path", "exact")),
                },
            )
            return HTTPResponse.json(body, status=status)
        finally:
            self.recorder.observe(
                "prioritize", time.perf_counter() - start,
                trace_id=span.trace_id,
            )
            if self.flight is not None:
                self._record_flight_verb("prioritize", request)

    def _decode_prioritize_args(self, request: HTTPRequest, span):
        """The exact path's decode quirks, shared with the degraded
        neutral path so they can never drift: decode failure / empty
        candidate list -> empty 200; missing policy label -> 400 but the
        verb still answers (telemetryscheduler.go:41-54).  Returns
        ``(args, names, status)`` or the quirk HTTPResponse."""
        with span.stage("decode"):
            args = self._decode(request)
        if args is None:
            return HTTPResponse()
        names = self._candidate_names(args)
        if not names:
            klog.v(2).info_s(
                "bad extender arguments. No nodes in list", component="extender"
            )
            return HTTPResponse()
        status = 200
        if TAS_POLICY_LABEL not in args.pod.get_labels():
            klog.v(2).info_s("no policy associated with pod", component="extender")
            status = 400  # and still prioritize (telemetryscheduler.go:50-54)
        return args, names, status

    def _neutral_prioritize(self, request: HTTPRequest, span) -> HTTPResponse:
        """Degraded Prioritize: every candidate gets the same score, on
        top of the exact path's shared decode quirks."""
        decoded = self._decode_prioritize_args(request, span)
        if isinstance(decoded, HTTPResponse):
            return decoded
        args, names, status = decoded
        with span.stage("encode"):
            body = encode_host_priority_list(
                [HostPriority(host=name, score=0) for name in names]
            )
        self._record_prioritize(
            span, args.pod.namespace, args.pod.name,
            args.pod.get_labels().get(TAS_POLICY_LABEL, ""),
            "neutral", None, len(names),
        )
        return HTTPResponse.json(body, status=status)

    def filter(self, request: HTTPRequest) -> HTTPResponse:
        start = time.perf_counter()
        span = trace.of(request)
        span.set("verb", "filter")
        try:
            klog.v(2).info_s("Filter request received", component="extender")
            degraded_action = None
            if self.degraded is not None:
                action, reason = self.degraded.filter_decision()
                if action in (
                    degraded_mode.ACTION_FAIL_OPEN,
                    degraded_mode.ACTION_FAIL_CLOSED,
                ):
                    # fail open/closed per --degradedMode; the response
                    # cache must not serve (its entries were keyed on
                    # healthy state), so the probe is skipped -> bypass
                    degraded_action = action
                    span.set("degraded", reason)
            probe = None
            if degraded_action is None:
                # gang mode: the cache serves NON-gang pods, keyed on
                # (gang reservation version, pod gang id) — any body
                # that carries the gang group label at all may belong
                # to a member (whose Filter has reservation side
                # effects: TTL refresh, membership) and bypasses
                gang_token = None
                if self.gangs is not None:
                    gang_token = self._gang_cache_token(request)
                if (
                    (self.gangs is None or gang_token is not None)
                    and self.admission is None
                    and (
                        self.shard is None
                        or not self.shard.remote_holds_possible()
                    )
                ):
                    # admission mode bypasses entirely: whether a pod is
                    # admitted, held, or queued is per-pod queue state
                    # that changes between identical request bodies;
                    # shard mode bypasses only while a remote digest
                    # actually lists violators — otherwise the merged
                    # verdict equals the local one for ANY candidate
                    # set, so the native fastpath (and its ~1/P-size
                    # problem) serves sharded Filter at full speed
                    # (shard/plane.py remote_holds_possible)
                    with span.stage("cache_probe"):
                        probe = self._filter_cache_probe(
                            request, gang_token
                        )
            # hit/miss attribution happens inside the probe, at its
            # non-None return sites only (it alone can tell a true
            # span-cache hit from the native encode that merely SEEDS the
            # cache); every None return — uncacheable OR device trouble —
            # is a bypass, so hit+miss+bypass counts each request once
            if isinstance(probe, HTTPResponse):
                return probe
            args_override = None
            if isinstance(probe, _HostArgsShortcut):
                # host-only policy over an interned span: the exact flow
                # below runs on Args built from the wire view — same
                # bytes out, no 10k-name json.loads in (still counted a
                # bypass: the span caches cannot serve host verdicts)
                args_override = probe.args
                probe = None
            if probe is None:
                span.set("filter_cache", "bypass")
                trace.COUNTERS.inc("pas_filter_cache_bypass_total")
            with span.stage("decode"):
                args = (
                    args_override
                    if args_override is not None
                    else self._decode(request)
                )
            if args is None:
                return HTTPResponse()
            if self.flight is not None:
                self._stash_flight_exact(request, args)
            gang_codes: Dict[str, int] = {}
            with span.stage("kernel"):
                result = self._filter_nodes(
                    args, degraded=degraded_action, gang_codes=gang_codes
                )
            if result is None:
                klog.v(2).info_s("No filtered nodes returned", component="extender")
                return HTTPResponse.json(b"null\n", status=404)
            span.set("pod", f"{args.pod.namespace}/{args.pod.name}")
            if self.shard is not None:
                with span.stage("shard"):
                    result = self._shard_review(args, result, span)
            if self.admission is not None:
                with span.stage("admission"):
                    result = self._admission_review(
                        args, result, gang_codes, degraded_action,
                        span.trace_id,
                    )
            with span.stage("encode"):
                body = result.to_json()
            if probe is not None:
                parsed, violations, use_node_names, gang_version, universe = (
                    probe
                )
                self.fastpath.filter_store(
                    violations, use_node_names, parsed, body,
                    len(result.failed_nodes), gang_version,
                    universe=universe,
                )
            if decisions.DECISIONS.enabled:
                path = span.attrs.get("filter_cache", "exact")
                reason_code = decisions.CODE_RULE_VIOLATION
                if degraded_action == degraded_mode.ACTION_FAIL_CLOSED:
                    path = "fail_closed"
                    reason_code = decisions.CODE_FAIL_CLOSED
                elif degraded_action == degraded_mode.ACTION_FAIL_OPEN:
                    path = "fail_open"
                candidates = self._candidate_names(args)
                reason_counts = None
                if gang_codes:
                    # a gang overlay mixes reason classes in one request:
                    # count each failed node under its own code so the
                    # per-reason counters stay exact
                    reason_counts = {}
                    for name in result.failed_nodes:
                        code = gang_codes.get(name, reason_code)
                        reason_counts[code] = reason_counts.get(code, 0) + 1
                decisions.DECISIONS.record_filter(
                    request_id=span.trace_id,
                    pod_namespace=args.pod.namespace,
                    pod_name=args.pod.name,
                    policy=args.pod.get_labels().get(TAS_POLICY_LABEL, ""),
                    path=path,
                    candidates=len(candidates),
                    filtered=len(result.failed_nodes),
                    violating=dict(result.failed_nodes),
                    violating_scope="request",
                    reason_code=reason_code,
                    reason_counts=reason_counts,
                )
            events.JOURNAL.publish(
                "verdict",
                "filter",
                request_id=span.trace_id,
                pod=f"{args.pod.namespace}/{args.pod.name}",
                data={
                    "failed": len(result.failed_nodes),
                    "path": str(span.attrs.get("filter_cache", "exact")),
                },
            )
            return HTTPResponse.json(body)
        finally:
            self.recorder.observe(
                "filter", time.perf_counter() - start,
                trace_id=span.trace_id,
            )
            if self.flight is not None:
                self._record_flight_verb("filter", request)

    def _admission_review(
        self, args, result, gang_codes, degraded_action, request_id=""
    ):
        """Consult the admission plane over one computed Filter verdict
        (admission/plane.py review contract): None keeps the verdict
        unchanged (admitted, or a failure that was enqueued/judged as a
        side effect); a replacement ``(failed, codes)`` pair means HELD
        — every candidate fails with CODE_ADMISSION_BLOCKED.  The held
        codes merge into ``gang_codes`` so the decision record counts
        holds under their own reason family.  Fails open: plane trouble
        must never take down Filter."""
        try:
            default_code = decisions.CODE_RULE_VIOLATION
            if degraded_action == degraded_mode.ACTION_FAIL_CLOSED:
                default_code = decisions.CODE_FAIL_CLOSED
            failed = dict(result.failed_nodes)
            codes = {
                name: gang_codes.get(name, default_code)
                for name in failed
            }
            verdict = self.admission.review(
                args.pod, self._candidate_names(args), failed, codes,
                request_id=request_id,
            )
        except Exception as exc:
            klog.error("admission review failed open: %r", exc)
            return result
        if verdict is None:
            return result
        held, held_codes = verdict
        gang_codes.update(held_codes)
        merged = dict(result.failed_nodes)
        merged.update(held)
        nodes = result.nodes
        if nodes is not None:
            nodes = [n for n in nodes if n.name not in held]
        node_names = result.node_names
        if node_names is not None:
            node_names = [n for n in node_names if n not in held]
        return FilterResult(
            nodes=nodes,
            node_names=node_names,
            failed_nodes=merged,
            error=result.error,
        )

    def _shard_review(self, args, result, span):
        """Merge REMOTE partitions' digest violators into the locally
        computed Filter verdict (shard/plane.py review contract): the
        local solve already judged every owned-partition candidate; a
        fresh remote digest contributes its violator set; a
        missing/stale/fenced digest contributes nothing — fail open, the
        node passes on remote facts and the degradation is visible on
        the gather counters + digest_stale events.  Plane trouble must
        never take down Filter."""
        try:
            policy_name = args.pod.get_labels().get(TAS_POLICY_LABEL, "")
            if not policy_name:
                return result
            held, consulted = self.shard.review_filter(
                policy_name, self._candidate_names(args)
            )
            span.set("shard_remote_partitions", str(consulted))
            held_set = set(held) - set(result.failed_nodes)
            if not held_set:
                return result
            merged = dict(result.failed_nodes)
            for name in held_set:
                merged[name] = (
                    f"node {name} violates policy {policy_name} "
                    "(remote partition digest)"
                )
            nodes = result.nodes
            if nodes is not None:
                nodes = [n for n in nodes if n.name not in held_set]
            node_names = result.node_names
            if node_names is not None:
                node_names = [n for n in node_names if n not in held_set]
            return FilterResult(
                nodes=nodes,
                node_names=node_names,
                failed_nodes=merged,
                error=result.error,
            )
        except Exception as exc:
            klog.error("shard filter review failed open: %r", exc)
            return result

    def _shard_prioritize(self, request: HTTPRequest, span):
        """Scatter/gather Prioritize: rank candidates over the merged
        {node: milli} map — owned partitions from the mirror's exact
        values, remote partitions from digest top-k summaries — with the
        host path's ordering semantics (GreaterThan descending, LessThan
        ascending, anything else input order; nodes absent from the
        merged map are dropped exactly like nodes absent from metric
        data).  Returns None to fall through: gang pods (the overlay
        owns the exact path), unresolvable policy/rule, an unusable
        local view, or any plane trouble — a local-only full-world
        answer beats no answer."""
        try:
            if self.gangs is not None:
                return None
            decoded = self._decode_prioritize_args(request, span)
            if isinstance(decoded, HTTPResponse):
                return decoded
            args, names, status = decoded
            try:
                policy = self._policy_from_pod(args.pod)
            except Exception:
                return None
            rule = self._scheduling_rule(policy)
            if rule is None:
                return None
            merged = self.shard.gather_metric(rule.metricname, names)
            if merged is None:
                return None
            entries = [(name, merged[name]) for name in names if name in merged]
            if rule.operator == "GreaterThan":
                entries.sort(key=lambda kv: kv[1], reverse=True)
            elif rule.operator == "LessThan":
                entries.sort(key=lambda kv: kv[1])
            result = self._apply_plan(
                args.pod,
                [
                    HostPriority(host=name, score=10 - i)
                    for i, (name, _milli) in enumerate(entries)
                ],
            )
            span.set("path", "shard")
            span.set("pod", f"{args.pod.namespace}/{args.pod.name}")
            with span.stage("encode"):
                body = encode_host_priority_list(result)
            self._record_prioritize(
                span, args.pod.namespace, args.pod.name, policy.name,
                "shard", rule, len(names), result=result,
            )
            events.JOURNAL.publish(
                "verdict",
                "prioritize",
                request_id=span.trace_id,
                pod=f"{args.pod.namespace}/{args.pod.name}",
                data={"candidates": len(names), "path": "shard"},
            )
            return HTTPResponse.json(body, status=status)
        except Exception as exc:
            klog.error("shard prioritize failed open: %r", exc)
            return None

    def _gang_cache_token(self, request: HTTPRequest):
        """(reservation version, held map) when this request may use the
        Filter response cache under gang mode; None bypasses.  A body
        mentioning the GANG SIZE label at all may belong to a member —
        the native wire view exposes no pod labels beyond the policy, and
        a member's Filter has reservation side effects (TTL refresh,
        membership) a cached response would skip — so only size-label-
        free bodies are cacheable.  The key is ``pas-gang-size``, not
        ``pas-workload-group``: gang membership requires BOTH
        (labels.gang_id_for), and the group label alone is the
        rebalancer's min-available grouping that ordinary non-gang
        workloads carry — those must keep their cache hits.  Fails open
        to a bypass on any trouble."""
        try:
            if shared_labels.GANG_SIZE_LABEL.encode() in request.body:
                return None
            return self.gangs.cache_token()
        except Exception as exc:
            klog.error("gang cache token failed, cache bypass: %s", exc)
            return None

    def _filter_cache_probe(self, request: HTTPRequest, gang_token=None):
        """Filter response reuse (same burst-amortization as Prioritize's
        span cache): a cached HTTPResponse on hit; a (parsed, violations,
        use_node_names, gang_version) token when cacheable but missed
        (the verb stores its exact Python-built bytes under that key);
        None when the request isn't cacheable (host-only policy, odd
        shapes, no native scanner) — the exact path then owns the
        response alone.

        Correctness: the key pairs the request's raw candidate-span bytes
        (memcmp, zero false positives) with the IDENTITY of the device
        violation frozenset — any state change produces a new frozenset,
        so stale bytes can never match.  Under gang mode
        (``gang_token``), the verdict additionally reflects gang-held
        nodes: the violation set/reasons are the MERGED overlay
        (fastpath.gang_merged) and the key carries the reservation
        version, so a reservation change misses instead of serving a
        stale verdict."""
        if self.fastpath is None:
            return None
        wirec = get_wirec()
        if wirec is None:
            return None
        span = trace.of(request)
        try:
            parsed = wirec.parse_prioritize(request.body)
            use_node_names = False
            if not parsed.nodes_present or parsed.num_nodes == 0:
                if (
                    self.node_cache_capable
                    and parsed.node_names_present
                    and parsed.num_node_names > 0
                ):
                    use_node_names = True
                else:
                    return None
            policy_name = parsed.policy_label
            if policy_name is None:
                return None
            try:
                policy = self.cache.read_policy(
                    parsed.pod_namespace or "", policy_name
                )
            except Exception:
                return None
            compiled, view = self._device_policy(policy)
            if compiled is None or not self._device_filter_ok(compiled):
                # host-only policy: the span caches cannot serve (the
                # verdict is host-computed), but an interned span still
                # spares the exact path its full json.loads
                return self._host_filter_shortcut(
                    wirec, parsed, use_node_names, span
                )
            # one call resolves the violation set AND its decoded per-node
            # provenance (the shared reason map the wire FailedNodes and
            # the decision records both reference)
            explained = self.fastpath.violation_reasons(
                compiled, view, policy.name
            )
            if explained is None:
                return None
            violations, reasons, _indexes = explained
            with span.stage("intern"):
                universe = self.fastpath.universe_probe(
                    wirec, parsed, use_node_names
                )
            gang_version = None
            reason_table = None
            if gang_token is not None:
                gang_version, held = gang_token
                if held:
                    # merge the reservation overlay into the verdict the
                    # cached bytes will encode (non-gang pods fail
                    # gang-held nodes with the concrete gang reason)
                    violations, reasons, reason_table = (
                        self.fastpath.gang_merged(
                            compiled, view, policy.name, violations,
                            reasons, held, gang_version,
                        )
                    )
            candidates = (
                parsed.num_node_names if use_node_names else parsed.num_nodes
            )
            if self.flight is not None:
                # the anonymized arrival key for the verb's finally: the
                # interned digest (or None on a cold span) + the count —
                # computed here where both already exist, O(1)
                request.flight_universe = (
                    universe.uid if universe is not None else None,
                    int(candidates),
                )
            cached = self.fastpath.filter_lookup(
                violations, use_node_names, parsed, gang_version,
                universe=universe,
            )
            if cached is not None:
                body, n_failed = cached
                span.set("filter_cache", "hit")
                trace.COUNTERS.inc("pas_filter_cache_hit_total")
                self._record_device_filter(
                    span, parsed, policy_name, "cache_hit",
                    candidates, n_failed, reasons,
                )
                return HTTPResponse.json(body)
            if use_node_names and hasattr(wirec, "filter_encode"):
                # span-cache miss, NodeNames mode: build the response
                # natively (row lookup + violation partition + byte
                # assembly in C) instead of paying the exact path's
                # full Python decode; the result seeds the span cache.
                # With an interned universe the partition runs over its
                # cached row map (filter_respond — zero hashing) and the
                # body seeds the skeleton layer instead.  The miss
                # counts ONLY once the encode succeeded — a raise here
                # lands in the outer except -> None -> the caller counts
                # it a bypass, never miss+bypass
                body, n_failed = self.fastpath.filter_parsed(
                    wirec, view, parsed, violations, compiled, policy.name,
                    reason_table=reason_table,
                    universe=universe if use_node_names else None,
                )
                self.fastpath.filter_store(
                    violations, use_node_names, parsed, body, n_failed,
                    gang_version, universe=universe,
                )
                span.set("filter_cache", "miss")
                trace.COUNTERS.inc("pas_filter_cache_miss_total")
                self._record_device_filter(
                    span, parsed, policy_name, "native",
                    candidates, n_failed, reasons,
                )
                return HTTPResponse.json(body)
            # cacheable but missed: the exact path builds (and stores) the
            # response via the returned token — still a miss
            span.set("filter_cache", "miss")
            trace.COUNTERS.inc("pas_filter_cache_miss_total")
            return parsed, violations, use_node_names, gang_version, universe
        except (ValueError, TypeError):
            return None
        except Exception as exc:
            # device trouble (XlaRuntimeError, OOM, ...) must never fail
            # the verb: degrade to the exact path, whose host fallback
            # owns the response — same invariant Prioritize keeps
            klog.error("filter cache probe failed, exact path: %s", exc)
            return None

    def _host_filter_shortcut(
        self, wirec, parsed, use_node_names: bool, span
    ) -> Optional[_HostArgsShortcut]:
        """Args for a host-only-policy Filter over an interned span, or
        None (exact decode serves).  Only NodeNames-mode bodies qualify —
        a Nodes-mode response echoes the request's node OBJECTS, which
        the native wire view does not retain.  The returned Args feed
        the unchanged exact flow (_filter_nodes, violated_details), so
        bytes match the exact path by construction; the interned name
        tuple replaces a per-request 10k-string json.loads."""
        if not use_node_names or self.fastpath is None:
            return None
        with span.stage("intern"):
            universe = self.fastpath.universe_probe(
                wirec, parsed, use_node_names
            )
        if universe is None:
            return None
        return _HostArgsShortcut(Args.from_parsed(parsed, universe.names()))

    def _record_device_filter(
        self, span, parsed, policy_name, path, candidates, n_failed, reasons
    ) -> None:
        """Decision record for the device Filter paths: O(1) — per-node
        detail is the SHARED per-state reason map, counts come from the
        native encoder / the response-cache entry."""
        if not decisions.DECISIONS.enabled:
            return
        decisions.DECISIONS.record_filter(
            request_id=span.trace_id,
            pod_namespace=parsed.pod_namespace or "",
            pod_name=parsed.pod_name or "",
            policy=policy_name,
            path=path,
            candidates=int(candidates),
            filtered=int(n_failed),
            violating=reasons,
            violating_scope="policy_state",
        )

    def bind(self, request: HTTPRequest) -> HTTPResponse:
        # TAS does not implement Bind (telemetryscheduler.go:179-181) —
        # the 404 wire behavior is untouched, but the body (the real
        # kube-scheduler POSTs BindingArgs regardless) is outcome
        # feedback: which node the pod actually landed on closes the
        # pod's open decision records AND promotes its gang reservation
        # toward fully-bound (gang/group.py observe_bind)
        if (
            decisions.DECISIONS.enabled
            or self.gangs is not None
            or self.admission is not None
        ) and request.body:
            try:
                from platform_aware_scheduling_tpu.extender.types import (
                    BindingArgs,
                )

                args = BindingArgs.from_json(request.body)
                if args.pod_name and args.node:
                    # verb + correlation attrs on the span: its completion
                    # becomes the chain-closing "bind responded" wire
                    # event in the causal spine (utils/events.py), 404
                    # status and all — the 404 IS the wire response here
                    span = trace.of(request)
                    span.set("verb", "bind")
                    span.set(
                        "pod", f"{args.pod_namespace}/{args.pod_name}"
                    )
                    span.set("node", args.node)
                    if decisions.DECISIONS.enabled:
                        decisions.DECISIONS.observe_bind(
                            args.pod_namespace, args.pod_name, args.node
                        )
                    if self.gangs is not None:
                        self.gangs.observe_bind(
                            args.pod_namespace, args.pod_name, args.node
                        )
                    if self.admission is not None:
                        self.admission.observe_bind(
                            args.pod_namespace, args.pod_name
                        )
                    events.JOURNAL.publish(
                        "verdict",
                        "bind observed",
                        request_id=trace.of(request).trace_id,
                        pod=f"{args.pod_namespace}/{args.pod_name}",
                        node=args.node,
                    )
            except Exception:
                pass  # feedback is best-effort; the verb stays a 404
        return HTTPResponse(status=404)

    # -- native fast path ------------------------------------------------------

    def _prioritize_native(self, request: HTTPRequest) -> Optional[HTTPResponse]:
        """Serve Prioritize through the _wirec zero-copy scanner when the
        body has the common well-formed shape; None -> exact Python path
        (which owns every decode-failure/empty-list wire quirk).  Byte
        parity between the two is pinned by tests/test_wirec.py.

        The whole native body is guarded by ValueError (which covers
        JSONDecodeError, UnicodeDecodeError, and UnicodeEncodeError): the
        scanner validates escapes/UTF-8 at parse time (wirec.c
        scan_string), so most malformed bodies fail the parse up front —
        but slice materialization can still raise on inputs the scan
        cannot reject, e.g. a ``\\u``-escaped lone surrogate whose
        materialized str cannot UTF-8-encode for the name-table lookup.
        Either way the request must fall back to the exact path, never
        drop the connection (round-2 advisor finding)."""
        if self.gangs is not None and (
            shared_labels.GANG_SIZE_LABEL.encode() in request.body
        ):
            # the parsed wire view exposes no pod gang labels, so the
            # native scanner cannot tell a gang member apart — a body
            # that mentions the gang SIZE label at all serves through
            # the exact path, whose overlay can.  Size-label-free bodies
            # are provably non-gang (membership requires pas-gang-size,
            # labels.gang_id_for — the group label alone is ordinary
            # rebalance grouping), and a non-gang pod's Prioritize never
            # consults reservations (prioritize_overlay returns None
            # before any side effect), so the native path stays exact
            # (docs/gang.md)
            return None
        if self.fastpath is None:
            return None
        wirec = get_wirec()
        if wirec is None:
            return None
        try:
            return self._prioritize_native_inner(wirec, request)
        except (ValueError, TypeError):
            return None

    def _prioritize_native_inner(
        self, wirec, request: HTTPRequest
    ) -> Optional[HTTPResponse]:
        span = trace.of(request)
        # parse errors (ValueError/TypeError) propagate to the outer guard
        with span.stage("decode"):
            parsed = wirec.parse_prioritize(request.body)
        use_node_names = False
        if not parsed.nodes_present or parsed.num_nodes == 0:
            if (
                self.node_cache_capable
                and parsed.node_names_present
                and parsed.num_node_names > 0
            ):
                use_node_names = True
            else:
                return None  # empty-200 quirks belong to the exact path
        status = 200
        policy_name = parsed.policy_label
        if policy_name is None:
            status = 400  # no label: 400 but still prioritize (-> empty)
            trace.COUNTERS.inc("pas_prioritize_native_total")
            return HTTPResponse.json(encode_host_priority_list([]), status)
        namespace = parsed.pod_namespace or ""
        try:
            policy = self.cache.read_policy(namespace, policy_name)
        except Exception:
            trace.COUNTERS.inc("pas_prioritize_native_total")
            return HTTPResponse.json(encode_host_priority_list([]), status)
        rule = self._scheduling_rule(policy)
        if rule is None:
            trace.COUNTERS.inc("pas_prioritize_native_total")
            return HTTPResponse.json(encode_host_priority_list([]), status)
        pod = Pod(
            {"metadata": {"name": parsed.pod_name or "", "namespace": namespace}}
        )
        # correlation key for the causal spine: the native path must
        # stamp the span and publish its verdict exactly like the exact
        # path below, or /debug/explain loses the score step for every
        # fastpath-served pod
        pod_key = f"{namespace}/{parsed.pod_name or ''}"
        span.set("pod", pod_key)
        planned = (
            self.planner.planned_node(pod) if self.planner is not None else None
        )
        compiled, view = self._device_policy(policy)
        candidates = (
            parsed.num_node_names if use_node_names else parsed.num_nodes
        )
        with span.stage("intern"):
            universe = self.fastpath.universe_probe(
                wirec, parsed, use_node_names
            )
        if self.flight is not None:
            request.flight_universe = (
                universe.uid if universe is not None else None,
                int(candidates),
            )
        if compiled is not None and self._device_prioritize_ok(compiled, rule):
            try:
                rank_view = self._forecast_rank_view(compiled) or view
                body = self.fastpath.prioritize_parsed(
                    wirec, compiled, rank_view, parsed, planned,
                    use_node_names, span=span, universe=universe,
                )
                span.set("path", "native")
                if rank_view is not view:
                    span.set("ranking", "forecast")
                trace.COUNTERS.inc("pas_prioritize_native_total")
                self._record_prioritize(
                    span, namespace, parsed.pod_name or "", policy_name,
                    "native", rule, int(candidates), planned,
                    compiled=compiled, view=rank_view,
                    forecast=rank_view is not view,
                )
                events.JOURNAL.publish(
                    "verdict",
                    "prioritize",
                    request_id=span.trace_id,
                    pod=pod_key,
                    data={"candidates": int(candidates), "path": "native"},
                )
                return HTTPResponse.json(body, status)
            except Exception as exc:
                trace.COUNTERS.inc("pas_prioritize_host_fallback_total")
                klog.error("native prioritize failed, host fallback: %s", exc)
        # host-only policy/metric: exact host semantics over the parsed
        # names — served from the universe's interned tuple when warm
        # (zero per-request unicode materialization)
        span.set("path", "native_host")
        if universe is not None:
            names = universe.names()
        else:
            names = (
                parsed.node_names_list()
                if use_node_names
                else parsed.node_names()
            )
        with span.stage("kernel"):
            result = self._apply_plan(pod, self._prioritize_host(rule, names))
        with span.stage("encode"):
            body = encode_host_priority_list(result)
        # partition counter only once the answer actually exists — an
        # exception above falls to the exact path, which counts itself
        trace.COUNTERS.inc("pas_prioritize_native_host_total")
        self._record_prioritize(
            span, namespace, parsed.pod_name or "", policy_name,
            "native_host", rule, int(candidates), planned, result=result,
        )
        events.JOURNAL.publish(
            "verdict",
            "prioritize",
            request_id=span.trace_id,
            pod=pod_key,
            data={"candidates": int(candidates), "path": "native_host"},
        )
        return HTTPResponse.json(body, status)

    def _record_prioritize(
        self,
        span,
        namespace: str,
        pod_name: str,
        policy_name: str,
        path: str,
        rule: Optional[TASPolicyRule],
        candidates: int,
        planned: Optional[str] = None,
        compiled: Optional[CompiledPolicy] = None,
        view: Optional[DeviceView] = None,
        result: Optional[List[HostPriority]] = None,
        forecast: bool = False,
    ) -> None:
        """One Prioritize decision record.  Device-path records reference
        the SHARED per-state score head + ranking (O(1) per request);
        host-path records copy the already-materialized top of their own
        result list.  ``forecast`` marks a ranking served from predicted
        values — the record's detail then carries the concrete forecast
        provenance ("predicted cpu=93 (slope +2.1/s)") for the top node.
        Never raises into the verb."""
        log = decisions.DECISIONS
        if not log.enabled:
            return
        try:
            head: List = []
            ranked = None
            node_index = None
            if compiled is not None and view is not None:
                head, ranked, node_index = self.fastpath.explain_prioritize(
                    compiled, view
                )
            elif result:
                head = [(hp.host, hp.score) for hp in result[:10]]
            detail = None
            if forecast and self.forecaster is not None:
                detail = {"ranking": "forecast"}
                if head and rule is not None:
                    described = self.forecaster.describe(
                        rule.metricname, head[0][0]
                    )
                    if described:
                        detail["top"] = described
            log.record_prioritize(
                request_id=span.trace_id,
                pod_namespace=namespace,
                pod_name=pod_name,
                policy=policy_name,
                path=path,
                candidates=candidates,
                metric=rule.metricname if rule is not None else "",
                operator=rule.operator if rule is not None else "",
                score_head=head,
                planned=planned,
                ranked=ranked,
                node_index=node_index,
                detail=detail,
            )
        except Exception as exc:  # provenance must never fail the verb
            klog.error("prioritize decision record failed: %r", exc)

    # -- decode ---------------------------------------------------------------

    def _decode(self, request: HTTPRequest) -> Optional[Args]:
        """DecodeExtenderRequest (telemetryscheduler.go:63-78): errors —
        including a missing Nodes list — log and produce an empty 200.
        With node_cache_capable, a body carrying only NodeNames is valid."""
        if not request.body:
            klog.v(2).info_s("request body empty", component="extender")
            return None
        try:
            args = Args.from_json(request.body)
        except Exception as exc:
            klog.v(2).info_s(f"error decoding request: {exc}", component="extender")
            return None
        if args.nodes is None:
            if self.node_cache_capable and args.node_names is not None:
                return args
            klog.v(2).info_s("no nodes in list", component="extender")
            return None
        return args

    def _candidate_names(self, args: Args) -> List[str]:
        """The request's candidate node names: Nodes.items when present,
        else (nodeCacheCapable only) the NodeNames list."""
        if args.nodes:
            return [node.name for node in args.nodes]
        if self.node_cache_capable and args.node_names:
            return list(args.node_names)
        return []

    # -- prioritize logic ------------------------------------------------------

    def _prioritize_body(
        self, args: Args, names: List[str], span=trace.NULL_SPAN
    ) -> bytes:
        """prioritizeNodes (telemetryscheduler.go:81-100) down to response
        bytes: any failure degrades to an empty priority list."""
        if self.gangs is not None:
            if self.shard is not None and not self.shard.owns_anchor(names):
                # sharded mode: a slice that straddles partitions
                # resolves through the owner of the ANCHOR partition
                # (the first candidate's partition — deterministic, so
                # every front-end agrees).  A non-owner serves the plain
                # ranking; the journaled reservation the owner creates
                # is visible to everyone (docs/sharding.md "Straddling
                # gangs")
                self.shard.counters.inc("pas_shard_gang_deferred_total")
                span.set("shard_gang", "deferred")
                gang_result = None
            else:
                try:
                    # a Prioritize-FIRST arrival drives the same
                    # reservation path Filter would, so it must solve
                    # over the same telemetry-clean candidate set —
                    # otherwise it could reserve a slice containing a
                    # violating node that Filter will then never pass
                    # (the livelock the Filter path explicitly excludes)
                    gang_result = self.gangs.prioritize_overlay(
                        args.pod, self._telemetry_clean(args.pod, names)
                    )
                except Exception as exc:  # overlay fails open to the ranking
                    klog.error(
                        "gang prioritize overlay failed open: %s", exc
                    )
                    gang_result = None
            if gang_result is not None:
                # gang member: the reserved slice in row-major order (the
                # anchor already minimizes stranded fragments); empty
                # when the gang cannot fully place — no node is a good
                # home for an unplaceable gang
                span.set("path", "gang")
                with span.stage("encode"):
                    body = encode_host_priority_list(gang_result)
                self._record_prioritize(
                    span, args.pod.namespace, args.pod.name,
                    args.pod.get_labels().get(TAS_POLICY_LABEL, ""),
                    "gang", None, len(names), result=gang_result,
                )
                return body
        try:
            policy = self._policy_from_pod(args.pod)
        except Exception as exc:
            klog.v(2).info_s(
                f"get policy from pod failed: {exc}", component="extender"
            )
            return encode_host_priority_list([])
        rule = self._scheduling_rule(policy)
        if rule is None:
            klog.v(2).info_s(
                "get scheduling rule from policy failed: no scheduling rule found",
                component="extender",
            )
            return encode_host_priority_list([])
        compiled, view = self._device_policy(policy)
        if compiled is not None and self._device_prioritize_ok(compiled, rule):
            try:
                planned = (
                    self.planner.planned_node(args.pod) if self.planner else None
                )
                rank_view = self._forecast_rank_view(compiled) or view
                body = self.fastpath.prioritize_bytes(
                    compiled, rank_view, names, planned, span=span
                )
                span.set("path", "device")
                if rank_view is not view:
                    span.set("ranking", "forecast")
                self._record_prioritize(
                    span, args.pod.namespace, args.pod.name, policy.name,
                    "device", rule, len(names), planned,
                    compiled=compiled, view=rank_view,
                    forecast=rank_view is not view,
                )
                return body
            except Exception as exc:  # device trouble must never fail the verb
                trace.COUNTERS.inc("pas_prioritize_host_fallback_total")
                klog.error("device prioritize failed, host fallback: %s", exc)
        span.set("path", "host")
        with span.stage("kernel"):
            result = self._apply_plan(
                args.pod, self._prioritize_host(rule, names)
            )
        with span.stage("encode"):
            body = encode_host_priority_list(result)
        self._record_prioritize(
            span, args.pod.namespace, args.pod.name, policy.name,
            "host", rule, len(names), result=result,
        )
        return body

    def _telemetry_clean(self, pod: Pod, names: List[str]) -> List[str]:
        """``names`` minus the pod policy's current dontschedule
        violation set — the candidate pool a gang reservation may solve
        over.  Best-effort: with no policy/strategy resolvable, the full
        list stands (Filter's own resolution owns the error paths)."""
        try:
            policy = self._policy_from_pod(pod)
            strategy = self._dontschedule_strategy(policy)
            if strategy is None:
                return names
            violating = self._violating_nodes(policy, strategy)
        except Exception:
            return names
        if not violating:
            return names
        return [name for name in names if name not in violating]

    def _apply_plan(
        self, pod: Pod, result: List[HostPriority]
    ) -> List[HostPriority]:
        """Promote the batch-planned node (if any, current, and among the
        scored candidates) to rank 1; scores stay ordinal 10-i."""
        if self.planner is None or not result:
            return result
        planned = self.planner.planned_node(pod)
        if planned is None:
            return result
        hosts = [hp.host for hp in result]
        if planned not in hosts:
            return result
        reordered = [planned] + [h for h in hosts if h != planned]
        return [
            HostPriority(host=h, score=10 - i) for i, h in enumerate(reordered)
        ]

    def _forecast_rank_view(self, compiled: Optional[CompiledPolicy]):
        """The forecast DeviceView to rank this policy's scheduleonmetric
        rule on, or None (snapshot ranking).  Never raises into a verb —
        forecasting trouble degrades to snapshot behavior."""
        forecaster = self.forecaster
        if forecaster is None or compiled is None:
            return None
        try:
            return forecaster.ranking_view(compiled.scheduleonmetric_metric)
        except Exception as exc:
            klog.error("forecast ranking view failed, snapshot serves: %s", exc)
            return None

    def _prioritize_host(
        self, rule: TASPolicyRule, candidate_names: List[str]
    ) -> List[HostPriority]:
        """prioritizeNodesForRule (telemetryscheduler.go:128-149), exact
        host semantics.  With a forecaster wired, ranking reads the SAME
        predicted milli values the device forecast view carries (the
        native<->host byte-comparability contract extends to forecasts);
        forecasting trouble falls back to the snapshot read.

        HOST-ONLY metrics never forecast: they are host-only precisely
        because their values are not milli-exact (sub-milli Quantities,
        milli-domain overflow — ops/state.py), and the history rings
        hold milli-truncated samples, so a forecast would silently
        replace the exact-Quantity ranking this path exists to provide
        with lossy-domain garbage."""
        if self.forecaster is not None and not (
            self.mirror is not None
            and self.mirror.metric_host_only(rule.metricname)
        ):
            try:
                predicted = self.forecaster.host_metric(rule.metricname)
            except Exception as exc:
                klog.error(
                    "forecast host metric failed, snapshot serves: %s", exc
                )
                predicted = None
            if predicted is not None:
                filtered = {
                    name: predicted[name]
                    for name in candidate_names
                    if name in predicted
                }
                ordered = core.ordered_list(filtered, rule.operator)
                return [
                    HostPriority(host=entry.node_name, score=10 - i)
                    for i, entry in enumerate(ordered)
                ]
        try:
            node_data = self.cache.read_metric(rule.metricname)
        except CacheMissError as exc:
            klog.v(2).info_s(
                f"failed to prioritize: {exc}, {rule.metricname}",
                component="extender",
            )
            return []
        filtered = {
            name: node_data[name] for name in candidate_names if name in node_data
        }
        ordered = core.ordered_list(filtered, rule.operator)
        return [
            HostPriority(host=entry.node_name, score=10 - i)
            for i, entry in enumerate(ordered)
        ]

    # -- filter logic ----------------------------------------------------------

    def _filter_nodes(
        self,
        args: Args,
        degraded: Optional[str] = None,
        gang_codes: Optional[Dict[str, int]] = None,
    ) -> Optional[FilterResult]:
        """filterNodes (telemetryscheduler.go:184-225).  ``degraded``
        overrides ONLY the telemetry-dependent violation set: fail_open
        -> no node violates, fail_closed -> every candidate violates;
        policy resolution (informer-fed, not telemetry) stays exact.

        With a gang tracker wired, its overlay merges OVER the telemetry
        verdict: gang members pass only their reserved slice, other pods
        fail gang-held nodes (docs/gang.md); ``gang_codes`` (when given)
        is filled with {node: decision reason code} for the overlay's
        failures so the caller's decision record counts them exactly."""
        try:
            policy = self._policy_from_pod(args.pod)
        except Exception as exc:
            klog.v(2).info_s(
                f"get policy from pod failed {exc}", component="extender"
            )
            return None
        strategy = self._dontschedule_strategy(policy)
        if strategy is None:
            klog.v(2).info_s(
                "Don't scheduler strategy failed no dontschedule strategy found",
                component="extender",
            )
            return None
        if degraded == degraded_mode.ACTION_FAIL_OPEN:
            violating: Dict[str, str] = {}
        elif degraded == degraded_mode.ACTION_FAIL_CLOSED:
            names = (
                [node.name for node in args.nodes]
                if args.nodes
                else list(args.node_names or [])
            )
            violating = {
                name: decisions.REASON_FAIL_CLOSED for name in names
            }
        else:
            violating = self._violating_nodes(policy, strategy)
        if self.gangs is not None:
            try:
                # the overlay sees only telemetry-CLEAN candidates: a
                # violating node must not enter the reservation solve's
                # free mask, or a gang could deterministically reserve a
                # slice it can never fully bind (livelock) while a clean
                # slice elsewhere goes unused.  Violating nodes keep
                # their telemetry reason in the merge below.
                clean = [
                    name
                    for name in self._candidate_names(args)
                    if name not in violating
                ]
                gang_failed, codes = self.gangs.filter_overlay(
                    args.pod, clean
                )
            except Exception as exc:
                # the overlay fails OPEN: gang trouble must never take
                # down plain telemetry filtering
                klog.error("gang filter overlay failed open: %s", exc)
                gang_failed, codes = {}, {}
            if gang_failed:
                # the gang verdict wins a collision: "reserved by gang X"
                # is the actionable reason for an operator
                violating = {**violating, **gang_failed}
                if gang_codes is not None:
                    gang_codes.update(codes)
        if not args.nodes:
            if self.node_cache_capable and args.node_names:
                return self._filter_node_names(policy, args.node_names, violating)
            klog.v(2).info_s("No nodes to compare", component="extender")
            return None
        filtered: List[Node] = []
        failed: Dict[str, str] = {}
        available = ""
        for node in args.nodes:
            if node.name in violating:
                failed[node.name] = violating[node.name]
            else:
                filtered.append(node)
                available += node.name + " "
        node_names = available.split(" ")  # trailing "" kept (see module doc)
        if available:
            klog.v(2).info_s(
                f"Filtered nodes for {policy.name}: {available}",
                component="extender",
            )
        return FilterResult(
            nodes=filtered, node_names=node_names, failed_nodes=failed, error=""
        )

    def _filter_node_names(
        self, policy: TASPolicy, names: List[str], violating: Dict[str, str]
    ) -> FilterResult:
        """nodeCacheCapable Filter: answer with NodeNames only (the
        kube-scheduler reads NodeNames from a nodeCacheCapable extender;
        Nodes stays null).  Unlike the legacy Nodes branch — where the
        scheduler ignores NodeNames and the trailing-"" split quirk is
        harmless wire trivia — here kube-scheduler consumes every entry
        and rejects names absent from its input list, so the list must
        hold exactly the passing names (the reference's own
        nodeCacheCapable extender appends cleanly, GAS scheduler.go:
        467-476)."""
        failed: Dict[str, str] = {}
        node_names: List[str] = []
        for name in names:
            if name in violating:
                failed[name] = violating[name]
            else:
                node_names.append(name)
        if node_names:
            available = " ".join(node_names)
            klog.v(2).info_s(
                f"Filtered nodes for {policy.name}: {available}",
                component="extender",
            )
        return FilterResult(
            nodes=None, node_names=node_names, failed_nodes=failed, error=""
        )

    def _violating_nodes(
        self, policy: TASPolicy, strategy: dontschedule.Strategy
    ) -> Dict[str, str]:
        """{violating node: concrete reason string}.  The device path's
        strings decode the kernel's rule-index vector; the host path's
        come from violated_details — byte-identical wherever both can
        run (tests/test_decisions.py pins the parity)."""
        compiled, view = self._device_policy(policy)
        if compiled is not None and self._device_filter_ok(compiled):
            try:
                explained = self.fastpath.violation_reasons(
                    compiled, view, policy.name
                )
                if explained is not None:
                    return explained[1]
            except Exception as exc:
                klog.error("device filter failed, host fallback: %s", exc)
        return {
            name: detail[1]
            for name, detail in strategy.violated_details(self.cache).items()
        }

    # -- shared helpers --------------------------------------------------------

    def _policy_from_pod(self, pod: Pod) -> TASPolicy:
        """getPolicyFromPod (telemetryscheduler.go:103-112)."""
        policy_name = pod.get_labels().get(TAS_POLICY_LABEL)
        if policy_name is None:
            raise CacheMissError(f"no policy found in pod spec for pod {pod.name}")
        return self.cache.read_policy(pod.namespace, policy_name)

    def _scheduling_rule(self, policy: TASPolicy) -> Optional[TASPolicyRule]:
        """getSchedulingRule (telemetryscheduler.go:115-124): rule[0] of
        scheduleonmetric, requiring a non-empty metric name."""
        strat = policy.strategies.get("scheduleonmetric")
        if strat and strat.rules and strat.rules[0].metricname:
            return strat.rules[0]
        return None

    def _dontschedule_strategy(
        self, policy: TASPolicy
    ) -> Optional[dontschedule.Strategy]:
        """getDontScheduleStrategy (telemetryscheduler.go:228-235)."""
        strat = policy.strategies.get("dontschedule")
        if strat is None or not strat.rules:
            return None
        return dontschedule.Strategy.from_policy_strategy(strat)

    # -- device-path eligibility ----------------------------------------------

    def _device_policy(self, policy: TASPolicy):
        """Atomic (compiled, view) snapshot — see
        TensorStateMirror.policy_with_view for why both come from one lock
        acquisition."""
        if self.mirror is None:
            return None, None
        return self.mirror.policy_with_view(policy.namespace, policy.name)

    # the single source of truth for "can the device fastpath serve this
    # policy", shared between the request path (host_only = live mirror
    # lookup) and the warmer (host_only = snapshotted map) so the warmed
    # set can never drift from what requests actually use

    @staticmethod
    def _prioritize_device_eligible(compiled: CompiledPolicy, host_only) -> bool:
        return compiled.scheduleonmetric_row >= 0 and not host_only(
            compiled.scheduleonmetric_metric
        )

    @staticmethod
    def _filter_device_eligible(compiled: CompiledPolicy, host_only) -> bool:
        rules = compiled.dontschedule
        if rules is None or rules.host_only or not rules.active.any():
            return False
        return not any(host_only(name) for name in rules.metric_names)

    def _device_prioritize_ok(
        self, compiled: CompiledPolicy, rule: TASPolicyRule
    ) -> bool:
        return self._prioritize_device_eligible(
            compiled, self.mirror.metric_host_only
        )

    def _device_filter_ok(self, compiled: CompiledPolicy) -> bool:
        return self._filter_device_eligible(
            compiled, self.mirror.metric_host_only
        )

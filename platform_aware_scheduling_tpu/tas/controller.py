"""TASPolicy controller: CRD informer -> cache writes + enforcer registry.

Reference: telemetry-aware-scheduling/pkg/controller/{controller,types}.go.
The informer watches ``taspolicies`` (controller.go:38-57); onAdd caches the
policy, registers each strategy with the enforcer, and registers each rule's
metric (refcounted) in the cache (controller.go:61-91); onUpdate removes the
old strategies/metrics then re-adds the new (111-149); onDelete unregisters
strategies, derefs metrics, drops the policy (152-176).  ``cast_strategy``
maps a strategy-type name to its concrete class (94-108).
"""

from __future__ import annotations

import threading
from typing import Optional

from platform_aware_scheduling_tpu.kube.informer import (
    DeletedFinalStateUnknown,
    Informer,
    ListWatch,
)
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import (
    TASPolicy,
    TASPolicyStrategy,
)
from platform_aware_scheduling_tpu.tas.strategies import (
    core,
    deschedule,
    dontschedule,
    scheduleonmetric,
)
from platform_aware_scheduling_tpu.utils import klog

_STRATEGY_CLASSES = {
    scheduleonmetric.STRATEGY_TYPE: scheduleonmetric.Strategy,
    deschedule.STRATEGY_TYPE: deschedule.Strategy,
    dontschedule.STRATEGY_TYPE: dontschedule.Strategy,
}


class InvalidStrategyError(ValueError):
    pass


def cast_strategy(strategy_type: str, strat: TASPolicyStrategy):
    """Strategy-type name -> concrete strategy instance
    (reference controller.go:94-108)."""
    cls = _STRATEGY_CLASSES.get(strategy_type)
    if cls is None:
        raise InvalidStrategyError(
            "strategy could not be added - invalid strategy type"
        )
    return cls.from_policy_strategy(strat)


class TelemetryPolicyController:
    """Watches the TASPolicy CRD and keeps cache + enforcer in sync
    (reference pkg/controller/types.go:11-15)."""

    def __init__(
        self,
        kube_client,
        cache: AutoUpdatingCache,
        enforcer: core.MetricEnforcer,
        namespace: Optional[str] = None,
    ):
        self.kube_client = kube_client
        self.cache = cache
        self.enforcer = enforcer
        self.namespace = namespace
        #: the CRD informer once :meth:`run` starts it — the mains feed
        #: its has_synced into /readyz (utils/health.informer_synced)
        self.informer: Optional[Informer] = None

    # -- lifecycle (controller.go:23-57) --------------------------------------

    def run(self, stop: Optional[threading.Event] = None) -> Informer:
        """Start the CRD informer; returns it (caller may wait for sync).
        Panics in handlers are contained per-event, like the reference's
        recover wrapper (controller.go:25-29)."""

        def list_policies():
            obj = self.kube_client.list_taspolicies(self.namespace)
            items = obj.get("items") or []
            rv = (obj.get("metadata") or {}).get("resourceVersion", "")
            return [TASPolicy.from_obj(item) for item in items], rv

        def watch_policies(resource_version):
            for event_type, raw in self.kube_client.watch_taspolicies(
                self.namespace, resource_version=resource_version
            ):
                yield event_type, TASPolicy.from_obj(raw)

        def key(policy: TASPolicy) -> str:
            return f"{policy.namespace}/{policy.name}"

        informer = Informer(
            ListWatch(list_policies, watch_policies, key),
            on_add=self._guarded(self.on_add),
            on_update=self._guarded(self.on_update),
            on_delete=self._guarded(self.on_delete),
            name="taspolicy",
        )
        self.informer = informer
        informer.start()
        if stop is not None:
            threading.Thread(
                target=lambda: (stop.wait(), informer.stop()),
                daemon=True,
            ).start()
        return informer

    def _guarded(self, fn):
        def wrapped(*args):
            try:
                fn(*args)
            except Exception as exc:
                klog.error("Recovered from policy event panic: %s", exc)

        return wrapped

    # -- handlers -------------------------------------------------------------

    def on_add(self, policy: TASPolicy) -> None:
        """Cache the policy, register strategies + metrics
        (controller.go:61-91)."""
        if not isinstance(policy, TASPolicy):
            klog.v(4).info_s(
                "cannot add policy: not recognized as a telemetry policy",
                component="controller",
            )
            return
        pol = policy.deep_copy()
        self.cache.write_policy(pol.namespace, pol.name, pol)
        for name, strat in pol.strategies.items():
            klog.v(4).info_s(
                f"registering {name} from {pol.name}", component="controller"
            )
            try:
                instance = cast_strategy(name, strat)
            except InvalidStrategyError as exc:
                klog.v(2).info_s(str(exc), component="controller")
                return
            instance.set_policy_name(pol.name)
            self.enforcer.add_strategy(instance, name)
            for rule in strat.rules:
                self.cache.write_metric(rule.metricname, None)
                klog.v(2).info_s(f"Added {rule.metricname}", component="controller")
        klog.v(2).info_s(f"Added policy, {pol.name}", component="controller")

    def on_update(self, old: TASPolicy, new: TASPolicy) -> None:
        """Swap cached policy; per strategy type remove old registration +
        metric refcounts, then add the new (controller.go:111-149)."""
        pol = new.deep_copy()
        self.cache.write_policy(pol.namespace, pol.name, pol)
        klog.v(2).info_s(f"Policy: {pol.name} updated", component="controller")
        for name, strat in pol.strategies.items():
            old_strat = old.strategies.get(name, TASPolicyStrategy())
            try:
                old_instance = cast_strategy(name, old_strat)
            except InvalidStrategyError as exc:
                klog.v(2).info_s(str(exc), component="controller")
                return
            old_instance.set_policy_name(old.name)
            self.enforcer.remove_strategy(old_instance, old_instance.strategy_type())
            for rule in old_strat.rules:
                self.cache.delete_metric(rule.metricname)
            try:
                instance = cast_strategy(name, strat)
            except InvalidStrategyError as exc:
                klog.v(2).info_s(str(exc), component="controller")
                return
            instance.set_policy_name(pol.name)
            self.enforcer.add_strategy(instance, name)
            for rule in strat.rules:
                self.cache.write_metric(rule.metricname, None)

    def on_delete(self, policy: TASPolicy) -> None:
        """Unregister strategies, deref metrics, drop the policy
        (controller.go:152-176)."""
        if isinstance(policy, DeletedFinalStateUnknown):
            policy = policy.obj
        pol = policy.deep_copy()
        for name, strat in pol.strategies.items():
            try:
                instance = cast_strategy(name, strat)
            except InvalidStrategyError as exc:
                klog.v(2).info_s(str(exc), component="controller")
                return
            instance.set_policy_name(pol.name)
            self.enforcer.remove_strategy(instance, instance.strategy_type())
            for rule in strat.rules:
                self.cache.delete_metric(rule.metricname)
        self.cache.delete_policy(pol.namespace, pol.name)
        klog.v(2).info_s(f"Policy: {pol.name} deleted", component="controller")

"""scheduleonmetric strategy: a marker type whose first rule drives
Prioritize ordering; Violated/Enforce are no-ops.

Reference: telemetry-aware-scheduling/pkg/strategies/scheduleonmetric/
strategy.go (no-ops at 20-28).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import (
    TASPolicyRule,
    TASPolicyStrategy,
)
from platform_aware_scheduling_tpu.tas.strategies import core
from platform_aware_scheduling_tpu.utils import trace

STRATEGY_TYPE = "scheduleonmetric"


@dataclass
class Strategy:
    policy_name: str = ""
    rules: List[TASPolicyRule] = field(default_factory=list)

    @classmethod
    def from_policy_strategy(cls, strat: TASPolicyStrategy) -> "Strategy":
        return cls(policy_name=strat.policy_name, rules=list(strat.rules))

    def violated(self, cache) -> Dict[str, None]:
        # a no-op by contract (strategy.go:20-22), but the enforcer DID
        # evaluate it — visible on the per-strategy counter
        trace.COUNTERS.inc(
            "pas_strategy_evaluations_total", labels={"strategy": STRATEGY_TYPE}
        )
        return {}

    def enforce(self, enforcer, cache) -> int:
        return 0

    def cleanup(self, enforcer, policy_name: str) -> None:
        return None

    def strategy_type(self) -> str:
        return STRATEGY_TYPE

    def equals(self, other) -> bool:
        return isinstance(other, Strategy) and core.rules_equal(self, other)

    def get_policy_name(self) -> str:
        return self.policy_name

    def set_policy_name(self, name: str) -> None:
        self.policy_name = name

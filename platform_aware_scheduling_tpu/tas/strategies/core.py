"""Strategy contract, rule math, and the periodic enforcer.

Reference: telemetry-aware-scheduling/pkg/strategies/core/.

``evaluate_rule`` and ``ordered_list`` (operator.go:13-42) are the entire
mathematical core of TAS.  These host versions are the exact-semantics
control; the batched device versions live in ``ops/rules.py`` and
``ops/scoring.py`` and are cross-checked against these in tests.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Protocol, runtime_checkable

from platform_aware_scheduling_tpu.tas.metrics import NodeMetricsInfo
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicyRule
from platform_aware_scheduling_tpu.utils import klog
from platform_aware_scheduling_tpu.utils.quantity import Quantity

OPERATOR_LESS_THAN = "LessThan"
OPERATOR_GREATER_THAN = "GreaterThan"
OPERATOR_EQUALS = "Equals"


def evaluate_rule(value: Quantity, rule: TASPolicyRule) -> bool:
    """True when ``value <op> target`` holds (operator.go:13-26).  An unknown
    operator raises KeyError, matching the reference's nil-map panic."""
    operators = {
        OPERATOR_LESS_THAN: lambda v, t: v.cmp_int64(t) == -1,
        OPERATOR_GREATER_THAN: lambda v, t: v.cmp_int64(t) == 1,
        OPERATOR_EQUALS: lambda v, t: v.cmp_int64(t) == 0,
    }
    return operators[rule.operator](value, rule.target)


@dataclass
class NodeSortableMetric:
    node_name: str
    metric_value: Quantity


def ordered_list(
    metrics_info: NodeMetricsInfo, operator: str
) -> List[NodeSortableMetric]:
    """Order nodes by metric value: GreaterThan -> descending, LessThan ->
    ascending, anything else -> input order (operator.go:30-42)."""
    mtrcs = [
        NodeSortableMetric(name, info.value) for name, info in metrics_info.items()
    ]
    if operator == OPERATOR_GREATER_THAN:
        mtrcs.sort(key=lambda m: m.metric_value.value, reverse=True)
    elif operator == OPERATOR_LESS_THAN:
        mtrcs.sort(key=lambda m: m.metric_value.value)
    return mtrcs


@runtime_checkable
class StrategyInterface(Protocol):
    """Expected behavior of a strategy (core/types.go:12-18)."""

    def violated(self, cache) -> Dict[str, None]: ...

    def strategy_type(self) -> str: ...

    def equals(self, other: "StrategyInterface") -> bool: ...

    def get_policy_name(self) -> str: ...

    def set_policy_name(self, name: str) -> None: ...


@runtime_checkable
class Enforceable(Protocol):
    """Strategies that act on the cluster each sync period
    (core/types.go:20-24)."""

    def enforce(self, enforcer: "MetricEnforcer", cache) -> int: ...

    def cleanup(self, enforcer: "MetricEnforcer", policy_name: str) -> None: ...


def rules_equal(a, b) -> bool:
    """Shared ``Equals`` body of all three strategies (e.g.
    dontschedule/strategy.go:57-76): same policy name, non-empty rule list,
    identical (metricname, operator, target) per index."""
    if a.get_policy_name() != b.get_policy_name():
        return False
    ra, rb = a.rules, b.rules
    if not ra or len(ra) != len(rb):
        return False
    return all(
        x.metricname == y.metricname
        and x.operator == y.operator
        and x.target == y.target
        for x, y in zip(ra, rb)
    )


class MetricEnforcer:
    """Registers strategies by type and periodically enforces them
    (core/enforcer.go:15-131)."""

    def __init__(self, kube_client=None, mirror=None):
        self.registered_strategies: Dict[str, Dict[int, StrategyInterface]] = {}
        self.kube_client = kube_client
        # optional TensorStateMirror: strategies with a device-path
        # ``violated_device`` use it during enforcement
        self.mirror = mirror
        # per-cycle violation subscribers: callables
        # ``(strategy_type, {node: [policy names]})`` invoked by strategies
        # at the end of every enforcement pass (including empty ones) —
        # the rebalance loop's drift detector feeds off this
        self.violation_observers: List = []
        # optional tas.degraded.DegradedModeController: while it reports
        # evictions suspended (stale telemetry / open kube circuit), the
        # deschedule strategy skips its label pass — no new eviction
        # pressure (in-tree or external) is created from data we cannot
        # trust (docs/robustness.md, hard invariant)
        self.degraded = None
        # optional kube.lease.LeaseElector: with --leaderElect, the
        # deschedule label pass is a singleton loop — followers evaluate
        # and publish violations (their caches stay warm for failover)
        # but never write labels (docs/robustness.md "HA & leader
        # election")
        self.leadership = None
        self._lock = threading.RLock()

    def publish_violations(
        self, strategy_type: str, violations: Dict[str, List[str]]
    ) -> None:
        """Fan a finished enforcement cycle's violation map out to the
        registered observers; a failing observer must never break the
        enforcement loop."""
        for observer in list(self.violation_observers):
            try:
                observer(strategy_type, violations)
            except Exception as exc:  # noqa: BLE001 — observer errors are theirs
                klog.error("violation observer failed: %r", exc)

    def register_strategy_type(self, strategy: StrategyInterface) -> None:
        with self._lock:
            self.registered_strategies[strategy.strategy_type()] = {}

    def unregister_strategy_type(self, strategy: StrategyInterface) -> None:
        with self._lock:
            self.registered_strategies.pop(strategy.strategy_type(), None)

    def is_registered(self, strategy_type: str) -> bool:
        with self._lock:
            return strategy_type in self.registered_strategies

    def registered_strategy_types(self) -> List[str]:
        with self._lock:
            return list(self.registered_strategies)

    def add_strategy(self, strategy: StrategyInterface, strategy_type: str) -> None:
        """Dedup by ``equals``; only Enforceable strategies under a registered
        type are stored (enforcer.go:85-103)."""
        with self._lock:
            registry = self.registered_strategies.get(strategy_type)
            if registry is not None:
                for existing in registry.values():
                    if existing.equals(strategy):
                        klog.v(2).info_s(
                            f"Duplicate strategy found. Not adding "
                            f"{existing.get_policy_name()}: {existing.strategy_type()} to registry",
                            component="controller",
                        )
                        return
            klog.v(2).info_s(
                f"Adding strategies: {strategy.strategy_type()} {strategy.get_policy_name()}",
                component="controller",
            )
            if registry is not None and isinstance(strategy, Enforceable):
                registry[id(strategy)] = strategy

    def remove_strategy(self, strategy: StrategyInterface, strategy_type: str) -> None:
        """Remove matching strategies, then run the strategy's cleanup
        (enforcer.go:65-82)."""
        with self._lock:
            registry = self.registered_strategies.get(strategy_type, {})
            for key, existing in list(registry.items()):
                if existing.equals(strategy):
                    del registry[key]
                    klog.v(2).info_s(
                        f"Removed {existing.get_policy_name()}: {strategy_type} "
                        "from strategy register",
                        component="controller",
                    )
        if isinstance(strategy, Enforceable):
            try:
                strategy.cleanup(self, strategy.get_policy_name())
            except Exception as exc:
                klog.v(2).info_s(
                    f"Failed to remove strategy: {exc}", component="controller"
                )

    def enforce_strategy(self, strategy_type: str, cache) -> None:
        with self._lock:
            strategies = list(
                self.registered_strategies.get(strategy_type, {}).values()
            )
        for strategy in strategies:
            if isinstance(strategy, Enforceable):
                try:
                    strategy.enforce(self, cache)
                except Exception as exc:
                    klog.error("Strategy was not enforceable. %s", exc)

    def enforce_registered_strategies(
        self,
        cache,
        period_seconds: float,
        stop: Optional[threading.Event] = None,
    ) -> None:
        """Periodic enforcement loop (enforcer.go:106-113): waits a tick,
        then enforces every registered type."""
        stop = stop or threading.Event()
        while not stop.wait(period_seconds):
            for strategy_type in self.registered_strategy_types():
                self.enforce_strategy(strategy_type, cache)

    def start_enforcing(
        self,
        cache,
        period_seconds: float,
        stop: Optional[threading.Event] = None,
    ) -> threading.Event:
        stop = stop or threading.Event()
        thread = threading.Thread(
            target=self.enforce_registered_strategies,
            args=(cache, period_seconds, stop),
            daemon=True,
        )
        thread.start()
        return stop

"""dontschedule strategy: nodes violating any rule are filtered out.

Reference: telemetry-aware-scheduling/pkg/strategies/dontschedule/strategy.go.
OR-semantics across rules: a node violating ANY rule is in the violation set
(strategy.go:25-44).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import (
    TASPolicyRule,
    TASPolicyStrategy,
)
from platform_aware_scheduling_tpu.tas.strategies import core
from platform_aware_scheduling_tpu.utils import decisions, klog, trace

STRATEGY_TYPE = "dontschedule"


@dataclass
class Strategy:
    policy_name: str = ""
    rules: List[TASPolicyRule] = field(default_factory=list)

    @classmethod
    def from_policy_strategy(cls, strat: TASPolicyStrategy) -> "Strategy":
        return cls(policy_name=strat.policy_name, rules=list(strat.rules))

    def violated(self, cache) -> Dict[str, None]:
        """Nodes whose current metric values violate any rule
        (strategy.go:25-44).  Unreadable metrics are skipped."""
        return {name: None for name in self.violated_details(cache)}

    def violated_details(self, cache) -> Dict[str, Tuple[int, str]]:
        """Violation provenance: ``{node: (first matching rule index,
        reason string)}``.  "First" is rule-list order (lowest index
        wins), matching the device path's argmax-over-rules exactly
        (ops/rules.first_violated_rule); the reason string formats the
        SAME milli integers the device mirror stores, so host and native
        Filter responses carry byte-identical FailedNodes values
        (pinned by tests/test_decisions.py)."""
        trace.COUNTERS.inc(
            "pas_strategy_evaluations_total", labels={"strategy": STRATEGY_TYPE}
        )
        violating: Dict[str, Tuple[int, str]] = {}
        for rule_index, rule in enumerate(self.rules):
            try:
                node_metrics = cache.read_metric(rule.metricname)
            except Exception as exc:
                klog.v(2).info_s(str(exc), component="controller")
                continue
            for node_name, node_metric in node_metrics.items():
                if node_name in violating:
                    continue  # an earlier rule already claimed this node
                if core.evaluate_rule(node_metric.value, rule):
                    klog.v(2).info_s(
                        f"{node_name} violating {self.policy_name}: "
                        f"{rule.metricname} {rule.operator} {rule.target}",
                        component="controller",
                    )
                    milli, exact = node_metric.value.milli_value_exact()
                    value_str = (
                        decisions.fmt_milli(milli)
                        if exact
                        else node_metric.value.as_dec()
                    )
                    violating[node_name] = (
                        rule_index,
                        decisions.rule_reason(
                            self.policy_name,
                            rule.metricname,
                            rule.operator,
                            value_str,
                            str(rule.target),
                        ),
                    )
        if violating:
            trace.COUNTERS.inc(
                "pas_strategy_violations_total",
                len(violating),
                labels={"strategy": STRATEGY_TYPE},
            )
        return violating

    def enforce(self, enforcer, cache) -> int:
        """Unimplemented for dontschedule (strategy.go:47-49)."""
        return 0

    def cleanup(self, enforcer, policy_name: str) -> None:
        return None

    def strategy_type(self) -> str:
        return STRATEGY_TYPE

    def equals(self, other) -> bool:
        return isinstance(other, Strategy) and core.rules_equal(self, other)

    def get_policy_name(self) -> str:
        return self.policy_name

    def set_policy_name(self, name: str) -> None:
        self.policy_name = name

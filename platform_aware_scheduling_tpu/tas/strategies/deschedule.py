"""deschedule strategy: violation detection + node labeling enforcement.

Reference: telemetry-aware-scheduling/pkg/strategies/deschedule/
{strategy,enforce}.go.  Violating nodes get the label
``<policyName>=violating`` via JSON patch; non-violating nodes that still
carry the label get it removed and re-added as "null" (the reference's
acknowledged oddity at enforce.go:118-132, kept for behavior parity since
external deschedulers match on these labels).  Actual pod eviction is
delegated to an external descheduler (survey §1 L6).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import (
    TASPolicyRule,
    TASPolicyStrategy,
)
from platform_aware_scheduling_tpu.tas.strategies import core
from platform_aware_scheduling_tpu.utils import klog, trace

STRATEGY_TYPE = "deschedule"


class _BareNode:
    """A name-only stand-in for a node known to carry none of the
    registered policy labels (it missed every label-exists selector):
    the label pass needs only its name to add ``=violating``."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def get_labels(self) -> Dict[str, str]:
        return {}


@dataclass
class Strategy:
    policy_name: str = ""
    rules: List[TASPolicyRule] = field(default_factory=list)

    @classmethod
    def from_policy_strategy(cls, strat: TASPolicyStrategy) -> "Strategy":
        return cls(policy_name=strat.policy_name, rules=list(strat.rules))

    # -- violation detection (strategy.go:31-55) -----------------------------

    def violated(self, cache) -> Dict[str, None]:
        trace.COUNTERS.inc(
            "pas_strategy_evaluations_total", labels={"strategy": STRATEGY_TYPE}
        )
        violating: Dict[str, None] = {}
        for rule in self.rules:
            try:
                node_metrics = cache.read_metric(rule.metricname)
            except Exception as exc:
                klog.v(2).info_s(str(exc), component="controller")
                continue
            for node_name, node_metric in node_metrics.items():
                if core.evaluate_rule(node_metric.value, rule):
                    klog.v(2).info_s(
                        f"{node_name} violating {self.policy_name}: "
                        f"{rule.metricname} {rule.operator} {rule.target}",
                        component="controller",
                    )
                    violating[node_name] = None
        if violating:
            trace.COUNTERS.inc(
                "pas_strategy_violations_total",
                len(violating),
                labels={"strategy": STRATEGY_TYPE},
            )
        return violating

    def violated_device(self, mirror) -> "Dict[str, None] | None":
        """Batched violation detection through the tensor mirror; None means
        'use the host path' (policy unknown, host-only values, or the
        compiled rules don't match this instance)."""
        try:
            import numpy as np

            from platform_aware_scheduling_tpu.ops.rules import (
                OP_IDS,
                violated_nodes,
            )

            compiled, view = mirror.policy_with_view_by_name(self.policy_name)
            if compiled is None or compiled.deschedule is None:
                return None
            rs = compiled.deschedule
            if rs.host_only or not rs.active.any():
                return None
            if any(mirror.metric_host_only(m) for m in rs.metric_names):
                return None
            # the enforcer's strategy instance and the mirror's compiled
            # policy come from the same CRD event but through different
            # paths — verify they describe the same rules before trusting
            # the device result
            mine = tuple(
                (r.metricname, OP_IDS.get(r.operator, -1), r.target * 1000)
                for r in self.rules
            )
            theirs = tuple(
                (name, int(rs.op_ids[i]), int(rs.targets[i]))
                for i, name in enumerate(rs.metric_names)
            )
            if mine != theirs:
                return None
            rules = compiled.device_rules("deschedule")
            mask = np.asarray(violated_nodes(view.values, view.present, rules))
            names = view.node_names
            violating = {
                names[i]: None for i in np.nonzero(mask)[0] if i < len(names)
            }
            # same counters the host path keeps — the evaluation happened,
            # just on the device (None returns fall through to the host
            # path, which counts itself)
            trace.COUNTERS.inc(
                "pas_strategy_evaluations_total",
                labels={"strategy": STRATEGY_TYPE},
            )
            if violating:
                trace.COUNTERS.inc(
                    "pas_strategy_violations_total",
                    len(violating),
                    labels={"strategy": STRATEGY_TYPE},
                )
            return violating
        except Exception as exc:
            klog.error("device deschedule failed, host fallback: %s", exc)
            return None

    # -- enforcement (enforce.go) --------------------------------------------

    def enforce(self, enforcer: core.MetricEnforcer, cache) -> int:
        """Compute per-policy violations, list the nodes whose labels
        can change, patch labels (enforce.go:57-71; see
        :meth:`_nodes_needing_labels` for the deliberate divergence from
        the reference's list-every-node loop).

        Hard invariant (docs/robustness.md): while the degraded-mode
        controller reports evictions suspended — telemetry stale or the
        kube circuit open — the LABEL pass is skipped.  Violations
        computed from untrustworthy data must not become ``=violating``
        labels (the eviction trigger external deschedulers act on).  The
        stale violation map is still published so the rebalancer can
        record the suspension on /debug/rebalance — its own gate
        guarantees it neither plans, actuates, nor advances drift
        streaks from it.

        HA (docs/robustness.md "HA & leader election"): with leader
        election wired, the label pass is a singleton loop.  A follower
        still evaluates violations and publishes them — its drift
        detector and /debug surfaces stay warm for failover — but never
        writes ``=violating`` labels, so N replicas create exactly one
        stream of eviction pressure."""
        leadership = getattr(enforcer, "leadership", None)
        if leadership is not None and not leadership.is_leader():
            enforcer.publish_violations(
                STRATEGY_TYPE,
                self._node_status_for_strategy(enforcer, cache),
            )
            return 0
        degraded = getattr(enforcer, "degraded", None)
        if degraded is not None:
            allowed, reason = degraded.evictions_allowed()
            if not allowed:
                klog.v(2).info_s(
                    f"deschedule enforcement suspended: {reason}",
                    component="controller",
                )
                # liveness: with the label pass skipped, NOTHING else in
                # this process may be calling the kube group — and a
                # breaker can only leave half-open through a probe CALL.
                # This read is that probe: refused instantly while the
                # circuit is open, it becomes the half-open probe once
                # the reset timeout elapses, closing the circuit (and
                # ending the suspension) as soon as the API server is
                # really back
                try:
                    enforcer.kube_client.list_nodes()
                except Exception as probe_exc:
                    klog.v(4).info_s(
                        f"suspended-cycle kube probe: {probe_exc}",
                        component="controller",
                    )
                enforcer.publish_violations(
                    STRATEGY_TYPE,
                    self._node_status_for_strategy(enforcer, cache),
                )
                return 0
        violations = self._node_status_for_strategy(enforcer, cache)
        try:
            nodes = self._nodes_needing_labels(enforcer, violations)
        except Exception as exc:
            klog.v(2).info_s(f"cannot list nodes: {exc}", component="controller")
            raise
        try:
            total = self._update_node_labels(enforcer, violations, nodes)
        finally:
            # close-the-loop feed: every enforcement cycle publishes its
            # full node -> [violated policies] map — including the empty
            # one (hysteresis streaks reset on clean cycles) and even when
            # label patching fails (the violations are already final; a
            # patch-failure window must not freeze the drift detector's
            # consecutive-cycle accounting)
            enforcer.publish_violations(STRATEGY_TYPE, violations)
        trace.COUNTERS.inc(
            "pas_strategy_enforcements_total", labels={"strategy": STRATEGY_TYPE}
        )
        return total

    def cleanup(self, enforcer: core.MetricEnforcer, policy_name: str) -> None:
        """Remove the violation label from labeled nodes when the policy is
        deleted (enforce.go:28-52)."""
        try:
            nodes = enforcer.kube_client.list_nodes(
                label_selector=f"{policy_name}=violating"
            )
        except Exception as exc:
            klog.v(2).info_s(f"cannot list nodes: {exc}", component="controller")
            raise
        for node in nodes:
            payload = []
            if policy_name in node.get_labels():
                payload.append(
                    {"op": "remove", "path": "/metadata/labels/" + policy_name}
                )
            try:
                self._patch_node(node.name, enforcer, payload)
            except Exception as exc:
                klog.v(2).info_s(str(exc), component="controller")
        klog.v(2).info_s(
            f"Remove the node label on policy {policy_name} deletion",
            component="controller",
        )

    def _patch_node(
        self, node_name: str, enforcer: core.MetricEnforcer, payload: List[Dict]
    ) -> None:
        enforcer.kube_client.patch_node(node_name, payload)

    def _nodes_needing_labels(
        self, enforcer: core.MetricEnforcer, violations: Dict[str, List[str]]
    ):
        """Only the nodes whose label state can change this cycle: any
        node carrying a registered policy's label (the remove/re-add-
        "null" dance, enforce.go:118-132) plus the violating nodes
        themselves.  The reference lists EVERY node each cycle; at 100k
        nodes that is a full-cluster copy per enforcement pass to build
        payloads that are empty on all but a handful.  A label-exists
        selector asks the API server for exactly the candidate set, and
        the final label state is identical — a node matching neither
        list got an empty payload (a no-op patch) before."""
        candidates: Dict[str, object] = {}
        for policy_name in self._all_policies(enforcer):
            for node in enforcer.kube_client.list_nodes(
                label_selector=policy_name
            ):
                candidates[node.name] = node
        for name in violations:
            if name not in candidates:
                candidates[name] = _BareNode(name)
        return list(candidates.values())

    def _all_policies(self, enforcer: core.MetricEnforcer) -> Dict[str, None]:
        return {
            strat.get_policy_name(): None
            for strat in enforcer.registered_strategies.get(
                STRATEGY_TYPE, {}
            ).values()
        }

    def _node_status_for_strategy(
        self, enforcer: core.MetricEnforcer, cache
    ) -> Dict[str, List[str]]:
        """node -> [policy names violated] over every registered deschedule
        strategy (enforce.go:154-164)."""
        violations: Dict[str, List[str]] = {}
        mirror = getattr(enforcer, "mirror", None)
        for strat in list(
            enforcer.registered_strategies.get(STRATEGY_TYPE, {}).values()
        ):
            klog.v(2).info_s(
                "Evaluating " + strat.get_policy_name(), component="controller"
            )
            nodes = None
            if mirror is not None and hasattr(strat, "violated_device"):
                nodes = strat.violated_device(mirror)
            if nodes is None:
                nodes = strat.violated(cache)
            for node in nodes:
                violations.setdefault(node, []).append(strat.get_policy_name())
        return violations

    def _update_node_labels(
        self,
        enforcer: core.MetricEnforcer,
        violations: Dict[str, List[str]],
        all_nodes,
    ) -> int:
        """Patch the candidate nodes: violating policies -> add
        ``=violating``; registered-but-not-violating policies whose
        label is present -> remove + re-add as "null"
        (enforce.go:99-151).  Empty payloads are skipped — a no-op
        patch costs an API round trip and changes nothing."""
        total_violations = 0
        label_errs = ""
        for node in all_nodes:
            payload: List[Dict] = []
            non_violated = self._all_policies(enforcer)
            violated_policies = ""
            for policy_name in violations.get(node.name, []):
                non_violated.pop(policy_name, None)
                payload.append(
                    {
                        "op": "add",
                        "path": "/metadata/labels/" + policy_name,
                        "value": "violating",
                    }
                )
                violated_policies += policy_name + ", "
            for policy_name in non_violated:
                if policy_name in node.get_labels():
                    payload.append(
                        {"op": "remove", "path": "/metadata/labels/" + policy_name}
                    )
                    payload.append(
                        {
                            "op": "add",
                            "path": "/metadata/labels/" + policy_name,
                            "value": "null",
                        }
                    )
            # the count is the node's ACTUAL violations; the old placement
            # inside the non-violated loop returned the number of
            # non-violating registered policies per node instead
            total_violations += len(violations.get(node.name, []))
            if not payload:
                # an empty JSON patch changes nothing: spare the API
                # server the round trip entirely
                continue
            try:
                self._patch_node(node.name, enforcer, payload)
            except Exception as exc:
                if not label_errs:
                    label_errs = "could not label: "
                klog.v(4).info_s(str(exc), component="controller")
                label_errs += f"{node.name}: [ {violated_policies} ]; "
            if violated_policies:
                klog.v(2).info_s(
                    f"Node {node.name} violating {violated_policies}",
                    component="controller",
                )
        if label_errs:
            raise RuntimeError(label_errs)
        return total_violations

    # -- identity ------------------------------------------------------------

    def strategy_type(self) -> str:
        return STRATEGY_TYPE

    def equals(self, other) -> bool:
        return isinstance(other, Strategy) and core.rules_equal(self, other)

    def get_policy_name(self) -> str:
        return self.policy_name

    def set_policy_name(self, name: str) -> None:
        self.policy_name = name

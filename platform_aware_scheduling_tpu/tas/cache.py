"""TAS state cache: policies + refcounted, self-updating metrics.

Reference: telemetry-aware-scheduling/pkg/cache/.  The reference serializes
all access through a single goroutine reading a request channel
(cache.go:20-63); here the same observable semantics — serialized reads and
writes, WRITE-with-nil-payload preserving the existing value (cache.go:52-57)
— are provided by a mutex-guarded store (the idiomatic Python translation;
there is no perf reason for channel hand-off since the hot path reads the
tensorized mirror, not this cache).

On top sits :class:`AutoUpdatingCache` (autoupdating.go:20-137): two
keyspaces ``policies/<ns>/<name>`` and ``metrics/<metric>``, a refcount map
so a metric shared by several policies is only evicted when the last one is
deleted, and ``periodic_update`` re-fetching every registered metric each
sync period.  Mutation listeners let the device-tensor mirror
(models/state.py) track changes without polling.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from platform_aware_scheduling_tpu.tas.metrics import Client, NodeMetricsInfo
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicy
from platform_aware_scheduling_tpu.utils import klog, trace
from platform_aware_scheduling_tpu.utils.tracing import CounterSet

POLICY_PATH = "policies/{}/{}"
METRIC_PATH = "metrics/{}"


class CacheMissError(KeyError):
    pass


def _refresh_error_reason(exc: BaseException) -> str:
    """Bounded ``reason`` label for pas_telemetry_refresh_errors_total:
    circuit_open / throttled / server_error / network / no_data /
    fetch_error — never a raw message (unbounded label values are a
    cardinality leak).  Walks the ``__cause__`` chain first: the
    production metrics client (tas/metrics.CustomMetricsClient) wraps
    every failure in a bare MetricsError whose CAUSE carries the real
    KubeError/CircuitOpenError — classifying only the wrapper would
    collapse the whole taxonomy to fetch_error."""
    seen = 0
    while exc.__cause__ is not None and seen < 8:
        exc = exc.__cause__
        seen += 1
    # local import: kube.retry pulls in kube.client; keep the cache
    # importable in metric-only unit tests that stub the kube layer
    try:
        from platform_aware_scheduling_tpu.kube.retry import CircuitOpenError

        if isinstance(exc, CircuitOpenError):
            return "circuit_open"
    except Exception:
        pass
    status = getattr(exc, "status", None)
    if isinstance(status, int) and status:
        if status == 429:
            return "throttled"
        if status >= 500:
            return "server_error"
        return "fetch_error"
    if isinstance(exc, (TimeoutError, OSError)):
        return "network"
    if "no metric" in str(exc) or "no metrics returned" in str(exc):
        return "no_data"
    return "fetch_error"


class _SerializedStore:
    """Serialized KV with the reference's write-nil-preserves rule."""

    def __init__(self):
        self._data: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def add(self, key: str, payload: Any) -> None:
        with self._lock:
            if payload is None and key in self._data:
                return  # nil write preserves existing value (cache.go:52-57)
            self._data[key] = payload

    def read(self, key: str) -> Any:
        with self._lock:
            return self._data.get(key)

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)


class AutoUpdatingCache:
    """Reader/Writer/SelfUpdating cache (reference pkg/cache/types.go)."""

    def __init__(
        self,
        counters: Optional[CounterSet] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._store = _SerializedStore()
        self._metric_refcounts: Dict[str, int] = {}
        self._mtx = threading.Lock()
        # injectable monotonic clock: freshness/aging decisions gate real
        # evictions (docs/robustness.md), so the chaos tests drive them
        # from a fake clock instead of sleeping
        self._clock = clock
        # telemetry-freshness bookkeeping (docs/observability.md): when
        # each metric last carried data, when the last refresh pass
        # completed, and the configured refresh period — the inputs to
        # the /readyz "telemetry_fresh" condition and the
        # pas_telemetry_* metric families
        self.counters = counters if counters is not None else trace.COUNTERS
        self._last_refresh: Dict[str, float] = {}  # metric -> monotonic
        self._last_pass: Optional[float] = None
        self._refresh_period: Optional[float] = None
        self._synced_once = threading.Event()
        #: freshness bound override (seconds); None = 3x the refresh period
        self.freshness_max_age_s: Optional[float] = None
        # held across store mutation + hook delivery so mirror subscribers
        # observe mutations in store order (the reference gets this from its
        # single cache goroutine, cache.go:43-63)
        self._mutation_lock = threading.RLock()
        # mirror hooks: fired after a successful mutation
        self.on_metric_write: List[Callable[[str, Optional[NodeMetricsInfo]], None]] = []
        self.on_metric_delete: List[Callable[[str], None]] = []
        self.on_policy_write: List[Callable[[str, str, TASPolicy], None]] = []
        self.on_policy_delete: List[Callable[[str, str], None]] = []
        # fired once at the END of each update_all_metrics pass (after
        # every per-metric write of the pass landed) — the forecast
        # subsystem refits here, once per pass instead of once per metric
        self.on_refresh_pass: List[Callable[[], None]] = []
        # optional fetched-map transform applied between the metrics API
        # fetch and write_metric: the shard plane's ~1/P ingest cut drops
        # non-owned nodes here (shard/plane.py).  None (the default) is a
        # straight passthrough — full-world mode unchanged.
        self.refresh_filter: Optional[Callable] = None
        # refresh-history substrate (docs/forecast.md): a bounded ring of
        # the last W data-bearing refreshes per metric — (monotonic stamp,
        # {node: milli int}) samples.  A FAILED refresh appends nothing,
        # so gaps stay visible through the stamps; delete_metric drops the
        # ring with the metric.  Off (window 0) until configure_history.
        self._history_window = 0
        self._history: Dict[str, deque] = {}
        self._history_generation = 0

    # -- Reader ---------------------------------------------------------------

    def read_metric(self, metric_name: str) -> NodeMetricsInfo:
        value = self._store.read(METRIC_PATH.format(metric_name))
        if isinstance(value, dict) and value:
            return value
        raise CacheMissError(f"no metric {metric_name} found")

    def read_policy(self, namespace: str, policy_name: str) -> TASPolicy:
        value = self._store.read(POLICY_PATH.format(namespace, policy_name))
        if isinstance(value, TASPolicy):
            return value
        raise CacheMissError(f"no policy {policy_name} found")

    # -- Writer ---------------------------------------------------------------

    def write_policy(self, namespace: str, policy_name: str, policy: TASPolicy) -> None:
        with self._mutation_lock:
            self._store.add(POLICY_PATH.format(namespace, policy_name), policy)
            for hook in self.on_policy_write:
                hook(namespace, policy_name, policy)

    def write_metric(
        self, metric_name: str, data: Optional[NodeMetricsInfo] = None
    ) -> None:
        """Empty/None data registers the metric (incrementing its refcount)
        without clobbering current values (autoupdating.go:105-122)."""
        payload = data if data else None
        with self._mutation_lock:
            self._store.add(METRIC_PATH.format(metric_name), payload)
            if payload is None:
                with self._mtx:
                    self._metric_refcounts[metric_name] = (
                        self._metric_refcounts.get(metric_name, 0) + 1
                    )
            else:
                # a data-bearing write IS a refresh — the freshness clock
                # this metric is judged by (telemetry_freshness)
                stamp = self._clock()
                # the history sample (one milli conversion per node) is
                # built OUTSIDE the lock — at 10k nodes that work must
                # not block request-path readers of metric_ages()/
                # history_snapshot().  The bare int read of the window
                # is racy only against configure_history; the locked
                # re-check below decides
                sample = None
                if self._history_window:
                    sample = {
                        node: metric.value.milli_value_exact()[0]
                        for node, metric in payload.items()
                    }
                with self._mtx:
                    self._last_refresh[metric_name] = stamp
                    if self._history_window and sample is not None:
                        ring = self._history.get(metric_name)
                        if ring is None:
                            ring = deque(maxlen=self._history_window)
                            self._history[metric_name] = ring
                        ring.append((stamp, sample))
                        self._history_generation += 1
            for hook in self.on_metric_write:
                hook(metric_name, payload)

    def delete_policy(self, namespace: str, policy_name: str) -> None:
        klog.v(2).info_s(
            "deleting " + POLICY_PATH.format(namespace, policy_name),
            component="controller",
        )
        with self._mutation_lock:
            self._store.delete(POLICY_PATH.format(namespace, policy_name))
            for hook in self.on_policy_delete:
                hook(namespace, policy_name)

    def delete_metric(self, metric_name: str) -> None:
        """Refcounted delete: evicted only when the last registered policy
        using it is removed (autoupdating.go:124-137)."""
        with self._mutation_lock:
            evicted = False
            with self._mtx:
                total = self._metric_refcounts.get(metric_name)
                if total == 1:
                    del self._metric_refcounts[metric_name]
                    self._store.delete(METRIC_PATH.format(metric_name))
                    self._last_refresh.pop(metric_name, None)
                    # the history ring dies with the metric: a later
                    # re-registration must not forecast from a ghost
                    # series (docs/forecast.md)
                    if self._history.pop(metric_name, None) is not None:
                        self._history_generation += 1
                    evicted = True
                elif total is not None:
                    self._metric_refcounts[metric_name] = total - 1
                else:
                    self._metric_refcounts[metric_name] = -1
            if evicted:
                # the age gauge must not stay frozen in /metrics for a
                # metric that no longer exists
                self.counters.remove(
                    "pas_telemetry_metric_age_seconds",
                    labels={"metric": metric_name},
                    kind="gauge",
                )
                for hook in self.on_metric_delete:
                    hook(metric_name)

    # -- SelfUpdating -----------------------------------------------------------

    def registered_metric_names(self) -> List[str]:
        with self._mtx:
            return [name for name in self._metric_refcounts if name]

    def update_all_metrics(self, client: Client) -> None:
        with self._mtx:
            names = list(self._metric_refcounts)
        errors: Dict[str, int] = {}  # reason -> count
        for name in names:
            if not name:
                with self._mtx:
                    self._metric_refcounts.pop(name, None)
                continue
            try:
                self._update_metric(client, name)
            except Exception as exc:
                # a failed refresh preserves the prior NodeMetricsInfo
                # (the store's write-nil rule — last-known-good) while
                # the metric keeps AGING (_last_refresh untouched), so
                # freshness decay stays visible
                reason = _refresh_error_reason(exc)
                errors[reason] = errors.get(reason, 0) + 1
                klog.v(2).info_s(str(exc), component="controller")
        # pass accounting: refresh counters + per-metric age gauges (a
        # metric whose fetch keeps failing shows a GROWING age while the
        # loop itself keeps ticking — the two failure modes separate)
        now = self._clock()
        with self._mtx:
            self._last_pass = now
            ages = {
                name: now - stamp
                for name, stamp in self._last_refresh.items()
                if name in self._metric_refcounts
            }
        self._synced_once.set()
        self.counters.inc("pas_telemetry_refresh_total")
        for reason, count in errors.items():
            self.counters.inc(
                "pas_telemetry_refresh_errors_total",
                count,
                labels={"reason": reason},
            )
        for name, age in ages.items():
            self.counters.set_gauge(
                "pas_telemetry_metric_age_seconds",
                round(age, 6),
                labels={"metric": name},
            )
        # one end-of-pass notification (never per metric): the forecast
        # subsystem refits against the pass's complete sample set here,
        # in the refresh thread — requests only ever read a finished fit
        for hook in list(self.on_refresh_pass):
            try:
                hook()
            except Exception as exc:  # a subscriber must not stop refreshes
                klog.error("refresh-pass subscriber failed: %r", exc)

    # -- refresh history (docs/forecast.md) -------------------------------------

    def configure_history(self, window: int) -> None:
        """Enable (or re-bound) the per-metric refresh-history rings:
        each data-bearing write appends one ``(stamp, {node: milli})``
        sample, bounded at the last ``window`` samples.  Failed refreshes
        append nothing — the gap shows up as stamp spacing, never as a
        fabricated sample."""
        window = int(window)
        if window < 1:
            raise ValueError(f"history window must be >= 1, got {window}")
        with self._mtx:
            if window != self._history_window:
                self._history = {
                    name: deque(ring, maxlen=window)
                    for name, ring in self._history.items()
                }
                self._history_window = window
                self._history_generation += 1

    def history_window(self) -> int:
        with self._mtx:
            return self._history_window

    def history_generation(self) -> int:
        """Monotonic counter bumped on every history mutation — the
        forecaster's memoization key (tas/forecast engine refits only
        when this moves)."""
        with self._mtx:
            return self._history_generation

    def history_snapshot(
        self,
    ) -> Tuple[int, Dict[str, List[Tuple[float, Dict[str, int]]]]]:
        """(generation, {metric: [(stamp, {node: milli}), ...]}) oldest
        first.  Sample dicts are shared read-only — consumers must not
        mutate them."""
        with self._mtx:
            return self._history_generation, {
                name: list(ring) for name, ring in self._history.items()
            }

    def metric_ages(self) -> Dict[str, Optional[float]]:
        """Registered metric -> seconds since its last data-bearing write
        (None = never refreshed)."""
        now = self._clock()
        with self._mtx:
            return {
                name: (
                    now - self._last_refresh[name]
                    if name in self._last_refresh
                    else None
                )
                for name in self._metric_refcounts
                if name
            }

    def telemetry_freshness(self) -> Tuple[bool, str]:
        """The /readyz "telemetry_fresh" condition (utils/health.py):
        ok when the cache has no refresh loop configured (static seed —
        as fresh as it gets), or when at least one refresh pass has
        completed, the loop's last pass is recent, and every registered
        metric's age is within bound (``freshness_max_age_s``, default
        3x the refresh period)."""
        period = self._refresh_period
        if period is None:
            return True, "static cache (no refresh loop configured)"
        if not self._synced_once.is_set():
            return False, "telemetry cache has not completed a refresh pass"
        bound = self.freshness_bound()
        now = self._clock()
        with self._mtx:
            last_pass = self._last_pass
            stale = sorted(
                name
                for name in self._metric_refcounts
                if name
                and (
                    name not in self._last_refresh
                    or now - self._last_refresh[name] > bound
                )
            )
            registered = sum(1 for name in self._metric_refcounts if name)
        if last_pass is None or now - last_pass > bound:
            since = "never" if last_pass is None else f"{now - last_pass:.1f}s"
            return False, (
                f"refresh loop stalled (last pass {since} ago, bound "
                f"{bound:.1f}s)"
            )
        if stale:
            return False, (
                f"metrics stale past {bound:.1f}s: {stale[:5]}"
            )
        return True, f"{registered} metrics fresh within {bound:.1f}s"

    def freshness_bound(self) -> Optional[float]:
        """The staleness bound in seconds (``freshness_max_age_s`` or 3x
        the refresh period); None for a static cache.  Degraded-mode
        consumers derive their last-known-good window from this
        (tas/degraded.py)."""
        period = self._refresh_period
        if period is None:
            return None
        if self.freshness_max_age_s is not None:
            return self.freshness_max_age_s
        return max(3.0 * period, 1.0)

    def _update_metric(self, client: Client, metric_name: str) -> None:
        info = client.get_node_metric(metric_name)
        if self.refresh_filter is not None and info:
            info = self.refresh_filter(info)
        self.write_metric(metric_name, info)

    def periodic_update(
        self,
        period_seconds: float,
        client: Client,
        initial_data: Optional[Dict[str, Any]] = None,
        stop: Optional[threading.Event] = None,
    ) -> None:
        """Refresh every registered metric each period until ``stop`` is set
        (autoupdating.go:37-43: update first, then wait the tick)."""
        for key, value in (initial_data or {}).items():
            self._store.add(key, value)
        self._refresh_period = period_seconds
        stop = stop or threading.Event()
        while not stop.is_set():
            self.update_all_metrics(client)
            stop.wait(period_seconds)

    def start_periodic_update(
        self,
        period_seconds: float,
        client: Client,
        initial_data: Optional[Dict[str, Any]] = None,
        stop: Optional[threading.Event] = None,
    ) -> threading.Event:
        """Run :meth:`periodic_update` on a daemon thread; returns the stop
        event (caller-supplied ``stop`` is used when given)."""
        self._refresh_period = period_seconds
        stop = stop or threading.Event()
        thread = threading.Thread(
            target=self.periodic_update,
            args=(period_seconds, client, initial_data, stop),
            daemon=True,
        )
        thread.start()
        return stop

#!/usr/bin/env bash
# Point the stock kube-scheduler at the extender(s): drops the
# KubeSchedulerConfiguration onto the control-plane host and patches the
# static-pod manifest to mount + use it.
# (capability parity: reference deploy/extender-configuration/configure-scheduler.sh)
#
# Requirements: run ON a control-plane host with sudo, python3, and
# kubectl available (a kubeadm-managed cluster).  For kind clusters use
# kubeadmConfigPatches at creation instead — the kindest node image has
# neither sudo nor python3 (.github/scripts/e2e_setup_cluster.sh shows
# the pattern).
set -euo pipefail

CONFIG=${1:-scheduler-config.yaml}
DEST=/etc/kubernetes/scheduler-extender-config.yaml
MANIFEST=/etc/kubernetes/manifests/kube-scheduler.yaml

if [[ ! -f "$CONFIG" ]]; then
  echo "config $CONFIG not found" >&2
  exit 1
fi

# detect the served KubeSchedulerConfiguration version
VERSION=$(kubectl version -o json 2>/dev/null |
  python3 -c 'import json,sys; v=json.load(sys.stdin)["serverVersion"]; print("v1" if (int(v["major"]),int(v["minor"].rstrip("+")))>=(1,25) else "v1beta3")' \
  || echo v1)
sed "s|kubescheduler.config.k8s.io/v1|kubescheduler.config.k8s.io/${VERSION}|" \
  "$CONFIG" | sudo tee "$DEST" >/dev/null

# mount the config into the scheduler static pod and pass --config
sudo python3 - "$MANIFEST" "$DEST" <<'EOF'
import sys, yaml
manifest_path, config_path = sys.argv[1], sys.argv[2]
with open(manifest_path) as f:
    pod = yaml.safe_load(f)
spec = pod["spec"]
container = spec["containers"][0]
flag = f"--config={config_path}"
if flag not in container["command"]:
    container["command"] = [
        c for c in container["command"] if not c.startswith("--config=")
    ] + [flag]
mounts = container.setdefault("volumeMounts", [])
if not any(m.get("name") == "extender-config" for m in mounts):
    mounts.append({"name": "extender-config", "mountPath": config_path,
                   "readOnly": True})
volumes = spec.setdefault("volumes", [])
if not any(v.get("name") == "extender-config" for v in volumes):
    volumes.append({"name": "extender-config",
                    "hostPath": {"path": config_path, "type": "File"}})
with open(manifest_path, "w") as f:
    yaml.safe_dump(pod, f)
print("kube-scheduler manifest updated; kubelet will restart it")
EOF

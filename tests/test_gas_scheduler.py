"""GAS extender tests: filter fit-checks, bind booking/rollback, cache
ingestion/replay, device-vs-host binpack equivalence."""

import json
import time

import numpy as np
import pytest

from platform_aware_scheduling_tpu.extender.server import HTTPRequest
from platform_aware_scheduling_tpu.gas.cache import Cache, get_key
from platform_aware_scheduling_tpu.gas.resource_map import ResourceMap
from platform_aware_scheduling_tpu.gas.scheduler import (
    GASExtender,
    check_resource_capacity,
    get_node_gpu_list,
    get_per_gpu_resource_capacity,
    get_per_gpu_resource_request,
)
from platform_aware_scheduling_tpu.gas.utils import (
    CARD_ANNOTATION,
    container_requests,
    has_gpu_resources,
    is_completed_pod,
)
from platform_aware_scheduling_tpu.testing.builders import make_node, make_pod
from platform_aware_scheduling_tpu.testing.fake_kube import FakeKubeClient


def post(obj) -> HTTPRequest:
    return HTTPRequest(
        method="POST",
        path="/scheduler/filter",
        headers={"Content-Type": "application/json"},
        body=json.dumps(obj).encode(),
    )


def gpu_node(name, cards=2, i915=2, millicores=2000, memory=4000):
    return make_node(
        name,
        labels={"gpu.intel.com/cards": ".".join(f"card{i}" for i in range(cards))},
        allocatable={
            "gpu.intel.com/i915": str(i915),
            "gpu.intel.com/millicores": str(millicores),
            "gpu.intel.com/memory.max": str(memory),
        },
    )


def gpu_pod(name, i915="1", millicores="500", node_name="", annotations=None,
            phase="Pending", containers=1):
    reqs = [{
        "gpu.intel.com/i915": i915,
        "gpu.intel.com/millicores": millicores,
    }] * containers
    return make_pod(
        name,
        container_requests=reqs,
        node_name=node_name,
        annotations=annotations,
        phase=phase,
    )


def wait_until(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture(params=["host", "staged", "mirror"])
def setup(request):
    kube = FakeKubeClient()
    cache = Cache(kube, start=False)
    ext = GASExtender(
        kube,
        cache=cache,
        use_device=request.param != "host",
        use_mirror=request.param == "mirror",
    )
    yield kube, cache, ext
    cache.stop()


def start(cache):
    cache.start()


class TestUtils:
    def test_container_requests_prefix_only(self):
        pod = make_pod("p", container_requests=[
            {"cpu": "2", "gpu.intel.com/i915": "1", "gpu.intel.com/millicores": "100"}
        ])
        reqs = container_requests(pod)
        assert reqs == [{"gpu.intel.com/i915": 1, "gpu.intel.com/millicores": 100}]

    def test_fractional_quantity_reads_zero(self):
        # AsInt64 of a fractional quantity: value 0 (reference ignores ok)
        pod = make_pod("p", container_requests=[{"gpu.intel.com/tiles": "500m"}])
        assert container_requests(pod) == [{"gpu.intel.com/tiles": 0}]

    def test_has_gpu_resources(self):
        assert has_gpu_resources(gpu_pod("p"))
        assert not has_gpu_resources(make_pod("p", container_requests=[{"cpu": "1"}]))
        assert not has_gpu_resources(None)

    def test_is_completed_pod(self):
        assert is_completed_pod(make_pod("p", phase="Succeeded"))
        assert is_completed_pod(make_pod("p", phase="Failed"))
        assert not is_completed_pod(make_pod("p", phase="Running"))
        pod = make_pod("p", phase="Running")
        pod.metadata["deletionTimestamp"] = "2026-01-01T00:00:00Z"
        assert is_completed_pod(pod)


class TestHelpers:
    def test_gpu_list_and_capacity(self):
        node = gpu_node("n1", cards=2, i915=2, millicores=2000)
        assert get_node_gpu_list(node) == ["card0", "card1"]
        per_gpu = get_per_gpu_resource_capacity(node, 2)
        assert per_gpu["gpu.intel.com/i915"] == 1
        assert per_gpu["gpu.intel.com/millicores"] == 1000

    def test_no_label_gives_empty(self):
        assert get_node_gpu_list(make_node("n")) == []

    def test_per_gpu_request_division(self):
        rm = ResourceMap({"gpu.intel.com/i915": 2, "gpu.intel.com/millicores": 900})
        per_gpu, k = get_per_gpu_resource_request(rm)
        assert k == 2
        assert per_gpu["gpu.intel.com/millicores"] == 450
        assert per_gpu["gpu.intel.com/i915"] == 1

    def test_check_resource_capacity(self):
        cap = ResourceMap(a=10)
        assert check_resource_capacity(ResourceMap(a=5), cap, ResourceMap(a=5))
        assert not check_resource_capacity(ResourceMap(a=6), cap, ResourceMap(a=5))
        assert not check_resource_capacity(ResourceMap(b=0), cap, ResourceMap())
        assert not check_resource_capacity(ResourceMap(a=0), ResourceMap(a=0),
                                           ResourceMap())


class TestFilter:
    def test_fit_and_reject(self, setup):
        kube, cache, ext = setup
        kube.add_node(gpu_node("empty-node"))
        kube.add_node(gpu_node("small-node", cards=1, i915=1, millicores=100))
        start(cache)
        resp = ext.filter(post({
            "Pod": gpu_pod("p", millicores="500").raw,
            "NodeNames": ["empty-node", "small-node"],
        }))
        assert resp.status == 200
        out = json.loads(resp.body)
        assert out["NodeNames"] == ["empty-node"]
        assert out["FailedNodes"] == {
            "small-node": "gas: no card fits request "
            "(gpu.intel.com/i915=1, gpu.intel.com/millicores=500)"
        }

    def test_missing_node_names_is_error_404(self, setup):
        _, cache, ext = setup
        start(cache)
        resp = ext.filter(post({"Pod": gpu_pod("p").raw, "Nodes": {"items": []}}))
        assert resp.status == 404
        assert "NodeCacheCapable" in json.loads(resp.body)["Error"]

    def test_unknown_node_fails(self, setup):
        _, cache, ext = setup
        start(cache)
        resp = ext.filter(post({
            "Pod": gpu_pod("p").raw, "NodeNames": ["ghost"],
        }))
        out = json.loads(resp.body)
        assert out["NodeNames"] is None or out["NodeNames"] == []
        assert "ghost" in out["FailedNodes"]

    def test_used_resources_counted(self, setup):
        kube, cache, ext = setup
        kube.add_node(gpu_node("n1", cards=1, i915=2, millicores=1000))
        start(cache)
        # book 800 of 1000 millicores on the single card
        booked = gpu_pod("booked", millicores="800", node_name="n1")
        cache.adjust_pod_resources_locked(booked, True, "card0", "n1")
        resp = ext.filter(post({
            "Pod": gpu_pod("p", millicores="300").raw, "NodeNames": ["n1"],
        }))
        out = json.loads(resp.body)
        assert out["FailedNodes"] == {
            "n1": "gas: no card fits request "
            "(gpu.intel.com/i915=1, gpu.intel.com/millicores=300)"
        }
        resp = ext.filter(post({
            "Pod": gpu_pod("p2", millicores="200").raw, "NodeNames": ["n1"],
        }))
        assert json.loads(resp.body)["NodeNames"] == ["n1"]

    def test_multi_gpu_spread(self, setup):
        kube, cache, ext = setup
        # 2 cards, 1000 each; i915=2 request of 1600 -> 800 per card: fits
        kube.add_node(gpu_node("n1", cards=2, i915=2, millicores=2000))
        start(cache)
        resp = ext.filter(post({
            "Pod": gpu_pod("p", i915="2", millicores="1600").raw,
            "NodeNames": ["n1"],
        }))
        assert json.loads(resp.body)["NodeNames"] == ["n1"]

    def test_prioritize_404(self, setup):
        _, cache, ext = setup
        resp = ext.prioritize(post({}))
        assert resp.status == 404


class TestBind:
    def test_bind_annotates_and_books(self, setup):
        kube, cache, ext = setup
        kube.add_node(gpu_node("n1"))
        pod = gpu_pod("p", millicores="500")
        kube.add_pod(pod)
        start(cache)
        resp = ext.bind(post({
            "PodName": "p", "PodNamespace": "default",
            "PodUID": pod.uid, "Node": "n1",
        }))
        assert resp.status == 200
        assert json.loads(resp.body) == {"Error": ""}
        bound = kube.get_pod("default", "p")
        assert bound.get_annotations()[CARD_ANNOTATION] == "card0"
        assert "gas-ts" in bound.get_annotations()
        assert bound.spec_node_name == "n1"
        used = cache.get_node_resource_status("n1")
        assert used["card0"]["gpu.intel.com/millicores"] == 500

    def test_bind_unknown_pod_errors(self, setup):
        _, cache, ext = setup
        start(cache)
        resp = ext.bind(post({
            "PodName": "ghost", "PodNamespace": "default",
            "PodUID": "u", "Node": "n1",
        }))
        assert resp.status == 404
        assert json.loads(resp.body)["Error"] != ""

    def test_bind_wont_fit_rolls_back(self, setup):
        kube, cache, ext = setup
        kube.add_node(gpu_node("n1", cards=1, i915=1, millicores=100))
        pod = gpu_pod("p", millicores="500")
        kube.add_pod(pod)
        start(cache)
        resp = ext.bind(post({
            "PodName": "p", "PodNamespace": "default",
            "PodUID": pod.uid, "Node": "n1",
        }))
        assert resp.status == 404
        assert cache.get_node_resource_status("n1") == {}
        assert get_key(pod) not in cache.annotated_pods


class TestCacheIngestion:
    def test_annotated_pod_replayed_on_start(self):
        """Restart reconstruction: informer ADD events replay annotated pods
        (SURVEY §3.7 / §5.4)."""
        kube = FakeKubeClient()
        kube.add_node(gpu_node("n1"))
        kube.add_pod(gpu_pod("p", millicores="600", node_name="n1",
                             annotations={CARD_ANNOTATION: "card0"}))
        cache = Cache(kube, start=False)
        cache.start()
        try:
            assert wait_until(
                lambda: cache.get_node_resource_status("n1")
                .get("card0", {})
                .get("gpu.intel.com/millicores") == 600
            )
        finally:
            cache.stop()

    def test_completed_pod_releases_resources(self):
        kube = FakeKubeClient()
        kube.add_node(gpu_node("n1"))
        pod = gpu_pod("p", millicores="600", node_name="n1",
                      annotations={CARD_ANNOTATION: "card0"})
        kube.add_pod(pod)
        cache = Cache(kube, start=False)
        cache.start()
        try:
            assert wait_until(
                lambda: get_key(pod) in cache.annotated_pods
            )
            done = gpu_pod("p", millicores="600", node_name="n1",
                           annotations={CARD_ANNOTATION: "card0"},
                           phase="Succeeded")
            done.metadata["uid"] = pod.uid
            done.metadata["resourceVersion"] = "99"
            kube.update_pod(done)
            assert wait_until(
                lambda: get_key(pod) not in cache.annotated_pods
            )
            used = cache.get_node_resource_status("n1")
            assert used["card0"]["gpu.intel.com/millicores"] == 0
        finally:
            cache.stop()

    def test_deleted_pod_releases_resources(self):
        kube = FakeKubeClient()
        kube.add_node(gpu_node("n1"))
        pod = gpu_pod("p", millicores="600", node_name="n1",
                      annotations={CARD_ANNOTATION: "card0"})
        kube.add_pod(pod)
        cache = Cache(kube, start=False)
        cache.start()
        try:
            assert wait_until(lambda: get_key(pod) in cache.annotated_pods)
            kube.delete_pod("default", "p")
            assert wait_until(lambda: get_key(pod) not in cache.annotated_pods)
            used = cache.get_node_resource_status("n1")
            assert used["card0"]["gpu.intel.com/millicores"] == 0
        finally:
            cache.stop()


class TestDeviceHostEquivalence:
    """Randomized cluster state: the batched kernel's verdicts must match
    the host first-fit on every node."""

    def test_random_fit_equivalence(self):
        rng = np.random.default_rng(7)
        kube = FakeKubeClient()
        names = []
        for i in range(24):
            name = f"n{i}"
            names.append(name)
            kube.add_node(gpu_node(
                name,
                cards=int(rng.integers(1, 5)),
                i915=int(rng.integers(1, 9)),
                millicores=int(rng.integers(100, 4000)),
                memory=int(rng.integers(100, 8000)),
            ))
        cache = Cache(kube, start=False)
        ext_host = GASExtender(kube, cache=cache, use_device=False)
        ext_dev = GASExtender(kube, cache=cache, use_device=True,
                              use_mirror=False)
        ext_mir = GASExtender(kube, cache=cache, use_device=True,
                              use_mirror=True)
        cache.start()
        try:
            # seed random bookings
            for i in range(10):
                node = f"n{int(rng.integers(0, 24))}"
                pod = gpu_pod(f"seed{i}",
                              millicores=str(int(rng.integers(0, 1500))),
                              node_name=node)
                card = f"card{int(rng.integers(0, 4))}"
                try:
                    cache.adjust_pod_resources_locked(pod, True, card, node)
                except Exception:
                    pass
            for trial in range(8):
                pod = gpu_pod(
                    f"trial{trial}",
                    i915=str(int(rng.integers(1, 4))),
                    millicores=str(int(rng.integers(0, 3000))),
                    containers=int(rng.integers(1, 3)),
                )
                req = post({"Pod": pod.raw, "NodeNames": names})
                host_out = json.loads(ext_host.filter(req).body)
                dev_out = json.loads(ext_dev.filter(req).body)
                mir_out = json.loads(ext_mir.filter(req).body)
                assert host_out == dev_out, f"trial {trial} staged diverged"
                assert host_out == mir_out, f"trial {trial} mirror diverged"
        finally:
            cache.stop()


class TestUsageMirrorSync:
    """The persistent mirror must track node events and bookings live."""

    def _filter_names(self, ext, names, millicores="500"):
        req = post({"Pod": gpu_pod("probe", millicores=millicores).raw,
                    "NodeNames": names})
        return json.loads(ext.filter(req).body)

    def test_node_update_changes_verdict(self):
        kube = FakeKubeClient()
        kube.add_node(gpu_node("n1", cards=1, i915=1, millicores=100))
        cache = Cache(kube, start=False)
        ext = GASExtender(kube, cache=cache, use_device=True, use_mirror=True)
        cache.start()
        try:
            out = self._filter_names(ext, ["n1"])
            assert "n1" in out["FailedNodes"]
            # capacity grows: update the node object
            bigger = gpu_node("n1", cards=1, i915=2, millicores=2000)
            bigger.metadata["resourceVersion"] = "7"
            kube.add_node(bigger)
            assert wait_until(
                lambda: self._filter_names(ext, ["n1"])["NodeNames"] == ["n1"]
            )
        finally:
            cache.stop()

    def test_fits_cache_invalidated_by_booking(self):
        """The per-(state version, template) fits cache must never serve
        stale fits: a booking bumps the mirror version, so a repeated
        identical request re-evaluates and sees the node full."""
        kube = FakeKubeClient()
        kube.add_node(gpu_node("n1", cards=1, i915=1, millicores=1000))
        cache = Cache(kube, start=False)
        ext = GASExtender(kube, cache=cache, use_device=True, use_mirror=True)
        cache.start()
        try:
            # two identical requests: second is a cache hit, same verdict
            assert wait_until(
                lambda: self._filter_names(ext, ["n1"], millicores="800")[
                    "NodeNames"
                ] == ["n1"]
            )
            assert self._filter_names(ext, ["n1"], millicores="800")[
                "NodeNames"
            ] == ["n1"]
            packer = ext._device
            assert len(packer._fits_cache) == 1
            # book 800 of 1000 millicores -> the same template no longer fits
            booked = gpu_pod("booked", millicores="800", node_name="n1")
            kube.add_pod(booked)
            cache.adjust_pod_resources_locked(booked, True, "card0", "n1")
            out = self._filter_names(ext, ["n1"], millicores="800")
            assert "n1" in out["FailedNodes"]
        finally:
            cache.stop()

    def test_unknown_request_resource_after_snapshot(self):
        """Interning a never-seen request resource must invalidate the
        memoized snapshot: before the fix the old state (too-small r_pad)
        made stage_request index out of bounds until the next cluster
        event, forcing host fallback on every such request."""
        kube = FakeKubeClient()
        kube.add_node(gpu_node("n1"))
        cache = Cache(kube, start=False)
        ext = GASExtender(kube, cache=cache, use_device=True, use_mirror=True)
        cache.start()
        try:
            # memoize the snapshot at the current version
            assert wait_until(
                lambda: self._filter_names(ext, ["n1"])["NodeNames"] == ["n1"]
            )
            pod = gpu_pod("probe2").raw
            pod["spec"]["containers"][0]["resources"]["requests"][
                "gpu.intel.com/never-seen"
            ] = "1"
            from platform_aware_scheduling_tpu.kube.objects import Pod

            fits = ext._device.batch_fit(Pod(pod), ["n1"])
            # no node carries the resource -> no fit; the point is the
            # device path answered (no IndexError -> host fallback)
            assert fits == [False]
        finally:
            cache.stop()

    def test_fits_cache_distinguishes_templates(self):
        """Different pod templates under one state version get separate
        cache entries with different verdicts."""
        kube = FakeKubeClient()
        kube.add_node(gpu_node("n1", cards=1, i915=1, millicores=1000))
        cache = Cache(kube, start=False)
        ext = GASExtender(kube, cache=cache, use_device=True, use_mirror=True)
        cache.start()
        try:
            assert wait_until(
                lambda: self._filter_names(ext, ["n1"], millicores="500")[
                    "NodeNames"
                ] == ["n1"]
            )
            out = self._filter_names(ext, ["n1"], millicores="5000")
            assert "n1" in out["FailedNodes"]
            assert len(ext._device._fits_cache) == 2
        finally:
            cache.stop()

    def test_node_delete_prefails(self):
        kube = FakeKubeClient()
        kube.add_node(gpu_node("n1"))
        cache = Cache(kube, start=False)
        ext = GASExtender(kube, cache=cache, use_device=True, use_mirror=True)
        cache.start()
        try:
            assert wait_until(
                lambda: self._filter_names(ext, ["n1"])["NodeNames"] == ["n1"]
            )
            kube.delete_node("n1")
            assert wait_until(
                lambda: "n1" in self._filter_names(ext, ["n1"])["FailedNodes"]
            )
        finally:
            cache.stop()

    def test_vanished_card_booking_tracked(self):
        """Usage booked on a card missing from the label: lane interned,
        marked invalid, skipped by first-fit — but label cards still fit."""
        kube = FakeKubeClient()
        kube.add_node(gpu_node("n1", cards=2, i915=4, millicores=2000))
        cache = Cache(kube, start=False)
        ext = GASExtender(kube, cache=cache, use_device=True, use_mirror=True)
        cache.start()
        try:
            ghost = gpu_pod("ghost", millicores="100", node_name="n1")
            cache.adjust_pod_resources_locked(ghost, True, "card9", "n1")
            out = self._filter_names(ext, ["n1"])
            assert out["NodeNames"] == ["n1"]
        finally:
            cache.stop()

"""Multi-chip sharding tests on the virtual 8-device CPU mesh: sharded
kernels must reproduce single-device results exactly, and the GSPMD-jitted
full solve must run under NamedSharding-annotated inputs."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from platform_aware_scheduling_tpu.models.batch_scheduler import (
    ClusterState,
    PendingPods,
    example_inputs,
    scheduling_step,
)
from platform_aware_scheduling_tpu.ops import i64
from platform_aware_scheduling_tpu.ops.assign import greedy_assign_kernel
from platform_aware_scheduling_tpu.ops.rules import (
    OP_GREATER_THAN,
    OP_LESS_THAN,
    RuleSet,
    violated_nodes,
)
from platform_aware_scheduling_tpu.ops.scoring import ordinal_scores
from platform_aware_scheduling_tpu.parallel.mesh import (
    NODE_AXIS,
    POD_AXIS,
    grid_sharded,
    make_mesh,
    node_sharded,
    pad_to_multiple,
    replicated,
)
from platform_aware_scheduling_tpu.parallel.sharded import (
    sharded_greedy_assign,
    sharded_prioritize,
    sharded_violations,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
)


def rand_i64(rng, shape):
    return rng.integers(-(2**62), 2**62, size=shape).astype(np.int64)


def make_metric_state(rng, m=3, n=64):
    values = rand_i64(rng, (m, n))
    present = rng.random((m, n)) > 0.2
    hi, lo = i64.split_int64_np(values)
    return (
        i64.I64(hi=jnp.asarray(hi), lo=jnp.asarray(lo)),
        jnp.asarray(present),
        values,
        present,
    )


def make_rules():
    t_hi, t_lo = i64.split_int64_np(np.array([0, 10, 0, 0], dtype=np.int64))
    return RuleSet(
        metric_row=jnp.asarray(np.array([0, 1, 0, 0], dtype=np.int32)),
        op_id=jnp.asarray(
            np.array([OP_GREATER_THAN, OP_LESS_THAN, 0, 0], dtype=np.int32)
        ),
        target=i64.I64(hi=jnp.asarray(t_hi), lo=jnp.asarray(t_lo)),
        active=jnp.asarray(np.array([True, True, False, False])),
    )


class TestShardedViolations:
    def test_matches_single_device(self):
        rng = np.random.default_rng(0)
        mesh = make_mesh(n_node_shards=8)
        values, present, *_ = make_metric_state(rng)
        rules = make_rules()
        want = np.asarray(violated_nodes(values, present, rules))
        got = np.asarray(sharded_violations(mesh, values, present, rules))
        np.testing.assert_array_equal(got, want)


class TestShardedPrioritize:
    @pytest.mark.parametrize("op", [OP_LESS_THAN, OP_GREATER_THAN, 2])
    def test_matches_single_device(self, op):
        rng = np.random.default_rng(1)
        mesh = make_mesh(n_node_shards=8)
        vals = rand_i64(rng, 64)
        vals[5] = vals[7]  # ties
        valid = rng.random(64) > 0.3
        value = i64.from_int64(vals)
        single = ordinal_scores(value, jnp.asarray(valid), jnp.int32(op))
        scores, valid_out = sharded_prioritize(
            mesh, value, jnp.asarray(valid), jnp.int32(op)
        )
        s_single = np.asarray(single.scores)
        s_shard = np.asarray(scores)
        for i in range(64):
            if valid[i]:
                assert s_shard[i] == s_single[i], i


class TestShardedGreedyAssign:
    def test_matches_single_device(self):
        rng = np.random.default_rng(2)
        mesh = make_mesh(n_node_shards=8)
        p, n = 12, 64
        score_np = rand_i64(rng, (p, n))
        score = i64.from_int64(score_np)
        eligible = jnp.asarray(rng.random((p, n)) > 0.4)
        capacity = jnp.asarray(rng.integers(0, 3, size=n).astype(np.int32))
        want = greedy_assign_kernel(score, eligible, capacity)
        got_assigned, got_cap = sharded_greedy_assign(
            mesh, score, eligible, capacity
        )
        np.testing.assert_array_equal(
            np.asarray(got_assigned), np.asarray(want.node_for_pod)
        )
        np.testing.assert_array_equal(
            np.asarray(got_cap), np.asarray(want.capacity_left)
        )

    def test_capacity_respected(self):
        mesh = make_mesh(n_node_shards=8)
        p, n = 8, 16
        score = i64.from_int64(np.full((p, n), 5, dtype=np.int64))
        eligible = jnp.asarray(np.ones((p, n), dtype=bool))
        capacity = jnp.asarray(np.array([2] + [0] * 15, dtype=np.int32))
        assigned, cap_left = sharded_greedy_assign(mesh, score, eligible, capacity)
        a = np.asarray(assigned)
        assert (a == 0).sum() == 2 and (a == -1).sum() == 6
        assert np.asarray(cap_left)[0] == 0

    @pytest.mark.parametrize("block_size", [4, 7, 32])
    def test_block_boundaries_match_single_device(self, block_size):
        """Block sizes that don't divide the pod count, exceed it, or
        force multi-block replay must all reproduce the sequential
        solve (heavy contention: few hot nodes, tiny capacities)."""
        rng = np.random.default_rng(5)
        mesh = make_mesh(n_node_shards=8)
        p, n = 26, 32
        base = rng.integers(0, 4, size=(p, n)).astype(np.int64)  # many ties
        score = i64.from_int64(base)
        eligible = jnp.asarray(rng.random((p, n)) > 0.2)
        capacity = jnp.asarray(rng.integers(0, 2, size=n).astype(np.int32))
        want = greedy_assign_kernel(score, eligible, capacity)
        got_assigned, got_cap = sharded_greedy_assign(
            mesh, score, eligible, capacity, block_size=block_size
        )
        np.testing.assert_array_equal(
            np.asarray(got_assigned), np.asarray(want.node_for_pod)
        )
        np.testing.assert_array_equal(
            np.asarray(got_cap), np.asarray(want.capacity_left)
        )

    def test_matches_single_device_at_scale(self):
        """VERDICT r3 #2: the chunked form at real scale — 1k pods x 8k
        nodes over 8 shards, ~P/32 collectives instead of P — must equal
        the single-chip solve exactly."""
        from platform_aware_scheduling_tpu.parallel.sharded import (
            greedy_assign_collective_count,
        )

        rng = np.random.default_rng(17)
        mesh = make_mesh(n_node_shards=8)
        p, n = 1024, 8192
        # clustered scores force cross-shard contention on the hot nodes
        base = rng.integers(0, 1000, size=(p, n)).astype(np.int64)
        hot = rng.choice(n, size=64, replace=False)
        base[:, hot] += 10**6
        score = i64.from_int64(base)
        eligible = jnp.asarray(rng.random((p, n)) > 0.3)
        capacity = jnp.asarray(rng.integers(0, 3, size=n).astype(np.int32))
        want = greedy_assign_kernel(score, eligible, capacity)
        got_assigned, got_cap = sharded_greedy_assign(
            mesh, score, eligible, capacity
        )
        np.testing.assert_array_equal(
            np.asarray(got_assigned), np.asarray(want.node_for_pod)
        )
        np.testing.assert_array_equal(
            np.asarray(got_cap), np.asarray(want.capacity_left)
        )
        assert greedy_assign_collective_count(p) == 32  # vs 1024 per-pod


class TestGreedyAssignSingle:
    def test_greedy_semantics(self):
        # pod0 takes the best node, pod1 must settle for second best
        score = i64.from_int64(np.array([[3, 9, 5], [1, 9, 5]], dtype=np.int64))
        eligible = jnp.asarray(np.ones((2, 3), dtype=bool))
        capacity = jnp.asarray(np.array([1, 1, 1], dtype=np.int32))
        out = greedy_assign_kernel(score, eligible, capacity)
        np.testing.assert_array_equal(np.asarray(out.node_for_pod), [1, 2])

    def test_unassignable_pod(self):
        score = i64.from_int64(np.array([[1, 2]], dtype=np.int64))
        eligible = jnp.asarray(np.zeros((1, 2), dtype=bool))
        capacity = jnp.asarray(np.array([1, 1], dtype=np.int32))
        out = greedy_assign_kernel(score, eligible, capacity)
        assert int(out.node_for_pod[0]) == -1

    def test_tie_breaks_to_lowest_index(self):
        score = i64.from_int64(np.array([[7, 7, 7]], dtype=np.int64))
        eligible = jnp.asarray(np.ones((1, 3), dtype=bool))
        capacity = jnp.asarray(np.array([1, 1, 1], dtype=np.int32))
        out = greedy_assign_kernel(score, eligible, capacity)
        assert int(out.node_for_pod[0]) == 0


class TestGSPMDFullSolve:
    """The production multi-chip path: jit + NamedSharding annotations on a
    (pods, nodes) mesh; XLA partitions the whole scheduling_step."""

    @pytest.mark.parametrize("pod_shards,node_shards", [(1, 8), (2, 4)])
    def test_sharded_matches_replicated(self, pod_shards, node_shards):
        state, pods = example_inputs(num_nodes=64, num_pods=16)
        want = scheduling_step(state, pods)
        mesh = make_mesh(n_node_shards=node_shards, n_pod_shards=pod_shards)
        ns = node_sharded(mesh)
        nodes1d = NamedSharding(mesh, P(NODE_AXIS))
        rep = replicated(mesh)
        state_s = ClusterState(
            metric_values=i64.I64(
                hi=jax.device_put(state.metric_values.hi, ns),
                lo=jax.device_put(state.metric_values.lo, ns),
            ),
            metric_present=jax.device_put(state.metric_present, ns),
            dontschedule=jax.tree.map(
                lambda x: jax.device_put(x, rep), state.dontschedule
            ),
            capacity=jax.device_put(state.capacity, nodes1d),
        )
        pods_sharding = NamedSharding(mesh, P(POD_AXIS))
        pods_s = PendingPods(
            metric_row=jax.device_put(pods.metric_row, pods_sharding),
            op_id=jax.device_put(pods.op_id, pods_sharding),
            candidates=jax.device_put(pods.candidates, grid_sharded(mesh)),
        )
        got = scheduling_step(state_s, pods_s)
        np.testing.assert_array_equal(
            np.asarray(got.assignment.node_for_pod),
            np.asarray(want.assignment.node_for_pod),
        )
        np.testing.assert_array_equal(
            np.asarray(got.violating), np.asarray(want.violating)
        )


class TestPadding:
    def test_pad_to_multiple(self):
        arr = np.arange(10).reshape(2, 5)
        out = pad_to_multiple(arr, 1, 8, fill=-1)
        assert out.shape == (2, 8)
        assert (out[:, 5:] == -1).all()
        assert pad_to_multiple(arr, 1, 5).shape == (2, 5)


class TestAuctionAssign:
    """auction_assign_kernel must equal greedy_assign_kernel exactly —
    the fixpoint IS sequential greedy."""

    @pytest.mark.parametrize("seed", range(6))
    def test_random_equivalence(self, seed):
        from platform_aware_scheduling_tpu.ops.assign import (
            auction_assign_kernel,
        )

        rng = np.random.default_rng(seed)
        p, n = int(rng.integers(1, 40)), int(rng.integers(1, 80))
        # heavy ties + contention: few distinct scores, tight capacity
        score_np = rng.integers(-3, 3, size=(p, n)).astype(np.int64) * (
            10 ** int(rng.integers(0, 15))
        )
        score = i64.from_int64(score_np)
        eligible = jnp.asarray(rng.random((p, n)) > 0.3)
        capacity = jnp.asarray(rng.integers(0, 2, size=n).astype(np.int32))
        want = greedy_assign_kernel(score, eligible, capacity)
        got = auction_assign_kernel(score, eligible, capacity)
        np.testing.assert_array_equal(
            np.asarray(got.node_for_pod), np.asarray(want.node_for_pod)
        )
        np.testing.assert_array_equal(
            np.asarray(got.capacity_left), np.asarray(want.capacity_left)
        )

    def test_eviction_chain(self):
        """The case naive conflict-resolution gets wrong: pod1 loses its
        first choice to pod0, must evict pod2 from pod2's first choice."""
        from platform_aware_scheduling_tpu.ops.assign import (
            auction_assign_kernel,
        )

        # pods 0,1 best = node0; pod1 second = node1; pod2 best = node1
        score = i64.from_int64(
            np.array([[9, 1, 0], [9, 5, 1], [0, 9, 1]], dtype=np.int64)
        )
        eligible = jnp.asarray(np.ones((3, 3), dtype=bool))
        capacity = jnp.asarray(np.array([1, 1, 1], dtype=np.int32))
        out = auction_assign_kernel(score, eligible, capacity)
        np.testing.assert_array_equal(np.asarray(out.node_for_pod), [0, 1, 2])


class TestPallasAssign:
    """Pallas greedy-assign (interpret mode on CPU) must equal the XLA scan."""

    @pytest.mark.parametrize("seed", range(4))
    def test_random_equivalence(self, seed):
        from platform_aware_scheduling_tpu.ops.pallas_assign import (
            greedy_assign_pallas,
        )

        rng = np.random.default_rng(seed)
        p, n = int(rng.integers(1, 30)), int(rng.integers(1, 300))
        score_np = rng.integers(-(2**62), 2**62, size=(p, n)).astype(np.int64)
        score = i64.from_int64(score_np)
        eligible = jnp.asarray(rng.random((p, n)) > 0.3)
        capacity = jnp.asarray(rng.integers(0, 3, size=n).astype(np.int32))
        want = greedy_assign_kernel(score, eligible, capacity)
        got = greedy_assign_pallas(score, eligible, capacity, interpret=True)
        np.testing.assert_array_equal(
            np.asarray(got.node_for_pod), np.asarray(want.node_for_pod)
        )
        np.testing.assert_array_equal(
            np.asarray(got.capacity_left), np.asarray(want.capacity_left)
        )

    def test_uint32_bias_edge_values(self):
        from platform_aware_scheduling_tpu.ops.pallas_assign import (
            greedy_assign_pallas,
        )

        # values whose lo limbs straddle the u32 sign bit
        vals = np.array([[2**31, 2**31 - 1, 2**32 - 1, 0, -1, -(2**31)]],
                        dtype=np.int64)
        score = i64.from_int64(vals)
        eligible = jnp.asarray(np.ones((1, 6), dtype=bool))
        capacity = jnp.asarray(np.ones(6, dtype=np.int32))
        want = greedy_assign_kernel(score, eligible, capacity)
        got = greedy_assign_pallas(score, eligible, capacity, interpret=True)
        np.testing.assert_array_equal(
            np.asarray(got.node_for_pod), np.asarray(want.node_for_pod)
        )


class TestRingPrioritize:
    """Ring-pass ranking must equal both the all_gather sharded form and
    the single-device sort."""

    @pytest.mark.parametrize("op", [OP_LESS_THAN, OP_GREATER_THAN, 2])
    def test_matches_all_gather_and_single(self, op):
        from platform_aware_scheduling_tpu.parallel.sharded import (
            sharded_prioritize_ring,
        )

        rng = np.random.default_rng(21)
        mesh = make_mesh(n_node_shards=8)
        vals = rand_i64(rng, 64)
        vals[3] = vals[40]  # cross-shard tie
        valid = rng.random(64) > 0.25
        value = i64.from_int64(vals)
        single = ordinal_scores(value, jnp.asarray(valid), jnp.int32(op))
        gather_scores, _ = sharded_prioritize(
            mesh, value, jnp.asarray(valid), jnp.int32(op)
        )
        ring_scores, ring_valid = sharded_prioritize_ring(
            mesh, value, jnp.asarray(valid), jnp.int32(op)
        )
        s_single = np.asarray(single.scores)
        s_gather = np.asarray(gather_scores)
        s_ring = np.asarray(ring_scores)
        np.testing.assert_array_equal(np.asarray(ring_valid), valid)
        for i in range(64):
            if valid[i]:
                assert s_ring[i] == s_single[i] == s_gather[i], i


class TestSinkhornAssign:
    def _instance(self, seed, p=20, n=30):
        rng = np.random.default_rng(seed)
        score = i64.from_int64(
            rng.integers(0, 10**9, size=(p, n)).astype(np.int64)
        )
        eligible = jnp.asarray(rng.random((p, n)) > 0.2)
        capacity = jnp.asarray(rng.integers(0, 3, size=n).astype(np.int32))
        return score, eligible, capacity

    @pytest.mark.parametrize("seed", range(5))
    def test_feasible_and_deterministic(self, seed):
        from platform_aware_scheduling_tpu.ops.sinkhorn import (
            sinkhorn_assign_kernel,
        )

        score, eligible, capacity = self._instance(seed)
        out1 = sinkhorn_assign_kernel(score, eligible, capacity)
        out2 = sinkhorn_assign_kernel(score, eligible, capacity)
        a = np.asarray(out1.assignment.node_for_pod)
        np.testing.assert_array_equal(
            a, np.asarray(out2.assignment.node_for_pod)
        )
        # capacity never exceeded; only eligible nodes assigned
        cap = np.asarray(capacity)
        elig = np.asarray(eligible)
        counts = np.zeros_like(cap)
        for pod, node in enumerate(a):
            if node >= 0:
                assert elig[pod, node]
                counts[node] += 1
        assert (counts <= cap).all()

    def test_global_coordination_beats_greedy(self):
        """The textbook case greedy loses: pod0 slightly prefers the node
        pod1 NEEDS (pod1 has no alternative)."""
        from platform_aware_scheduling_tpu.ops.sinkhorn import (
            sinkhorn_assign_kernel,
            total_utility,
        )
        from platform_aware_scheduling_tpu.ops.assign import (
            greedy_assign_kernel,
        )

        score = i64.from_int64(
            np.array([[100, 99], [100, 0]], dtype=np.int64)
        )
        eligible = jnp.asarray(np.array([[True, True], [True, False]]))
        capacity = jnp.asarray(np.array([1, 1], dtype=np.int32))
        greedy = greedy_assign_kernel(score, eligible, capacity)
        # greedy: pod0 -> n0, pod1 unassigned
        np.testing.assert_array_equal(
            np.asarray(greedy.node_for_pod), [0, -1]
        )
        sink = sinkhorn_assign_kernel(score, eligible, capacity)
        # coordinated: pod0 -> n1 (99), pod1 -> n0 (100): both placed
        np.testing.assert_array_equal(
            np.asarray(sink.assignment.node_for_pod), [1, 0]
        )

    @pytest.mark.parametrize("seed", [0, 3])
    def test_objective_not_worse_than_greedy(self, seed):
        from platform_aware_scheduling_tpu.ops.sinkhorn import (
            sinkhorn_assign_kernel,
            total_utility,
        )
        from platform_aware_scheduling_tpu.ops.assign import (
            greedy_assign_kernel,
        )

        score, eligible, capacity = self._instance(seed, p=30, n=20)
        greedy = greedy_assign_kernel(score, eligible, capacity)
        sink = sinkhorn_assign_kernel(score, eligible, capacity)
        g_assigned = int((np.asarray(greedy.node_for_pod) >= 0).sum())
        s_assigned = int(
            (np.asarray(sink.assignment.node_for_pod) >= 0).sum()
        )
        # coordination must never place fewer pods
        assert s_assigned >= g_assigned


class TestShardedAuction:
    """The mesh auction fixpoint must equal the single-chip kernel (and
    therefore sequential greedy) EXACTLY — integer keys, deterministic
    tiebreaks, no tolerance."""

    @pytest.mark.parametrize("seed", range(5))
    def test_matches_single_device(self, seed):
        from platform_aware_scheduling_tpu.ops.assign import (
            auction_assign_kernel,
        )
        from platform_aware_scheduling_tpu.parallel.sharded import (
            sharded_auction_assign,
        )

        rng = np.random.default_rng(seed)
        mesh = make_mesh(n_node_shards=8)
        p, n = int(rng.integers(1, 30)), 64
        # heavy ties + contention, scores straddling limb boundaries
        score_np = rng.integers(-3, 3, size=(p, n)).astype(np.int64) * (
            10 ** int(rng.integers(0, 15))
        )
        score = i64.from_int64(score_np)
        eligible = jnp.asarray(rng.random((p, n)) > 0.3)
        capacity = jnp.asarray(rng.integers(0, 2, size=n).astype(np.int32))
        want = auction_assign_kernel(score, eligible, capacity)
        got_choice, got_cap = sharded_auction_assign(
            mesh, score, eligible, capacity
        )
        np.testing.assert_array_equal(
            np.asarray(got_choice), np.asarray(want.node_for_pod)
        )
        np.testing.assert_array_equal(
            np.asarray(got_cap), np.asarray(want.capacity_left)
        )

    def test_eviction_chain_on_mesh(self):
        """The chain case (pod1 loses node0 to pod0, evicts pod2 from
        node1) across shard boundaries — one node per shard."""
        from platform_aware_scheduling_tpu.parallel.sharded import (
            sharded_auction_assign,
        )

        n = 8
        score_np = np.zeros((3, n), dtype=np.int64)
        score_np[0, 0] = 9
        score_np[1, 0], score_np[1, 1], score_np[1, 2] = 9, 5, 1
        score_np[2, 1], score_np[2, 2] = 9, 1
        mesh = make_mesh(n_node_shards=8)
        choice, _ = sharded_auction_assign(
            mesh,
            i64.from_int64(score_np),
            jnp.asarray(np.ones((3, n), dtype=bool)),
            jnp.asarray(
                np.array([1, 1, 1] + [0] * 5, dtype=np.int32)
            ),
        )
        np.testing.assert_array_equal(np.asarray(choice), [0, 1, 2])


class TestShardedSinkhorn:
    """The mesh churn engine (VERDICT r4 #5): feasibility and determinism
    are exact (the rounding is the exact sharded greedy); plan guidance is
    f32 over collectives, so the objective — not the bitwise assignment —
    must match the single-chip kernel."""

    def _instance(self, seed, p=24, n=64):
        rng = np.random.default_rng(seed)
        score = i64.from_int64(
            rng.integers(0, 10**9, size=(p, n)).astype(np.int64)
        )
        eligible = jnp.asarray(rng.random((p, n)) > 0.2)
        capacity = jnp.asarray(rng.integers(0, 3, size=n).astype(np.int32))
        return score, eligible, capacity

    @pytest.mark.parametrize("seed", range(4))
    def test_feasible_deterministic_and_objective_parity(self, seed):
        from platform_aware_scheduling_tpu.ops.sinkhorn import (
            sinkhorn_assign_kernel,
            total_utility,
        )
        from platform_aware_scheduling_tpu.parallel.sharded import (
            sharded_sinkhorn_assign,
        )

        mesh = make_mesh(n_node_shards=8)
        score, eligible, capacity = self._instance(seed)
        assigned, cap_left = sharded_sinkhorn_assign(
            mesh, score, eligible, capacity, iterations=20
        )
        again, _ = sharded_sinkhorn_assign(
            mesh, score, eligible, capacity, iterations=20
        )
        a = np.asarray(assigned)
        np.testing.assert_array_equal(a, np.asarray(again))  # deterministic
        cap = np.asarray(capacity)
        elig = np.asarray(eligible)
        counts = np.zeros_like(cap)
        for pod, node in enumerate(a):
            if node >= 0:
                assert elig[pod, node]
                counts[node] += 1
        assert (counts <= cap).all()
        np.testing.assert_array_equal(np.asarray(cap_left), cap - counts)
        # objective parity with the single-chip kernel (module doc: the
        # plans can differ in last-ulp f32, never materially)
        single = sinkhorn_assign_kernel(score, eligible, capacity,
                                        iterations=20)
        u_mesh = float(total_utility(score, assigned))
        u_single = float(
            total_utility(score, single.assignment.node_for_pod)
        )
        assert u_mesh >= u_single - max(0.02 * abs(u_single), 0.1), (
            u_mesh,
            u_single,
        )

    def test_coordination_case_on_mesh(self):
        """The pod0/pod1 contention case the single-chip kernel solves
        must survive sharding (pads to the 8-shard node axis)."""
        from platform_aware_scheduling_tpu.parallel.sharded import (
            sharded_sinkhorn_assign,
        )

        n = 8  # one node per shard
        score_np = np.zeros((2, n), dtype=np.int64)
        score_np[0, 0], score_np[0, 1] = 100, 99
        score_np[1, 0] = 100
        eligible_np = np.zeros((2, n), dtype=bool)
        eligible_np[0, :2] = True
        eligible_np[1, 0] = True
        mesh = make_mesh(n_node_shards=8)
        # at the defaults: sharded and single-chip share
        # ops.sinkhorn.DEFAULT_ITERATIONS (50 — enough anneal steps for
        # this contention; the old sharded-only default of 20 was not)
        assigned, _ = sharded_sinkhorn_assign(
            mesh,
            i64.from_int64(score_np),
            jnp.asarray(eligible_np),
            jnp.asarray(np.ones(n, dtype=np.int32)),
        )
        np.testing.assert_array_equal(np.asarray(assigned), [1, 0])


class TestMultisliceMesh:
    def test_single_slice_degenerates(self):
        from platform_aware_scheduling_tpu.parallel.mesh import (
            make_multislice_mesh,
        )

        mesh = make_multislice_mesh(n_pod_shards_per_slice=2)
        shape = dict(zip(mesh.axis_names, mesh.devices.shape))
        assert shape[POD_AXIS] == 2
        assert shape[POD_AXIS] * shape[NODE_AXIS] <= len(jax.devices())

"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip shardings compile and
execute without TPU hardware (the driver separately dry-runs the multi-chip
path via ``__graft_entry__.dryrun_multichip``).  The env vars must be set
before JAX is imported anywhere.
"""

import os
import sys

# hard override: the ambient environment may pin JAX to the real
# accelerator (e.g. an axon sitecustomize calling
# jax.config.update("jax_platforms", "axon,cpu"), which beats env vars);
# tests always run on the virtual CPU mesh
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax (< 0.5) has no such option; the XLA_FLAGS override above
    # already forces the 8-device host platform there
    pass

# repo root on sys.path so `import platform_aware_scheduling_tpu` works
# without installation
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip shardings compile and
execute without TPU hardware (the driver separately dry-runs the multi-chip
path via ``__graft_entry__.dryrun_multichip``).  The env vars must be set
before JAX is imported anywhere.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# repo root on sys.path so `import platform_aware_scheduling_tpu` works
# without installation
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
